"""MasterFilesystem: the namespace + block management core.

Parity: curvine-server/src/master/fs/master_filesystem.rs (+ fs/context.rs,
master/meta/fs_dir.rs). All mutations flow through journaled apply-ops so a
restart (or a raft follower) reaches the same state by replay.

Two durability modes, selected by the metadata store:

* ``MemMetaStore`` — namespace in RAM; restart = snapshot + journal replay
  (the reference's journal-only mode).
* ``KvMetaStore`` — namespace in a log-structured KV
  (curvine-server/src/master/meta/store/rocks_inode_store.rs parity):
  every journal entry's effects commit as one atomic KV batch tagged with
  the entry seq, so cold start opens the KV and replays only the journal
  tail past ``applied_seq`` — restart cost is O(tail), not O(namespace),
  and the namespace can exceed RAM."""

from __future__ import annotations

import logging

from curvine_tpu.common import errors as err
from curvine_tpu.common.journal import Journal
from curvine_tpu.common.types import (
    CommitBlock, ExtendedBlock, FileBlocks, FileStatus, FileType, LocatedBlock,
    MasterInfo, SetAttrOpts, StoragePolicy, StorageState, StorageType,
    TtlAction, WorkerInfo, WorkerState, now_ms,
)
from curvine_tpu.master.block_map import BlockMap
from curvine_tpu.master.inode import Inode, InodeTree, ROOT_ID
from curvine_tpu.master.placement import PlacementPolicy, create_policy
from curvine_tpu.master.store import KvMetaStore, MemMetaStore
from curvine_tpu.master.worker_map import WorkerMap

log = logging.getLogger(__name__)

# default storage policy in wire form, hoisted off the create hot path
# (copied per entry — journal args must never share mutable state)
_DEFAULT_POLICY_WIRE = StoragePolicy().to_wire()


class MasterFilesystem:
    def __init__(self, journal: Journal | None = None,
                 placement: str | PlacementPolicy = "local",
                 lost_timeout_ms: int = 30_000,
                 snapshot_interval: int = 100_000,
                 store: MemMetaStore | KvMetaStore | None = None,
                 id_stride: int = 1, id_offset: int = 0,
                 ici_mesh_shape: list[int] | None = None):
        self.store = store if store is not None else MemMetaStore()
        self.tree = InodeTree(self.store, id_stride=id_stride,
                              id_offset=id_offset)
        self.blocks = BlockMap(self.store)
        self.workers = WorkerMap(lost_timeout_ms=lost_timeout_ms)
        self.journal = journal
        self.snapshot_interval = snapshot_interval
        self._entries_since_snapshot = 0
        if isinstance(placement, str):
            placement = create_policy(placement,
                                      mesh_shape=ici_mesh_shape or None)
        self.policy = placement
        # worker_id -> block ids scheduled for deletion (drained by heartbeat)
        self.pending_deletes: dict[int, set[int]] = {}
        self.mounts = None          # set by MountManager
        # inode ids of files open for writing (is_complete=False):
        # lease recovery iterates THIS, not the whole namespace. None
        # until first use after a restart (built by one lazy scan, then
        # maintained incrementally by the journaled applies).
        self.open_files: set[int] | None = None
        self.on_worker_lost = None  # hook: ReplicationManager
        self.on_mutation = None     # hook: RaftLite journal replication
        # active raft membership config, set by journaled raft_conf
        # entries (master/ha.py) and carried through snapshots so a
        # fresh/restarted replica adopts the journaled config, not its
        # possibly-stale boot peers
        self.raft_conf: dict | None = None
        self.acl = None             # set by AclEnforcer (permission checks)
        # runtime mirrors of the durable EC stripe map (store.iter_ec):
        # logical block id -> stripe wire, and the reverse cell index
        # cell block id -> (logical block id, cell index). Kept hot so
        # the get_block_locations path never pays a KV read per block.
        self.ec_stripes: dict[int, dict] = {}
        self.ec_cells: dict[int, tuple[int, int]] = {}
        # GroupCommitter (common/journal.py), installed by MasterServer:
        # when present, _log journals unflushed + stages KV writes; the
        # RPC handler awaits committer.sync() before replying.
        self.committer = None
        self._walk_hint = None          # leader-local walk pass-through
        self.start_ms = now_ms()

    @property
    def _kv(self) -> bool:
        return self.store.kind == "kv"

    # ==================== journal plumbing ====================

    def _rebuild_ec_index(self) -> None:
        self.ec_stripes = {}
        self.ec_cells = {}
        for bid, stripe in self.store.iter_ec():
            self._ec_index(bid, stripe)

    def _ec_index(self, block_id: int, stripe: dict) -> None:
        old = self.ec_stripes.get(block_id)
        if old is not None:
            for cid in old.get("cells", []):
                self.ec_cells.pop(cid, None)
        self.ec_stripes[block_id] = stripe
        for idx, cid in enumerate(stripe.get("cells", [])):
            self.ec_cells[cid] = (block_id, idx)

    def recover(self) -> None:
        if self.journal is None:
            self._rebuild_ec_index()
            return
        snap, entries = self.journal.recover()
        if self._kv:
            applied = self.store.get_counter("applied_seq", 0)
            snap_seq = getattr(self.journal, "last_snapshot_seq", 0)
            if snap is not None and applied < snap_seq:
                # KV is behind the newest snapshot (migration from mem mode
                # or an HA snapshot install mid-crash): load it wholesale.
                self._load_snapshot(snap)
                applied = snap_seq
                self.store.commit_applied(applied)
            replayed = 0
            tail_seq = applied
            for seq, op, args, _term in entries:
                if seq <= applied:
                    continue
                try:
                    self._apply(op, args)
                    self.store.stage_entry()
                except err.CurvineError as e:
                    self.store.rollback()
                    log.warning("journal replay: %s(%s) -> %s", op, args, e)
                tail_seq = seq
                replayed += 1
                # batched replay: one KV write_batch per ~4096 entries
                # makes restart cost track the group-commit write path
                if replayed % 4096 == 0:
                    self.store.commit_applied(tail_seq)
            if tail_seq > applied:
                self.store.commit_applied(tail_seq)
            self.journal.seq = max(self.journal.seq, applied)
            log.info("kv recovery: %d inodes, %d blocks, applied_seq=%d, "
                     "replayed %d tail entries",
                     self.tree.count(), self.blocks.count(),
                     self.store.get_counter("applied_seq"), replayed)
            self._rebuild_ec_index()
            return
        if snap is not None:
            self._load_snapshot(snap)
        for _seq, op, args, _term in entries:
            try:
                self._apply(op, args)
            except err.CurvineError as e:
                log.warning("journal replay: %s(%s) -> %s", op, args, e)
        if snap is not None or entries:
            log.info("recovered namespace: %d inodes, %d blocks, seq=%d",
                     self.tree.count(), self.blocks.count(), self.journal.seq)
        self._rebuild_ec_index()

    audit_log = False   # set from MasterConf.audit_log

    def _log(self, op: str, args: dict):
        # WAL discipline: journal BEFORE apply, so an append failure (disk
        # full) never leaves in-memory state ahead of the durable log.
        # Mutations are validated before journaling; if an apply still
        # fails, on_mutation fires anyway so follower seqs stay contiguous
        # (followers fail the same deterministic way and skip the entry).
        #
        # Group commit: with a committer installed, the journal write is
        # buffered (flush=False) and the entry's KV effects are STAGED,
        # not committed — the committer later syncs the journal and lands
        # the whole group as one KV batch. Durability therefore moves to
        # committer.sync(), which the RPC handler awaits before replying;
        # validate→journal→apply is one synchronous stretch on the actor
        # loop, so applied state is visible to later ops immediately.
        grouped = self.committer is not None and self.committer.accepting
        seq = None
        if self.journal is not None:
            seq = self.journal.append(op, args, flush=not grouped)
        try:
            result = self._apply(op, args)
        except BaseException:
            if self._kv:
                self.store.rollback()
                if seq is not None and not grouped:
                    self.store.commit_applied(seq)
            if grouped:
                self.committer.note()
            if seq is not None and self.on_mutation is not None:
                self.on_mutation(seq, op, args, self.journal.last_term)
            raise
        if self._kv:
            if grouped:
                self.store.stage_entry()
            else:
                self.store.commit_applied(
                    seq if seq is not None
                    else self.store.get_counter("applied_seq", 0))
        if grouped:
            self.committer.note()
        if self.audit_log:
            from curvine_tpu.common.logging import audit
            audit.log(op, str(args.get("path", args.get("src", ""))))
        if seq is not None:
            if self.on_mutation is not None:
                self.on_mutation(seq, op, args, self.journal.last_term)
            self._entries_since_snapshot += 1
            if self._entries_since_snapshot >= self.snapshot_interval:
                self.checkpoint()
        return result

    def apply_replicated(self, seq: int, op: str, args: dict,
                         term: int) -> None:
        self.apply_replicated_batch([(seq, op, args, term)])

    def apply_replicated_batch(
            self, entries: list[tuple[int, str, dict, int]]) -> None:
        """Follower-side apply of a leader-streamed batch: journal the
        WHOLE batch with ONE flush (WAL), then apply in order, then land
        the group's KV effects as one atomic batch under the tail seq —
        the follower-side half of group commit. Per-entry failures are
        deterministic (the leader failed identically): roll back that
        entry's pending writes, keep the rest of the batch. CancelledError
        propagates — a cancelled handler must NOT mark entries applied
        (the journal has the batch; restart replays it)."""
        assert self.journal is not None
        if not entries:
            return
        self.journal.append_batch([(op, args, term)
                                   for _seq, op, args, term in entries])
        try:
            for _seq, op, args, _term in entries:
                try:
                    self._apply(op, args)
                    if self._kv:
                        self.store.stage_entry()
                except Exception as e:
                    if self._kv:
                        self.store.rollback()
                    lvl = (log.warning if isinstance(e, err.CurvineError)
                           else log.error)
                    lvl("follower apply %s failed: %s", op, e)
        except BaseException:
            if self._kv:
                self.store.rollback_group()
            raise
        if self._kv:
            self.store.commit_applied(entries[-1][0])

    def install_snapshot(self, state: dict, seq: int, last_term: int) -> None:
        """Replace the whole state machine (HA catch-up / divergence heal)."""
        self._load_snapshot(state)
        if self._kv:
            self.store.commit_applied(seq)
        if self.journal is not None:
            # stale on-disk entries (possibly from a divergent history)
            # must not survive to be replayed after a restart
            self.journal.reset_log()
            self.journal.seq = seq
            self.journal.last_term = last_term
            self.journal.note_term(seq, last_term)
            self.journal.write_snapshot(state)

    def flush_group(self) -> None:
        """Commit any open journal group inline. Snapshot scans, restarts
        and direct-KV reads must not observe staged-but-unflushed state."""
        if self.committer is not None:
            self.committer.flush_sync()

    def checkpoint(self) -> None:
        if self.journal is None:
            return
        self.flush_group()
        if self._kv:
            # KV mode: the store IS the checkpoint. Flush the memtable and
            # drop journal segments fully covered by applied_seq — no full
            # snapshot write, so checkpoint cost is O(memtable) not O(ns).
            self.store.flush()
            self.journal.gc_covered(self.store.get_counter("applied_seq", 0))
        else:
            self.journal.write_snapshot(self._snapshot_state())
        self._entries_since_snapshot = 0

    def _snapshot_state(self) -> dict:
        """Full-state dump (HA snapshot transfer / mem-mode checkpoints)."""
        self.flush_group()
        ch_map: dict[int, dict[str, int]] = {}
        for pid, name, cid in self.store.iter_children_all():
            ch_map.setdefault(pid, {})[name] = cid
        inodes = []
        for node in self.store.iter_inodes():
            inodes.append({
                "id": node.id, "name": node.name, "ft": int(node.file_type),
                "pid": node.parent_id, "mtime": node.mtime, "atime": node.atime,
                "owner": node.owner, "group": node.group, "mode": node.mode,
                "xattr": node.x_attr, "sp": node.storage_policy.to_wire(),
                "nlink": node.nlink, "len": node.len, "bs": node.block_size,
                "rep": node.replicas, "blocks": node.blocks,
                "done": node.is_complete, "target": node.target,
                "dir": node.is_dir,
                # explicit directory entries: a hard-linked inode has a
                # second (parent, name) pair that (pid, name) alone cannot
                # represent — children must be serialized, not derived.
                "ch": ch_map.get(node.id, {}) if node.is_dir else None,
            })
        blocks = [(bid, length, iid, rep)
                  for bid, (length, iid, rep) in self.store.iter_blocks()]
        state = {"next_id": self.store.get_counter("next_id", ROOT_ID + 1),
                 "next_block_id": self.store.get_counter("next_block_id", 1),
                 "inodes": inodes, "blocks": blocks,
                 "jobs": list(self.store.iter_jobs()),
                 "ec": [[bid, stripe] for bid, stripe in self.store.iter_ec()],
                 "deco": sorted(self.workers.deco_ids)}
        if self.mounts is not None:
            state["mounts"] = self.mounts.snapshot_state()
        if self.raft_conf is not None:
            state["raft_conf"] = self.raft_conf
        return state

    def _load_snapshot(self, snap: dict) -> None:
        self.store.clear()
        self.open_files = None       # rebuilt lazily from the new state
        have_entries = any(d.get("ch") is not None for d in snap["inodes"])
        for d in snap["inodes"]:
            is_dir = d["dir"]
            ch = d.get("ch") if have_entries else None
            node = Inode(
                id=d["id"], name=d["name"], file_type=FileType(d["ft"]),
                parent_id=d["pid"], mtime=d["mtime"], atime=d["atime"],
                owner=d["owner"], group=d["group"], mode=d["mode"],
                x_attr=d["xattr"] or {},
                storage_policy=StoragePolicy.from_wire(d["sp"]),
                nlink=d["nlink"], len=d["len"], block_size=d["bs"],
                replicas=d["rep"], blocks=list(d["blocks"]),
                is_complete=d["done"], target=d.get("target"),
                children_num=len(ch) if ch is not None else 0)
            self.store.put(node, new=True)
            if ch is not None:
                for name, cid in ch.items():
                    self.store.child_put(node.id, str(name), cid)
        if not have_entries:
            # legacy snapshot: derive children from (parent_id, name)
            counts: dict[int, int] = {}
            for d in snap["inodes"]:
                if d["pid"]:
                    self.store.child_put(d["pid"], d["name"], d["id"])
                    counts[d["pid"]] = counts.get(d["pid"], 0) + 1
            for pid, n in counts.items():
                parent = self.store.get(pid)
                if parent is not None:
                    parent.children_num = n
                    self.store.put(parent)
        self.store.set_counter("next_id", snap["next_id"])
        self.store.set_counter("next_block_id", snap["next_block_id"])
        for bid, blen, iid, rep in snap["blocks"]:
            self.store.block_put(bid, blen, iid, rep)
        for wire in snap.get("jobs", []):
            self.store.job_put(wire["job_id"], wire)
        for bid, stripe in snap.get("ec", []):
            self.store.ec_put(bid, stripe)
        self._rebuild_ec_index()
        self.workers.deco_ids = set(snap.get("deco", []))
        for wid in self.workers.deco_ids:
            self.store.deco_put(wid)
        if self.mounts is not None and "mounts" in snap:
            self.mounts.load_snapshot_state(snap["mounts"])
        if snap.get("raft_conf") is not None:
            self.raft_conf = snap["raft_conf"]

    def _apply(self, op: str, args: dict):
        fn = getattr(self, f"_apply_{op}", None)
        if fn is None:
            raise err.InvalidArgument(f"unknown journal op {op!r}")
        return fn(**args)

    def _apply_noop(self) -> None:
        """Term-opening no-op (raft leader turnover)."""

    def _apply_raft_conf(self, ver: int = 0, voters: dict | None = None,
                         learners: dict | None = None,
                         action: str | None = None,
                         target: int | None = None) -> None:
        """Raft membership config entry (master/ha.py): the state
        machine only RECORDS the active config (so snapshots and replay
        carry it); RaftLite adopts it via on_mutation / _h_append /
        raft.start()."""
        self.raft_conf = {"ver": ver, "voters": dict(voters or {}),
                          "learners": dict(learners or {}),
                          "action": action, "target": target}

    def decommission_worker(self, worker_id: int, on: bool = True) -> None:
        """Journaled decommission intent: survives restarts/failovers
        (workers re-register from heartbeats, so intents can't live only
        in the runtime worker map). Recommission is allowed for ABSENT
        workers too — a durable intent for a long-gone worker must be
        clearable. Parity: curvine-cli node --add/remove-decommission."""
        if on:
            self.workers.get(worker_id)      # raises WorkerNotFound
        self._log("worker_deco", dict(worker_id=worker_id, on=on))

    def _apply_worker_deco(self, worker_id: int, on: bool) -> None:
        if on:
            self.store.deco_put(worker_id)
            self.workers.decommission(worker_id)
        else:
            self.store.deco_remove(worker_id)
            self.workers.recommission(worker_id)

    def _apply_job_put(self, job: dict) -> None:
        """Durable job record (resume after restart/failover)."""
        self.store.job_put(job["job_id"], job)

    def _apply_job_del(self, job_id: str) -> None:
        self.store.job_remove(job_id)

    # ==================== erasure-coded stripes ====================
    # A striped logical block keeps its durable block record (length,
    # inode linkage) but its bytes live in k+m CELL blocks, each a
    # first-class block with its own checksum and replica location.
    # Protocol: ec_plan durably allocates + registers the cell ids
    # BEFORE any cell byte is written (a cell arriving in a worker
    # block report must never look like an orphan and get GC'd), then
    # the converting worker writes all cells and sends EC_COMMIT_STRIPE,
    # which journals ec_put (state "committed") — the read path switches
    # to the stripe and the 3x replicas retire copy-first-delete-last.

    def ec_plan(self, block_id: int, profile: str, k: int, m: int,
                cell_size: int) -> list[int]:
        durable = self.store.block_get(block_id)
        if durable is None:
            raise err.InvalidArgument(f"ec_plan: unknown block {block_id}")
        stripe = self.ec_stripes.get(block_id)
        if stripe is not None and stripe.get("state") == "committed":
            raise err.InvalidArgument(
                f"ec_plan: block {block_id} already striped")
        return self._log("ec_plan", dict(
            block_id=block_id, profile=profile, n_cells=k + m,
            cell_size=cell_size))

    def _apply_ec_plan(self, block_id: int, profile: str, n_cells: int,
                       cell_size: int) -> list[int]:
        durable = self.store.block_get(block_id)
        if durable is None:
            raise err.InvalidArgument(f"ec_plan: unknown block {block_id}")
        blen, inode_id, _rep = durable
        # re-plan (job retry after a crash): free the previous attempt's
        # cells so abandoned ids never leak in the durable block table
        old = self.ec_stripes.get(block_id)
        if old is not None and old.get("state") != "committed":
            for cid in old.get("cells", []):
                meta = self.blocks.remove_block(cid)
                if meta:
                    for wid in meta.locs:
                        self.pending_deletes.setdefault(wid, set()).add(cid)
        cells = [self.tree.alloc_block_id() for _ in range(n_cells)]
        for cid in cells:
            self.store.block_put(cid, cell_size, inode_id, 1)
        stripe = {"profile": profile, "cell_size": cell_size,
                  "block_len": blen, "cells": cells, "state": "planned"}
        self.store.ec_put(block_id, stripe)
        self._ec_index(block_id, stripe)
        return cells

    def ec_commit(self, block_id: int,
                  cell_locs: list[list[int]]) -> None:
        """EC_COMMIT_STRIPE: all cells written. cell_locs is
        [[cell_id, worker_id, storage_type], ...]."""
        stripe = self.ec_stripes.get(block_id)
        if stripe is None:
            raise err.InvalidArgument(
                f"ec_commit: no planned stripe for block {block_id}")
        known = set(stripe.get("cells", []))
        for cid, _wid, _st in cell_locs:
            if cid not in known:
                raise err.InvalidArgument(
                    f"ec_commit: cell {cid} not in stripe {block_id}")
        if stripe.get("state") != "committed":
            self._log("ec_put", dict(block_id=block_id))
        # replica locations are runtime state (rebuilt by reports)
        for cid, wid, st in cell_locs:
            self.blocks.add_replica(cid, wid, StorageType(st))
        self.retire_stripe_replicas(block_id)

    def _apply_ec_put(self, block_id: int) -> None:
        stripe = self.store.ec_get(block_id)
        if stripe is None:
            raise err.InvalidArgument(
                f"ec_put: no planned stripe for block {block_id}")
        stripe = dict(stripe)
        stripe["state"] = "committed"
        self.store.ec_put(block_id, stripe)
        self._ec_index(block_id, stripe)

    def retire_stripe_replicas(self, block_id: int) -> None:
        """Copy-first-delete-last: drop the replicated copies of a
        committed stripe. Runtime-only (worker deletes ride heartbeat
        pending_deletes); the replication scan re-runs this until the
        locations converge to empty, so a crash between ec_put and the
        deletes cannot strand live replicas."""
        meta = self.blocks.get(block_id)
        if meta is None:
            return
        for wid in list(meta.locs):
            self.blocks.remove_replica(block_id, wid)
            self.pending_deletes.setdefault(wid, set()).add(block_id)

    # ==================== namespace ops ====================

    def mkdir(self, path: str, create_parent: bool = True, mode: int = 0o755,
              owner: str = "root", group: str = "root",
              x_attr: dict | None = None) -> FileStatus:
        self._mount_write_guard(path)
        node = self.tree.resolve(path)
        if node is not None:
            if node.is_dir:
                return node.to_status(path)
            raise err.FileAlreadyExists(f"{path} exists and is a file")
        self.tree.check_parent_dirs(path)
        parent, _ = self.tree.resolve_parent(path)
        if parent is None and not create_parent:
            raise err.FileNotFound(f"parent of {path} not found")
        return self._log("mkdir", dict(path=path, create_parent=create_parent,
                                       mode=mode, owner=owner, group=group,
                                       x_attr=x_attr or {}))

    def _apply_mkdir(self, path: str, create_parent: bool, mode: int,
                     owner: str, group: str, x_attr: dict) -> FileStatus:
        node, _ = self.tree.mkdirs(path, mode=mode, owner=owner, group=group,
                                   create_parent=create_parent, x_attr=x_attr)
        return node.to_status(path)

    def create_file(self, path: str, overwrite: bool = False,
                    create_parent: bool = True, replicas: int = 1,
                    block_size: int = 64 * 1024 * 1024, mode: int = 0o644,
                    owner: str = "root", group: str = "root",
                    client_name: str = "", x_attr: dict | None = None,
                    storage_policy: dict | None = None,
                    file_type: int = int(FileType.FILE),
                    walked: tuple | None = None) -> FileStatus:
        # cache-warming loads mark themselves with the ufs_mtime they
        # observed; those creates are allowed on read-only mounts
        caching = bool((storage_policy or {}).get("ufs_mtime"))
        self._mount_write_guard(path, caching=caching)
        # one walk replaces resolve + check_parent_dirs + resolve_parent;
        # the RPC layer passes its acl/quota walk through (same
        # synchronous actor-loop stretch, so the tree cannot change
        # between the two)
        parent, _name, existing = walked or self.tree.walk_parent(path)
        if existing is not None:
            if existing.is_dir:
                raise err.IsADirectory(path)
            if not overwrite:
                raise err.FileAlreadyExists(path)
        if parent is None and not create_parent:
            raise err.FileNotFound(f"parent of {path} not found")
        # leader fast path: hand the validated walk to _apply_create
        # (nothing runs between here and the apply — same synchronous
        # stretch). NOT journaled: replay and followers re-walk.
        self._walk_hint = (parent, _name, existing)
        try:
            return self._log("create", dict(
                path=path, overwrite=overwrite, create_parent=create_parent,
                replicas=replicas, block_size=block_size, mode=mode,
                owner=owner, group=group, client_name=client_name,
                x_attr=x_attr or {},
                storage_policy=storage_policy or dict(_DEFAULT_POLICY_WIRE),
                file_type=file_type))
        finally:
            self._walk_hint = None

    def _apply_create(self, path: str, overwrite: bool, create_parent: bool,
                      replicas: int, block_size: int, mode: int, owner: str,
                      group: str, client_name: str, x_attr: dict,
                      storage_policy: dict, file_type: int) -> FileStatus:
        hint, self._walk_hint = self._walk_hint, None
        parent, name, existing = hint if hint is not None \
            else self.tree.walk_parent(path)
        if existing is not None:
            self._delete_inode(existing, recursive=False, parent=parent,
                               name=name)
        if parent is None:
            parent, _ = self.tree.mkdirs("/".join(path.split("/")[:-1]) or "/")
        if not parent.is_dir:
            raise err.NotADirectory(self.tree.path_of(parent))
        ts = now_ms()
        # the wire->object parse is hot; the overwhelmingly common case
        # is the default policy, which the default ctor builds cheaper
        sp = StoragePolicy() if storage_policy == _DEFAULT_POLICY_WIRE \
            else StoragePolicy.from_wire(storage_policy)
        node = Inode(id=self.tree._alloc_id(), name=name,
                     file_type=FileType(file_type), parent_id=parent.id,
                     mtime=ts, atime=ts, owner=owner, group=group,
                     mode=mode, x_attr=dict(x_attr), storage_policy=sp,
                     replicas=replicas, block_size=block_size,
                     is_complete=False, client_name=client_name)
        self.tree.add_child(parent, node)
        if self.open_files is not None:
            self.open_files.add(node.id)
        return node.to_status(path)

    def append_file(self, path: str, client_name: str = "") -> FileBlocks:
        self._mount_write_guard(path)
        node = self._file_or_raise(path)
        if not node.is_complete:
            raise err.LeaseConflict(f"{path} is being written")
        self._log("set_incomplete", dict(inode_id=node.id,
                                         client_name=client_name))
        return self._file_blocks(self.tree.get(node.id), path)

    def _apply_set_incomplete(self, inode_id: int, client_name: str) -> None:
        node = self._inode_or_raise(inode_id)
        node.is_complete = False
        node.client_name = client_name
        self.tree.save(node)
        if self.open_files is not None:
            self.open_files.add(node.id)

    def exists(self, path: str) -> bool:
        return self.tree.resolve(path) is not None

    def file_status(self, path: str) -> FileStatus:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        return node.to_status(path)

    def list_status(self, path: str) -> list[FileStatus]:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        if not node.is_dir:
            return [node.to_status(path)]
        base = path.rstrip("/")
        return [child.to_status(f"{base}/{name}")
                for name, child in self.tree.children(node)]

    def rename(self, src: str, dst: str) -> bool:
        self._mount_write_guard(src, subtree=True)
        self._mount_write_guard(dst)
        s = self.tree.resolve(src)
        if s is None:
            raise err.FileNotFound(src)
        if src == "/" or dst.startswith(src.rstrip("/") + "/"):
            raise err.InvalidArgument(f"cannot rename {src} into itself")
        d = self.tree.resolve(dst)
        if d is not None:
            if d.is_dir and d.children_num:
                raise err.DirNotEmpty(dst)
            if d.is_dir != s.is_dir:
                raise (err.IsADirectory if d.is_dir else err.NotADirectory)(dst)
        self.tree.check_parent_dirs(dst)
        return self._log("rename", dict(src=src, dst=dst))

    def _apply_rename(self, src: str, dst: str) -> bool:
        s = self.tree.resolve(src)
        if s is None:
            raise err.FileNotFound(src)
        d = self.tree.resolve(dst)
        if d is not None:
            p, n = self.tree.resolve_parent(dst)
            self._delete_inode(d, recursive=False, parent=p, name=n)
        new_parent, new_name = self.tree.resolve_parent(dst)
        if new_parent is None or not new_parent.is_dir:
            raise err.FileNotFound(f"parent of {dst} not found")
        # move the directory ENTRY (src path tail, which for a hard link
        # can differ from s.name): remove old entry, add new, no nlink churn
        old_parent, old_name = self.tree.resolve_parent(src)
        self.store.child_remove(old_parent.id, old_name)
        old_parent.children_num = max(0, old_parent.children_num - 1)
        old_parent.mtime = now_ms()
        self.tree.save(old_parent)
        s.name = new_name
        # refresh: old_parent save may be the same object as new_parent
        new_parent = self.tree.get(new_parent.id)
        s.parent_id = new_parent.id
        self.tree.save(s)
        self.store.child_put(new_parent.id, new_name, s.id)
        new_parent.children_num += 1
        new_parent.mtime = now_ms()
        self.tree.save(new_parent)
        return True

    def delete(self, path: str, recursive: bool = False,
               system: bool = False) -> None:
        # system=True: master-internal reclaim (TTL actions) bypasses the
        # read-only-mount guard — the mount's own policy initiated it
        if not system:
            self._mount_write_guard(path, subtree=recursive)
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        if node.is_dir and node.children_num and not recursive:
            raise err.DirNotEmpty(path)
        if node.id == ROOT_ID:
            raise err.InvalidArgument("cannot delete root")
        self._log("delete", dict(path=path, recursive=recursive))

    def _apply_delete(self, path: str, recursive: bool) -> None:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        parent, name = self.tree.resolve_parent(path)
        self._delete_inode(node, recursive, parent=parent, name=name)

    def _delete_inode(self, node: Inode, recursive: bool,
                      parent: Inode | None = None,
                      name: str | None = None) -> None:
        """`name` is the directory-entry name being removed — it can
        differ from node.name when the inode has hard links."""
        if node.is_dir and node.children_num:
            if not recursive:
                raise err.DirNotEmpty(self.tree.path_of(node))
            for child_name, child in self.tree.children(node):
                self._delete_inode(child, recursive=True,
                                   parent=node, name=child_name)
        if parent is None:
            parent = self.tree.get(node.parent_id)
        if parent is not None:
            removed = self.tree.remove_child(parent, name or node.name)
            if removed is not None and removed.nlink <= 0:
                self._free_blocks(removed)
                if self.open_files is not None:
                    self.open_files.discard(removed.id)

    def _free_blocks(self, node: Inode) -> None:
        """Drops the node's blocks. Does NOT save the inode: callers on
        the delete path have already removed it from the store (saving
        would resurrect it as an orphan); the free path saves explicitly."""
        for bid in node.blocks:
            stripe = self.ec_stripes.pop(bid, None)
            if stripe is not None:
                # striped block: free its cells too
                for cid in stripe.get("cells", []):
                    self.ec_cells.pop(cid, None)
                    cmeta = self.blocks.remove_block(cid)
                    if cmeta:
                        for wid in cmeta.locs:
                            self.pending_deletes.setdefault(
                                wid, set()).add(cid)
                self.store.ec_remove(bid)
            meta = self.blocks.remove_block(bid)
            if meta:
                for wid in meta.locs:
                    self.pending_deletes.setdefault(wid, set()).add(bid)
        node.blocks = []

    def free(self, path: str, recursive: bool = False) -> int:
        """Drop cached blocks but keep metadata (data remains in UFS)."""
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        return self._log("free", dict(path=path, recursive=recursive))

    def _apply_free(self, path: str, recursive: bool) -> int:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        return self._free_inode(node, recursive)

    def _free_inode(self, node: Inode, recursive: bool) -> int:
        n = 0
        if node.is_dir:
            if not recursive:
                return 0
            for _name, child in self.tree.children(node):
                n += self._free_inode(child, recursive)
            return n
        if node.blocks:
            self._free_blocks(node)
            node.storage_policy.state = StorageState.UFS
            self.tree.save(node)
            n += 1
        return n

    def set_attr(self, path: str, opts: SetAttrOpts) -> None:
        self._mount_write_guard(path)
        if self.tree.resolve(path) is None:
            raise err.FileNotFound(path)
        if opts.ec:
            from curvine_tpu.common.ec import ECProfile
            ECProfile.parse(opts.ec)       # validate before journaling
        self._log("set_attr", dict(path=path, opts=opts.to_wire()))

    def _apply_set_attr(self, path: str, opts: dict) -> None:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        o = SetAttrOpts.from_wire(opts)
        if o.replicas is not None:
            node.replicas = o.replicas
        if o.owner is not None:
            node.owner = o.owner
        if o.group is not None:
            node.group = o.group
        if o.mode is not None:
            node.mode = o.mode
        if o.ttl_ms is not None:
            node.storage_policy.ttl_ms = o.ttl_ms
        if o.ttl_action is not None:
            node.storage_policy.ttl_action = TtlAction(o.ttl_action)
        if o.atime is not None:
            node.atime = o.atime
        if o.mtime is not None:
            node.mtime = o.mtime
        if o.ec is not None:
            node.storage_policy.ec = o.ec
        node.x_attr.update(o.add_x_attr)
        for k in o.remove_x_attr:
            node.x_attr.pop(k, None)
        self.tree.save(node)

    def symlink(self, target: str, link: str) -> FileStatus:
        self._mount_write_guard(link)
        if self.tree.resolve(link) is not None:
            raise err.FileAlreadyExists(link)
        parent, _ = self.tree.resolve_parent(link)
        if parent is None or not parent.is_dir:
            raise err.FileNotFound(f"parent of {link} not found")
        return self._log("symlink", dict(target=target, link=link))

    def _apply_symlink(self, target: str, link: str) -> FileStatus:
        parent, name = self.tree.resolve_parent(link)
        if parent is None or not parent.is_dir:
            raise err.FileNotFound(f"parent of {link} not found")
        node = Inode(id=self.tree._alloc_id(), name=name,
                     file_type=FileType.LINK, parent_id=parent.id,
                     mtime=now_ms(), atime=now_ms(), target=target)
        self.tree.add_child(parent, node)
        return node.to_status(link)

    def link(self, src: str, dst: str) -> FileStatus:
        self._mount_write_guard(dst)
        self._file_or_raise(src)
        if self.tree.resolve(dst) is not None:
            raise err.FileAlreadyExists(dst)
        parent, _ = self.tree.resolve_parent(dst)
        if parent is None or not parent.is_dir:
            raise err.FileNotFound(f"parent of {dst} not found")
        return self._log("link", dict(src=src, dst=dst))

    def _apply_link(self, src: str, dst: str) -> FileStatus:
        node = self._file_or_raise(src)
        parent, name = self.tree.resolve_parent(dst)
        if parent is None or not parent.is_dir:
            raise err.FileNotFound(f"parent of {dst} not found")
        self.tree.add_entry(parent, name, node)
        return node.to_status(dst)

    # ============ cross-shard two-phase ops (master/sharding.py) ============
    # Presumed-abort 2PC for renames/links whose src and dst hash to
    # different namespace shards. Each participant journals its vote
    # (tx_prepare) and keeps a durable tx record until the coordinator
    # tells it to commit/abort; the dst side RETAINS its record in state
    # "committed" until the final forget, so a recovery sweep that finds
    # any committed record knows the tx passed the commit point. All
    # methods run on the shard's single-writer actor loop.

    def tx_prepare(self, txid: str, op: str, src: str, dst: str,
                   role: str, rec: dict | None = None) -> dict:
        from curvine_tpu.master.store import _enc_inode
        if op not in ("rename", "link"):
            raise err.InvalidArgument(f"unknown shard tx op {op!r}")
        if role == "src":
            node = self.tree.resolve(src)
            if node is None:
                raise err.FileNotFound(src)
            if node.is_dir:
                raise err.IsADirectory(src)
            if op == "rename":
                self._mount_write_guard(src)
                if node.nlink > 1:
                    raise err.Unsupported(
                        "cross-shard rename of a hard-linked file")
                if not node.is_complete:
                    raise err.InvalidArgument(
                        f"cross-shard rename of open file {src}")
            blocks, locs = [], []
            for bid in node.blocks:
                meta = self.blocks.get(bid)
                if meta is None:
                    blocks.append([bid, 0, 1])
                    continue
                blocks.append([bid, meta.len, meta.replicas])
                for wid, loc in meta.locs.items():
                    locs.append([bid, wid, int(loc.storage_type)])
            rec = {"txid": txid, "role": "src", "op": op, "src": src,
                   "dst": dst, "inode": _enc_inode(node), "blocks": blocks,
                   "locs": locs, "state": "prepared"}
        else:
            if rec is None:
                raise err.InvalidArgument("dst prepare without src payload")
            self._mount_write_guard(dst)
            d = self.tree.resolve(dst)
            if d is not None:
                if op == "link":
                    raise err.FileAlreadyExists(dst)
                if d.is_dir and d.children_num:
                    raise err.DirNotEmpty(dst)
                if d.is_dir:
                    raise err.IsADirectory(dst)
            self.tree.check_parent_dirs(dst)
            rec = dict(rec)
            rec["role"] = "dst"
        self._log("tx_prepare", dict(rec=rec))
        return rec

    def _apply_tx_prepare(self, rec: dict) -> None:
        self.store.tx_put(rec["txid"], rec)

    def tx_commit(self, txid: str) -> None:
        # idempotent: a retried/replayed commit for a forgotten tx no-ops
        if self.store.tx_get(txid) is None:
            return
        self._log("tx_commit", dict(txid=txid))

    def _apply_tx_commit(self, txid: str) -> None:
        rec = self.store.tx_get(txid)
        if rec is None:
            return
        if rec["role"] == "src":
            self._tx_commit_src(rec)
            self.store.tx_remove(txid)
            return
        self._tx_commit_dst(rec)
        # dst keeps the record ("committed") until the coordinator's
        # forget — it is the durable marker that the tx passed the
        # commit point, consulted by the crash-recovery sweep
        rec = dict(rec)
        rec["state"] = "committed"
        self.store.tx_put(txid, rec)

    def _tx_commit_src(self, rec: dict) -> None:
        node = self.tree.resolve(rec["src"])
        if node is None:
            return                     # replay after the entry moved
        if rec["op"] == "link":
            # the dst shard now holds a mirrored entry referencing the
            # same blocks: count it here so a later delete of this copy
            # never frees blocks the mirror still reads
            node.nlink += 1
            self.tree.save(node)
            return
        parent, name = self.tree.resolve_parent(rec["src"])
        if parent is None:
            return
        removed = self.tree.remove_child(parent, name)
        if removed is not None:
            # drop block METAS only — ownership moved to the dst shard,
            # so no worker-side deletes are queued
            for bid in list(removed.blocks):
                self.blocks.remove_block(bid)
            if self.open_files is not None:
                self.open_files.discard(removed.id)

    def _tx_commit_dst(self, rec: dict) -> None:
        from curvine_tpu.master.store import _dec_inode
        node = _dec_inode(rec["inode"])
        dst = rec["dst"]
        parent, name = self.tree.resolve_parent(dst)
        if parent is None or not parent.is_dir:
            raise err.FileNotFound(f"parent of {dst} not found")
        existing = self.tree.resolve(dst)
        if existing is not None:
            if existing.id == node.id:
                return                 # replay: already committed
            if rec["op"] == "rename":
                self._delete_inode(existing, recursive=False,
                                   parent=parent, name=name)
                parent = self.tree.get(parent.id)
            else:
                raise err.FileAlreadyExists(dst)
        node.name = name
        node.parent_id = parent.id
        node.mtime = now_ms()
        if rec["op"] == "link":
            # mirrored hard link: 1 for this entry + 1 phantom for the
            # src shard's copy — neither side ever frees the shared
            # blocks (leak-over-corruption; see docs/metadata-scale.md)
            node.nlink = 2
        self.tree.add_child(parent, node)
        for bid, length, replicas in rec.get("blocks", []):
            self.blocks.put(bid, length, node.id, replicas)
        for bid, wid, st in rec.get("locs", []):
            self.blocks.add_replica(bid, wid, StorageType(st))

    def tx_abort(self, txid: str) -> None:
        if self.store.tx_get(txid) is None:
            return
        self._log("tx_abort", dict(txid=txid))

    def _apply_tx_abort(self, txid: str) -> None:
        self.store.tx_remove(txid)

    def tx_forget(self, txid: str) -> None:
        if self.store.tx_get(txid) is None:
            return
        self._log("tx_forget", dict(txid=txid))

    def _apply_tx_forget(self, txid: str) -> None:
        self.store.tx_remove(txid)

    def list_tx(self) -> list[dict]:
        """In-doubt tx records for the recovery sweep (no inode bytes)."""
        out = []
        for rec in self.store.iter_tx():
            out.append({k: rec[k] for k in
                        ("txid", "role", "op", "src", "dst", "state")})
        return out

    def resize_file(self, path: str, new_len: int) -> None:
        """Shrink OR extend. Extending past the last written block
        creates a HOLE — a region with no backing block — which the
        client read path serves as zeros (parity: reference
        block_reader_hole.rs; sparse-file semantics)."""
        self._mount_write_guard(path)
        node = self._file_or_raise(path)
        if new_len < 0:
            raise err.InvalidArgument(f"resize to negative length {new_len}")
        self._log("resize", dict(path=path, new_len=new_len))

    def _apply_resize(self, path: str, new_len: int) -> None:
        node = self._file_or_raise(path)
        grow = new_len >= node.len
        node.len = new_len
        node.mtime = now_ms()
        if grow:
            # extend: existing blocks keep their data, the tail becomes
            # a hole (no block allocation — readers zero-fill)
            self.tree.save(node)
            return
        # drop whole blocks past the new length
        keep, off = [], 0
        for bid in node.blocks:
            meta = self.blocks.get(bid)
            blen = meta.len if meta else node.block_size
            if off < new_len:
                keep.append(bid)
            else:
                removed = self.blocks.remove_block(bid)
                if removed:
                    for wid in removed.locs:
                        self.pending_deletes.setdefault(wid, set()).add(bid)
            off += blen
        node.blocks = keep
        self.tree.save(node)

    # ==================== block ops ====================

    def add_block(self, path: str, client_host: str = "",
                  exclude_workers: list[int] | None = None,
                  commit_blocks: list[CommitBlock] | None = None,
                  ici_coords: list[int] | None = None,
                  storage_type: StorageType = StorageType.MEM,
                  abandon_block: int | None = None,
                  ) -> LocatedBlock:
        node = self._file_or_raise(path)
        if node.is_complete:
            raise err.LeaseConflict(f"{path} is not open for writing")
        self._commit(node, commit_blocks)
        chosen = self.policy.choose(
            self.workers.live_workers(), max(1, node.replicas),
            client_host=client_host, exclude=set(exclude_workers or []),
            needed=node.block_size, ici_coords=ici_coords, min_count=1)
        args = dict(inode_id=node.id)
        # HDFS abandonBlock semantics: a writer retrying a failed block
        # open discards its previous allocation in the same journal
        # entry, so retries never accumulate zero-length ghost blocks on
        # the inode. Only the trailing, never-committed block qualifies.
        if abandon_block is not None and node.blocks \
                and node.blocks[-1] == abandon_block:
            meta = self.blocks.get(abandon_block)
            if meta is None or meta.len == 0:
                args["abandon"] = abandon_block
        block_id = self._log("alloc_block", args)
        block = ExtendedBlock(id=block_id, len=0, storage_type=storage_type,
                              file_type=node.file_type)
        node = self.tree.get(node.id)
        off = sum(meta.len for b in node.blocks[:-1]
                  if (meta := self.blocks.get(b)) is not None)
        return LocatedBlock(block=block, offset=off,
                            locs=[w.address for w in chosen],
                            storage_types=[storage_type] * len(chosen))

    def _apply_alloc_block(self, inode_id: int,
                           abandon: int | None = None) -> int:
        node = self._inode_or_raise(inode_id)
        if abandon is not None and node.blocks \
                and node.blocks[-1] == abandon:
            node.blocks.pop()
            self.blocks.remove_block(abandon)
        block_id = self.tree.alloc_block_id()
        node.blocks.append(block_id)
        node.mtime = now_ms()      # writer liveness for lease recovery
        self.tree.save(node)
        # placeholder meta: a worker report of this in-flight block must
        # not look like an orphan (it is referenced by the inode)
        if self.store.block_get(block_id) is None:
            self.store.block_put(block_id, 0, inode_id, node.replicas)
        return block_id

    def complete_file(self, path: str, length: int,
                      commit_blocks: list[CommitBlock] | None = None,
                      client_name: str = "", only_flush: bool = False) -> bool:
        node = self._file_or_raise(path)
        self._commit(node, commit_blocks)
        if not only_flush:
            self._log("complete", dict(path=path, length=length))
        return True

    def _apply_complete(self, path: str, length: int) -> None:
        node = self._file_or_raise(path)
        node.len = length
        node.is_complete = True
        node.mtime = now_ms()
        node.client_name = ""
        self.tree.save(node)
        if self.open_files is not None:
            self.open_files.discard(node.id)

    def _commit(self, node: Inode, commit_blocks: list[CommitBlock] | None
                ) -> None:
        """Journal block lens (durable), then register replica locations
        (runtime state, rebuilt from worker reports after a restart)."""
        if not commit_blocks:
            return
        self._log("commit_blocks", dict(
            inode_id=node.id,
            commits=[[cb.block_id, cb.block_len] for cb in commit_blocks]))
        for cb in commit_blocks:
            for wid in cb.worker_ids:
                self.blocks.add_replica(cb.block_id, wid, cb.storage_type)

    def _apply_commit_blocks(self, inode_id: int, commits: list) -> None:
        node = self.tree.get(inode_id)
        replicas = node.replicas if node is not None else 1
        for bid, blen in commits:
            durable = self.store.block_get(bid)
            if durable is None:
                self.store.block_put(bid, blen, inode_id, replicas)
            else:
                old_len, iid, rep = durable
                self.store.block_put(bid, max(old_len, blen),
                                     iid or inode_id, rep)

    def get_block_locations(self, path: str) -> FileBlocks:
        node = self._file_or_raise(path)
        return self._file_blocks(node, path)

    def _file_blocks(self, node: Inode, path: str) -> FileBlocks:
        out = []
        off = 0
        for bid in node.blocks:
            meta = self.blocks.get(bid)
            if meta is None:
                continue
            locs, sts = [], []
            for wid, loc in meta.locs.items():
                try:
                    w = self.workers.get(wid)
                except err.WorkerNotFound:
                    continue
                # LIVE and DECOMMISSIONING replicas both serve reads
                # (draining workers keep their data until re-replicated)
                if w.state.value in (0, 2):
                    locs.append(w.address)
                    sts.append(loc.storage_type)
            out.append(LocatedBlock(
                block=ExtendedBlock(id=bid, len=meta.len,
                                    storage_type=sts[0] if sts else StorageType.MEM,
                                    file_type=node.file_type),
                offset=off, locs=locs, storage_types=sts,
                ec=self._ec_descriptor(bid)))
            off += meta.len
        return FileBlocks(status=node.to_status(path), block_locs=out)

    def _ec_descriptor(self, block_id: int) -> dict | None:
        """Stripe descriptor for a located block: per-cell ids + live
        worker addresses (wire form). None for replicated blocks and
        for stripes still mid-conversion (replicas serve those)."""
        stripe = self.ec_stripes.get(block_id)
        if stripe is None or stripe.get("state") != "committed":
            return None
        cells = []
        for idx, cid in enumerate(stripe["cells"]):
            cmeta = self.blocks.get(cid)
            clocs = []
            if cmeta is not None:
                for wid in cmeta.locs:
                    try:
                        w = self.workers.get(wid)
                    except err.WorkerNotFound:
                        continue
                    if w.state.value in (0, 2):
                        clocs.append(w.address.to_wire())
            cells.append({"index": idx, "block_id": cid, "locs": clocs})
        return {"profile": stripe["profile"],
                "cell_size": stripe["cell_size"],
                "block_len": stripe["block_len"], "cells": cells}

    # ==================== worker plane ====================

    def worker_heartbeat(self, info_wire: dict) -> dict:
        info = WorkerInfo.from_wire(info_wire)
        w = self.workers.heartbeat(info.address, info.storages,
                                   info.ici_coords)
        wid = info.address.worker_id
        deletes = list(self.pending_deletes.pop(wid, set()))
        cmds = {"delete_blocks": deletes}
        if w.state in (WorkerState.LIVE, WorkerState.DECOMMISSIONING) \
                and not self.workers.has_current_report(wid):
            # no full block report since this worker (re)registered — the
            # worker just started, returned from LOST, or THIS MASTER
            # restarted and lost its runtime location map. Ask for a
            # report now: reads need locations, and waiting out the
            # periodic report interval leaves every pre-restart block
            # location-less for up to that long.
            cmds["report_now"] = True
        if w.state == WorkerState.DECOMMISSIONING:
            # drain hint: the worker bounces NEW write streams with a
            # retryable error (in-flight ones finish), so the drain scan
            # never races fresh uploads onto a departing worker
            cmds["draining"] = True
        return cmds

    def worker_block_report(self, worker_id: int, held: dict,
                            storage_types: dict,
                            incremental: bool = False) -> dict:
        w = self.workers.workers.get(worker_id)
        if w is not None and w.state == WorkerState.DECOMMISSIONED:
            # a drained worker's copies are surplus and were purged from
            # the block map at drain completion — a report must not
            # resurrect them as countable locations
            return {"delete_blocks": []}
        held = {int(k): int(v) for k, v in held.items()}
        storage_types = {int(k): int(v) for k, v in storage_types.items()}
        orphans = self.blocks.apply_report(worker_id, held, storage_types,
                                           incremental=incremental)
        if not incremental:
            self.workers.mark_reported(worker_id)
        # report-driven len bumps are durable but not journaled: persist
        # them now so they don't ride some later entry's atomic batch
        self.store.commit_runtime()
        return {"delete_blocks": orphans}

    def recover_stale_leases(self, lease_timeout_ms: int = 300_000) -> int:
        """Finalize files abandoned mid-write (dead client, no complete).
        Parity: master/fs/fs_dir_watchdog.rs. A stale incomplete file is
        completed at its committed block length (data salvaged) or deleted
        when nothing was ever committed."""
        deadline = now_ms() - lease_timeout_ms
        recovered = 0
        if self.open_files is None:
            # one lazy scan after restart; incremental from then on
            self.open_files = {n.id for n in self.tree.iter_files()
                               if not n.is_complete}
        for inode_id in list(self.open_files):
            node = self.tree.get(inode_id)
            if node is None or node.file_type == FileType.DIR:
                self.open_files.discard(inode_id)
                continue
            if node.is_complete:
                self.open_files.discard(inode_id)
                continue
            if node.mtime >= deadline:
                continue
            path = self.tree.path_of(node)
            committed = sum((self.blocks.get(b).len
                             for b in node.blocks if self.blocks.get(b)),
                            start=0)
            try:
                if committed > 0:
                    self._log("complete", dict(path=path, length=committed))
                    log.warning("lease recovery: completed %s at %d bytes",
                                path, committed)
                else:
                    self._log("delete", dict(path=path, recursive=False))
                    log.warning("lease recovery: removed empty stale %s",
                                path)
                recovered += 1
            except err.CurvineError as e:
                log.warning("lease recovery of %s failed: %s", path, e)
        return recovered

    def check_lost_workers(self, act: bool = True) -> list[WorkerInfo]:
        """LOST-state bookkeeping always runs (reads filter locations on
        worker state, so followers must notice dead workers too);
        `act=False` skips the repair dispatch side effects (HA followers
        must not initiate re-replication)."""
        newly_lost = self.workers.check_lost()
        for w in newly_lost:
            affected = self.blocks.worker_lost(w.address.worker_id)
            if act and affected and self.on_worker_lost:
                self.on_worker_lost(w, affected)
        return newly_lost

    def master_info(self, addr: str = "") -> MasterInfo:
        cap, avail = self.workers.capacity()
        return MasterInfo(
            active_master=addr, inode_num=self.tree.count(),
            block_num=self.blocks.count(), capacity=cap, available=avail,
            fs_used=cap - avail,
            # draining workers still serve and still report in: they
            # belong in the live list (their state field says the rest);
            # fully-drained DECOMMISSIONED workers ride the lost list so
            # `cv node list` keeps showing the safe-to-remove signal
            live_workers=self.workers.serving_workers(),
            lost_workers=(self.workers.lost_workers()
                          + self.workers.retired_workers()))

    # ==================== helpers ====================

    def _mount_write_guard(self, path: str, caching: bool = False,
                           subtree: bool = False) -> None:
        """Reference parity: write RPCs under a read-only mount are
        refused (curvine-client unified_filesystem.rs
        is_mount_write_rpc + AccessMode); enforced master-side here so
        every client/gateway/FUSE path also gets it without carrying the
        mount table. Cache-warming loads are exempt — their creates
        carry the ufs_mtime marker. Like the reference's client-side
        gate, that marker is COOPERATIVE (a raw-RPC client can set it);
        the access mode protects against accidental writes — authz is
        the ACL layer's job. `subtree` ops (recursive delete, rename of
        an ancestor) are refused when a read-only mount lies anywhere
        UNDER the target too."""
        if self.mounts is None or caching:
            return
        m = self.mounts.get_mount(path)
        if m is not None and getattr(m, "access_mode", "rw") == "r":
            raise err.Unsupported(
                f"write on read-only mount {m.cv_path}: {path}")
        if subtree:
            prefix = path.rstrip("/") + "/"
            for info in self.mounts.table():
                if info.access_mode == "r" and \
                        info.cv_path.startswith(prefix):
                    raise err.Unsupported(
                        f"{path} contains read-only mount {info.cv_path}")

    def _file_or_raise(self, path: str) -> Inode:
        node = self.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        if node.is_dir:
            raise err.IsADirectory(path)
        return node

    def _inode_or_raise(self, inode_id: int) -> Inode:
        node = self.tree.get(inode_id)
        if node is None:
            raise err.FileNotFound(f"inode {inode_id}")
        return node
