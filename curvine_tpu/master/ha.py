"""Master HA: raft-lite journal replication.

Parity: curvine-common/src/raft/ (raft_node, raft_journal, snapshot/) —
the reference replicates master metadata through the raft crate. This is
a compact re-implementation over our RPC fabric with the same observable
behavior: leader election (highest journal seq wins, majority votes,
term-monotonic), journal-entry streaming to followers, snapshot catch-up
for lagging peers, NOT_LEADER redirects that the client already follows.

Simplification vs full Raft (documented): the leader applies+journals
locally before majority acknowledgment, so an acked write can be lost if
the leader dies before any follower received it. The reference's raft
commit rule closes that window; tightening this is tracked for a later
round."""

from __future__ import annotations

import asyncio
import logging
import random

import msgpack

from curvine_tpu.common import errors as err
from curvine_tpu.rpc import Message, RpcCode, RpcServer, ServerConn
from curvine_tpu.rpc.client import ConnectionPool
from curvine_tpu.rpc.frame import pack, unpack

log = logging.getLogger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftLite:
    def __init__(self, node_id: int, peers: dict[int, str], fs,
                 rpc: RpcServer, election_timeout_ms: tuple[int, int] =
                 (600, 1200), heartbeat_ms: int = 150):
        self.node_id = node_id
        self.peers = dict(peers)            # id -> addr (excluding self)
        self.fs = fs
        self.rpc = rpc
        self.role = FOLLOWER
        self.term = 0
        self.voted_for: int | None = None
        self.leader_id: int | None = None
        self.election_timeout = election_timeout_ms
        self.heartbeat_ms = heartbeat_ms
        self.pool = ConnectionPool(size=1, timeout_ms=2_000)
        self._last_heard = 0.0
        self._bg: list[asyncio.Task] = []
        self._repl_queues: dict[int, asyncio.Queue] = {}
        rpc.register(RpcCode.RAFT_VOTE, self._h_vote)
        rpc.register(RpcCode.RAFT_APPEND, self._h_append)
        rpc.register(RpcCode.RAFT_SNAPSHOT, self._h_snapshot)

    # ---------------- lifecycle ----------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    @property
    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def last_seq(self) -> int:
        return self.fs.journal.seq if self.fs.journal else 0

    async def start(self) -> None:
        self._touch()
        self._bg.append(asyncio.ensure_future(self._election_loop()))

    async def stop(self) -> None:
        for t in self._bg:
            t.cancel()
        self._bg.clear()
        await self.pool.close()

    def _touch(self) -> None:
        self._last_heard = asyncio.get_event_loop().time()

    # ---------------- election ----------------

    async def _election_loop(self) -> None:
        while True:
            timeout = random.uniform(*self.election_timeout) / 1000
            await asyncio.sleep(timeout / 4)
            if self.role == LEADER:
                continue
            now = asyncio.get_event_loop().time()
            if now - self._last_heard < timeout:
                continue
            await self._run_election()

    async def _run_election(self) -> None:
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.node_id
        self.leader_id = None
        votes = 1
        log.info("node %d: starting election term %d (last_seq=%d)",
                 self.node_id, self.term, self.last_seq())

        async def ask(pid: int, addr: str) -> bool:
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_VOTE, data=pack({
                    "term": self.term, "candidate": self.node_id,
                    "last_seq": self.last_seq()}), timeout=1.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                return bool(body.get("granted"))
            except Exception:
                return False

        # Tally votes as they land: waiting on slow/dead peers must not
        # delay a quorum win (a rival's next-term request would demote us
        # first and elections would live-lock).
        term_at_start = self.term
        tasks = [asyncio.ensure_future(ask(pid, addr))
                 for pid, addr in self.peers.items()]
        try:
            for fut in asyncio.as_completed(tasks):
                granted = await fut
                if self.role != CANDIDATE or self.term != term_at_start:
                    return
                if granted:
                    votes += 1
                if votes >= self.quorum:
                    await self._become_leader()
                    return
        finally:
            for t in tasks:
                t.cancel()
        if self.role == CANDIDATE:
            self.role = FOLLOWER
            self._touch()

    def _step_down(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
        if self.role == LEADER:
            log.info("node %d: stepping down in term %d", self.node_id, term)
            for t in self._bg[1:]:
                t.cancel()
            del self._bg[1:]
        self.role = FOLLOWER
        self._touch()

    async def _become_leader(self) -> None:
        log.info("node %d: leader for term %d", self.node_id, self.term)
        self.role = LEADER
        self.leader_id = self.node_id
        self._repl_queues = {pid: asyncio.Queue() for pid in self.peers}
        for pid, addr in self.peers.items():
            self._bg.append(asyncio.ensure_future(
                self._replicate_loop(pid, addr)))

    # ---------------- replication (leader) ----------------

    def on_mutation(self, seq: int, op: str, args: dict) -> None:
        """Called by MasterFilesystem._log after a local apply+journal."""
        if self.role != LEADER:
            return
        for q in self._repl_queues.values():
            q.put_nowait((seq, op, args))

    async def _replicate_loop(self, pid: int, addr: str) -> None:
        """Per-follower: heartbeats + journal entry stream + catch-up."""
        follower_seq = -1     # unknown until first ack
        while self.role == LEADER:
            batch: list = []
            q = self._repl_queues[pid]
            try:
                entry = await asyncio.wait_for(
                    q.get(), self.heartbeat_ms / 1000)
                batch.append(entry)
                while not q.empty() and len(batch) < 256:
                    batch.append(q.get_nowait())
            except asyncio.TimeoutError:
                pass          # heartbeat
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_APPEND, data=pack({
                    "term": self.term, "leader": self.node_id,
                    "entries": [[s, o, a] for s, o, a in batch],
                    "leader_seq": self.last_seq()}), timeout=2.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                    return
                follower_seq = body.get("applied_seq", follower_seq)
                if body.get("need_snapshot"):
                    await self._send_snapshot(addr)
            except Exception as e:
                log.debug("replicate to %d failed: %s", pid, e)
                # don't lose the batch: requeue it for the next round
                # (followers dedupe by seq)
                for entry in batch:
                    q.put_nowait(entry)
                await asyncio.sleep(0.2)

    async def _send_snapshot(self, addr: str) -> None:
        state = self.fs._snapshot_state()
        conn = await self.pool.get(addr)
        await conn.call(RpcCode.RAFT_SNAPSHOT, data=msgpack.packb({
            "term": self.term, "leader": self.node_id,
            "seq": self.last_seq(), "state": state}, use_bin_type=True),
            timeout=30.0)
        log.info("snapshot (seq=%d) sent to %s", self.last_seq(), addr)

    # ---------------- handlers (follower) ----------------

    async def _h_vote(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term, candidate, last_seq = q["term"], q["candidate"], q["last_seq"]
        if term > self.term:
            self._step_down(term)
        granted = (term >= self.term
                   and self.voted_for in (None, candidate)
                   and last_seq >= self.last_seq())
        if granted:
            self.voted_for = candidate
            self._touch()
        return {}, pack({"granted": granted, "term": self.term})

    async def _h_append(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term = q["term"]
        if term < self.term:
            return {}, pack({"term": self.term, "applied_seq": self.last_seq()})
        if term > self.term or self.role != FOLLOWER:
            self._step_down(term)
        self.leader_id = q["leader"]
        self._touch()
        need_snapshot = False
        for seq, op, args in q.get("entries", []):
            if seq <= self.last_seq():
                continue                      # already have it
            if seq != self.last_seq() + 1:
                need_snapshot = True          # gap: ask for catch-up
                break
            try:
                self.fs._apply(op, args)
            except err.CurvineError as e:
                log.warning("follower apply %s failed: %s", op, e)
            if self.fs.journal:
                self.fs.journal.append(op, args)
        if not need_snapshot and q.get("leader_seq", 0) > self.last_seq():
            need_snapshot = True
        return {}, pack({"term": self.term, "applied_seq": self.last_seq(),
                         "need_snapshot": need_snapshot})

    async def _h_snapshot(self, msg: Message, conn: ServerConn):
        q = msgpack.unpackb(bytes(msg.data), raw=False, strict_map_key=False)
        if q["term"] < self.term:
            return {}, pack({"term": self.term})
        self._touch()
        self.fs._load_snapshot(q["state"])
        if self.fs.journal:
            self.fs.journal.seq = q["seq"]
            self.fs.journal.write_snapshot(q["state"])
        log.info("node %d: installed snapshot at seq %d", self.node_id,
                 q["seq"])
        return {}, pack({"term": self.term, "applied_seq": self.last_seq()})

    # ---------------- client gate ----------------

    def check_leader(self) -> None:
        if self.role != LEADER:
            raise err.NotLeader(
                f"node {self.node_id} is {self.role}; "
                f"leader is {self.leader_id}")
