"""Master HA: raft-lite journal replication with a membership lifecycle.

Parity: curvine-common/src/raft/ (raft_node, raft_journal, snapshot/,
raft_group.rs) — the reference replicates master metadata through the
raft crate. This is a compact re-implementation over our RPC fabric with
the same observable guarantees:

* leader election with persisted hard state (term + voted_for survive
  restarts, so a node cannot double-vote in the same term);
* log matching: every entry carries its term; AppendEntries carries the
  predecessor's (seq, term) and followers reject mismatches, falling back
  to a full snapshot install (which REPLACES follower state — the correct
  recovery for a follower whose state machine already applied divergent
  entries, since applies here are not undoable);
* commit-after-majority: client-visible acks wait until the entry's seq
  is replicated on a quorum (`wait_committed`), closing the acked-write-
  loss window the round-1/2 design documented;
* journaled membership (docs/raft.md): single-server config changes
  (ADD_LEARNER / PROMOTE / REMOVE) ride the journal as ``raft_conf``
  entries and take effect when appended; quorum is computed from the
  active voter set; one change may be in flight at a time; a removed
  node refuses to start elections and peers refuse its vote requests;
* learners: non-voting members that receive the full replication stream
  (chunked snapshot install + log tail) but never count toward quorum;
  the leader auto-promotes a learner once its match lag drops below
  ``master.raft_promote_lag``, so growing the cluster never drops the
  effective quorum;
* chunked snapshot install: catch-up state streams as bounded, resumable
  RAFT_SNAPSHOT_CHUNK frames with a final CRC — a namespace larger than
  MAX_FRAME (the 10M-file scale is ~332 MB) can rejoin, which the
  monolithic blob never could;
* leader transfer: the leader drains its log to the chosen voter, then
  sends TIMEOUT_NOW so the target elects immediately (bounded,
  election-timeout-free failover for rolling restarts).

The leader still applies locally before replicating (reference applies on
commit; here applies are deterministic and a deposed leader's extra
applied entries are healed by snapshot install from the new leader).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import zlib

import msgpack

from curvine_tpu.common import errors as err
from curvine_tpu.rpc import Message, RpcCode, RpcServer, ServerConn
from curvine_tpu.rpc.client import ConnectionPool
from curvine_tpu.rpc.frame import pack, unpack

log = logging.getLogger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"

_ROLE_GAUGE = {FOLLOWER: 0, CANDIDATE: 1, LEADER: 2}
# soft byte cap per AppendEntries batch: entries with fat args (xattrs,
# batched creates) must never push one frame past MAX_FRAME
_BATCH_SOFT_BYTES = 8 * 1024 * 1024


def _rough_size(obj) -> int:
    """Cheap wire-size estimate for batch byte capping (not exact msgpack
    accounting — it only has to be the right order of magnitude)."""
    if isinstance(obj, (bytes, bytearray, memoryview, str)):
        return len(obj)
    if isinstance(obj, dict):
        return 8 + sum(_rough_size(k) + _rough_size(v)
                       for k, v in obj.items())
    if isinstance(obj, (list, tuple)):
        return 8 + sum(_rough_size(v) for v in obj)
    return 8


class RaftLite:
    def __init__(self, node_id: int, peers: dict[int, str], fs,
                 rpc: RpcServer, election_timeout_ms: tuple[int, int] =
                 (600, 1200), heartbeat_ms: int = 150,
                 state_dir: str | None = None,
                 commit_timeout_s: float = 10.0,
                 self_addr: str = "",
                 learner: bool = False,
                 promote_lag: int = 64,
                 snapshot_chunk_bytes: int = 4 * 1024 * 1024,
                 transfer_timeout_s: float = 5.0,
                 metrics=None):
        self.node_id = node_id
        self.fs = fs
        self.rpc = rpc
        self.role = FOLLOWER
        self.term = 0
        self.voted_for: int | None = None
        self.leader_id: int | None = None
        self.election_timeout = election_timeout_ms
        self.heartbeat_ms = heartbeat_ms
        self.commit_timeout_s = commit_timeout_s
        self.promote_lag = promote_lag
        self.snapshot_chunk_bytes = max(64 * 1024, snapshot_chunk_bytes)
        self.transfer_timeout_s = transfer_timeout_s
        self.metrics = metrics
        self.pool = ConnectionPool(size=1, timeout_ms=2_000)
        # --- membership (boot config; superseded by journaled raft_conf
        # entries the moment one exists) ---
        # voters includes self; `peers` (the ctor arg) excludes self
        if learner:
            self.voters: dict[int, str] = dict(peers)
            self.learners: dict[int, str] = {node_id: self_addr}
        else:
            self.voters = dict(peers)
            self.voters[node_id] = self_addr
            self.learners = {}
        self.conf_ver = 0
        self.removed = False
        # seq of the in-flight config entry; a second change is refused
        # until it commits (single-server-change rule)
        self._conf_seq: int | None = None
        self._transferring = False
        self._last_heard = 0.0
        self._bg: list[asyncio.Task] = []
        # leader-term replication loops, torn down at step-down/reconfig
        self._repl_tasks: list[asyncio.Task] = []
        self._repl_queues: dict[int, asyncio.Queue] = {}
        # commit tracking (leader): member id -> highest acked seq
        self.match: dict[int, int] = {}
        self.commit_seq = 0
        self._commit_waiters: list[tuple[int, asyncio.Future]] = []
        # in-progress chunked snapshot receive (follower side)
        self._snap_rx: dict | None = None
        # last adopted config, persisted beside term/voted_for: a KV-mode
        # restart may neither replay the raft_conf entry (compacted away)
        # nor see it in a mem snapshot, so the hard-state file is the
        # always-there recovery path for membership
        self._hs_conf: dict | None = None
        # persisted hard state (term, voted_for): raft_node.rs parity
        self._state_path = os.path.join(
            state_dir or (fs.journal.dir if fs.journal else "."),
            "raft_hard_state")
        self._load_hard_state()
        rpc.register(RpcCode.RAFT_VOTE, self._h_vote)
        rpc.register(RpcCode.RAFT_PREVOTE, self._h_prevote)
        rpc.register(RpcCode.RAFT_APPEND, self._h_append)
        rpc.register(RpcCode.RAFT_SNAPSHOT, self._h_snapshot)
        rpc.register(RpcCode.RAFT_SNAPSHOT_CHUNK, self._h_snapshot_chunk)
        rpc.register(RpcCode.RAFT_TIMEOUT_NOW, self._h_timeout_now)
        rpc.register(RpcCode.RAFT_STATUS, self._h_status)

    # ---------------- hard state ----------------

    def _load_hard_state(self) -> None:
        try:
            with open(self._state_path, "rb") as f:
                d = msgpack.unpackb(f.read(), raw=False)
            self.term = d.get("term", 0)
            self.voted_for = d.get("voted_for")
            self._hs_conf = d.get("conf")
        except (FileNotFoundError, ValueError, msgpack.UnpackException):
            pass
        if self.fs.journal is not None:
            self.fs.journal.term = self.term

    def _save_hard_state(self) -> None:
        """fsync'd before any vote/step-up takes effect: a restarted node
        must never vote twice in one term or regress its term."""
        tmp = self._state_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({"term": self.term,
                                   "voted_for": self.voted_for,
                                   "conf": self._hs_conf}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path)
        if self.fs.journal is not None:
            self.fs.journal.term = self.term

    # ---------------- membership ----------------

    @property
    def peers(self) -> dict[int, str]:
        """Replication/communication targets: every member — voter or
        learner — except self. (Compat view: pre-membership code indexed
        a static peers dict; it now tracks the live config.)"""
        out = dict(self.voters)
        out.update(self.learners)
        out.pop(self.node_id, None)
        return out

    def _voter_peers(self) -> dict[int, str]:
        return {pid: a for pid, a in self.voters.items()
                if pid != self.node_id}

    def _addr_of(self, nid: int | None) -> str:
        if nid is None:
            return ""
        return self.voters.get(nid) or self.learners.get(nid) or ""

    def _adopt_config(self, cfg: dict | None) -> None:
        """Make a journaled ``raft_conf`` entry the active config.
        Called when the leader appends one (on_mutation), when a follower
        applies one (_h_append), after a snapshot install, and at boot
        from the recovered ``fs.raft_conf``. The config takes effect when
        APPENDED, not when committed (raft single-server-change rule)."""
        if not cfg:
            return
        ver = int(cfg.get("ver", 0))
        if ver < self.conf_ver:
            return
        self.conf_ver = ver
        self.voters = {int(k): v for k, v in (cfg.get("voters") or {}).items()}
        self.learners = {int(k): v
                         for k, v in (cfg.get("learners") or {}).items()}
        self._hs_conf = {"ver": ver,
                         "voters": {str(k): v
                                    for k, v in self.voters.items()},
                         "learners": {str(k): v
                                      for k, v in self.learners.items()}}
        self._save_hard_state()     # membership must survive restarts too
        if self.node_id not in self.voters and \
                self.node_id not in self.learners:
            if not self.removed:
                log.info("node %d: removed from the cluster config (ver %d)",
                         self.node_id, ver)
            self.removed = True
            if self.role == LEADER:
                self._step_down(self.term)
            self.role = FOLLOWER
        else:
            self.removed = False
        if self.role == LEADER:
            self._reconcile_replication()
            self._advance_commit()

    def _reconcile_replication(self) -> None:
        """Leader: align per-member replication loops with the active
        config — spawn queues/loops for new members, retire loops for
        removed ones (their loop notices its queue was unhooked)."""
        targets = self.peers
        for pid, addr in targets.items():
            if pid not in self._repl_queues:
                self._repl_queues[pid] = asyncio.Queue()
                self.match.setdefault(pid, 0)
                self._repl_tasks.append(asyncio.ensure_future(
                    self._replicate_loop(pid, addr)))
        for pid in list(self._repl_queues):
            if pid not in targets:
                # the removal config entry was queued for this member
                # just before adoption — keep its loop hooked for a few
                # heartbeats so the farewell append is actually sent and
                # the removed node learns to stand down; its ack can no
                # longer move commit (it left the voter set already)
                self._repl_tasks.append(asyncio.ensure_future(
                    self._retire_member(pid, self._repl_queues[pid])))
        self._repl_tasks = [t for t in self._repl_tasks if not t.done()]

    async def _retire_member(self, pid: int, q: asyncio.Queue) -> None:
        await asyncio.sleep(self.heartbeat_ms * 4 / 1000)
        if self._repl_queues.get(pid) is q and pid not in self.peers:
            self._repl_queues.pop(pid, None)
            self.match.pop(pid, None)

    def propose_member_change(self, action: str, target_id: int,
                              addr: str = "") -> dict:
        """Leader-only: append a single-server config change to the
        journal. One change at a time: a proposal while the previous
        config entry is uncommitted is refused (retryable IN_PROGRESS)."""
        self.check_leader()
        if self._conf_seq is not None and self._conf_seq > self.commit_seq:
            raise err.CapacityPending(
                "a membership change is already in flight "
                f"(seq {self._conf_seq} > commit {self.commit_seq})")
        action = str(action).lower()
        target_id = int(target_id)
        voters, learners = dict(self.voters), dict(self.learners)
        if action in ("add", "add_learner"):
            if not addr:
                raise err.InvalidArgument(
                    "add requires the new node's host:port")
            if target_id in voters or target_id in learners:
                raise err.InvalidArgument(
                    f"node {target_id} is already a member")
            learners[target_id] = addr
            action = "add_learner"
        elif action == "promote":
            if target_id not in learners:
                raise err.InvalidArgument(
                    f"node {target_id} is not a learner")
            voters[target_id] = learners.pop(target_id)
        elif action == "remove":
            if target_id == self.node_id:
                raise err.InvalidArgument(
                    "cannot remove the leader; transfer leadership first")
            if target_id in voters:
                voters.pop(target_id)
            elif target_id in learners:
                learners.pop(target_id)
            else:
                raise err.InvalidArgument(
                    f"node {target_id} is not a member")
        else:
            raise err.InvalidArgument(
                f"unknown membership action {action!r}")
        args = {"ver": self.conf_ver + 1,
                "voters": {str(k): v for k, v in voters.items()},
                "learners": {str(k): v for k, v in learners.items()},
                "action": action, "target": target_id}
        log.info("node %d: proposing %s of node %d (conf ver %d -> %d)",
                 self.node_id, action, target_id, self.conf_ver,
                 args["ver"])
        self.fs._log("raft_conf", args)
        self._conf_seq = self.last_seq()
        if self.metrics is not None:
            self.metrics.inc("raft.member_changes")
        return args

    async def _membership_loop(self) -> None:
        """Metrics tick + learner auto-promotion: once a learner's match
        lag drops below promote_lag it is proposed as a voter — by then
        promoting it cannot stall the cluster behind a cold replica."""
        while True:
            await asyncio.sleep(max(self.heartbeat_ms * 2, 40) / 1000)
            self._metrics_tick()
            if (self.role != LEADER or not self.learners
                    or self._transferring):
                continue
            if self._conf_seq is not None and \
                    self._conf_seq > self.commit_seq:
                continue
            for pid in sorted(self.learners):
                m = self.match.get(pid, 0)
                if m > 0 and self.last_seq() - m <= self.promote_lag:
                    try:
                        self.propose_member_change("promote", pid)
                    except err.CurvineError as e:
                        log.debug("auto-promote of %d refused: %s", pid, e)
                    break

    def _metrics_tick(self) -> None:
        m = self.metrics
        if m is None:
            return
        m.gauge("raft.role", _ROLE_GAUGE.get(self.role, 0))
        m.gauge("raft.term", self.term)
        m.gauge("raft.commit_seq", self.commit_seq)
        m.gauge("raft.conf_ver", self.conf_ver)
        m.gauge("raft.voters", len(self.voters))
        m.gauge("raft.learners", len(self.learners))
        if self.role == LEADER:
            last = self.last_seq()
            for pid, mseq in self.match.items():
                m.gauge(f"raft.match_lag.{pid}", max(0, last - mseq))

    # ---------------- lifecycle ----------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    @property
    def quorum(self) -> int:
        return len(self.voters) // 2 + 1

    def last_seq(self) -> int:
        return self.fs.journal.seq if self.fs.journal else 0

    def last_term(self) -> int:
        return self.fs.journal.last_term if self.fs.journal else 0

    async def start(self) -> None:
        self._touch()
        # a journaled config recovered from the hard-state file or from
        # snapshot/WAL replay overrides the boot config (fs.recover()
        # ran before us); ver ordering picks the newest
        self._adopt_config(self._hs_conf)
        self._adopt_config(getattr(self.fs, "raft_conf", None))
        self._bg.append(asyncio.ensure_future(self._election_loop()))
        self._bg.append(asyncio.ensure_future(self._membership_loop()))

    async def stop(self) -> None:
        # Demote BEFORE cancelling: asyncio.wait_for swallows a
        # cancellation that races its inner future completing
        # (bpo-37658), and a replicate loop whose queue is hot mid-storm
        # hits that race routinely — the cancel is lost and a ZOMBIE
        # leader keeps heartbeating, suppressing every election on the
        # survivors. The role flip ends the `while self.role == LEADER`
        # loops regardless, and awaiting the tasks proves they exited.
        self.role = FOLLOWER
        tasks = list(self._bg) + list(self._repl_tasks)
        self._bg.clear()
        self._repl_tasks.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._fail_waiters(err.NotLeader("shutting down"))
        await self.pool.close()

    def _touch(self) -> None:
        self._last_heard = asyncio.get_event_loop().time()

    # ---------------- election ----------------

    async def _election_loop(self) -> None:
        while True:
            timeout = random.uniform(*self.election_timeout) / 1000
            await asyncio.sleep(timeout / 4)
            if self.role == LEADER:
                continue
            if self.removed or self.node_id not in self.voters:
                continue        # learners/removed nodes never elect
            now = asyncio.get_event_loop().time()
            if now - self._last_heard < timeout:
                continue
            await self._run_election()

    async def _run_prevote(self) -> bool:
        """Pre-vote round (raft §9.6): ask voters whether they WOULD
        grant a vote for term+1, without bumping our term or persisting
        anything. Peers that heard from a live leader recently refuse, so
        a partitioned node retrying elections forever keeps its term
        frozen — when the partition heals it rejoins as a follower
        instead of deposing the healthy leader with an inflated term."""
        term = self.term + 1

        async def ask(addr: str) -> bool:
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_PREVOTE, data=pack({
                    "term": term, "candidate": self.node_id,
                    "last_seq": self.last_seq(),
                    "last_term": self.last_term()}), timeout=1.0)
                body = unpack(rep.data) or {}
                return bool(body.get("granted"))
            except Exception:
                return False

        votes = 1                         # our own
        tasks = [asyncio.ensure_future(ask(addr))
                 for addr in self._voter_peers().values()]
        try:
            for fut in asyncio.as_completed(tasks):
                if await fut:
                    votes += 1
                if votes >= self.quorum:
                    return True
        finally:
            for t in tasks:
                t.cancel()
        return votes >= self.quorum

    async def _run_election(self, force: bool = False) -> None:
        if self.removed or self.node_id not in self.voters:
            return
        # TIMEOUT_NOW (leader transfer) skips pre-vote: the live leader
        # asked us to depose it, so "heard from a leader recently" must
        # not veto the election
        if (not force and self._voter_peers()
                and not await self._run_prevote()):
            log.debug("node %d: pre-vote failed (term %d stays)",
                      self.node_id, self.term)
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.node_id
        self._save_hard_state()
        self.leader_id = None
        votes = 1
        if self.metrics is not None:
            self.metrics.inc("raft.elections")
        log.info("node %d: starting election term %d (last=%d/t%d)",
                 self.node_id, self.term, self.last_seq(), self.last_term())

        async def ask(pid: int, addr: str) -> bool:
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_VOTE, data=pack({
                    "term": self.term, "candidate": self.node_id,
                    "last_seq": self.last_seq(),
                    "last_term": self.last_term()}), timeout=1.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                return bool(body.get("granted"))
            except Exception:
                return False

        # Tally votes as they land: waiting on slow/dead peers must not
        # delay a quorum win (a rival's next-term request would demote us
        # first and elections would live-lock).
        term_at_start = self.term
        tasks = [asyncio.ensure_future(ask(pid, addr))
                 for pid, addr in self._voter_peers().items()]
        try:
            for fut in asyncio.as_completed(tasks):
                granted = await fut
                if self.role != CANDIDATE or self.term != term_at_start:
                    return
                if granted:
                    votes += 1
                if votes >= self.quorum:
                    await self._become_leader()
                    return
        finally:
            for t in tasks:
                t.cancel()
        if self.role == CANDIDATE:
            self.role = FOLLOWER
            self._touch()

    def _step_down(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._save_hard_state()
        if self.role == LEADER:
            log.info("node %d: stepping down in term %d", self.node_id, term)
            for t in self._repl_tasks:
                t.cancel()
            self._repl_tasks.clear()
            self._repl_queues = {}
            self.match = {}
            self._conf_seq = None
            self._fail_waiters(err.NotLeader("deposed"))
        self.role = FOLLOWER
        self._touch()

    async def _become_leader(self) -> None:
        log.info("node %d: leader for term %d (voters=%s learners=%s)",
                 self.node_id, self.term, sorted(self.voters),
                 sorted(self.learners))
        self.role = LEADER
        self.leader_id = self.node_id
        self._conf_seq = None
        for t in self._repl_tasks:
            t.cancel()
        self._repl_tasks = []
        targets = self.peers
        self._repl_queues = {pid: asyncio.Queue() for pid in targets}
        self.match = {pid: 0 for pid in targets}
        self.commit_seq = self.last_seq() if not targets else 0
        for pid, addr in targets.items():
            self._repl_tasks.append(asyncio.ensure_future(
                self._replicate_loop(pid, addr)))
        if targets and self.fs.journal is not None:
            # term-opening no-op (raft §5.4.2): gives the new term an entry
            # that CAN be committed by counting, which transitively commits
            # every prior-term entry beneath it
            try:
                self.fs._log("noop", {})
            except err.CurvineError:
                pass

    # ---------------- commit tracking (leader) ----------------

    def _advance_commit(self) -> None:
        # only VOTERS count toward commit; learners replicate but their
        # acks can never move the commit point
        acked = sorted([self.last_seq()] +
                       [self.match.get(pid, 0)
                        for pid in self._voter_peers()],
                       reverse=True)
        new_commit = acked[min(self.quorum, len(acked)) - 1]
        # Raft commit restriction: only entries of the CURRENT term may be
        # committed by replica counting (figure-8 unsafety otherwise). The
        # no-op appended at _become_leader makes this reachable right away;
        # committing a current-term entry commits everything before it.
        if new_commit > self.commit_seq:
            t = (self.fs.journal.term_of(new_commit)
                 if self.fs.journal else self.term)
            if t != self.term:
                return
            self.commit_seq = new_commit
            still = []
            for seq, fut in self._commit_waiters:
                if fut.done():
                    continue        # timed-out/cancelled waiter: prune
                if seq <= self.commit_seq:
                    fut.set_result(True)
                else:
                    still.append((seq, fut))
            self._commit_waiters = still

    def _fail_waiters(self, exc: Exception) -> None:
        for _seq, fut in self._commit_waiters:
            if not fut.done():
                fut.set_exception(exc)
        self._commit_waiters = []

    async def wait_committed(self, seq: int | None = None,
                             deadline=None) -> None:
        """Block until ``seq`` (default: the journal head) is replicated
        on a quorum. This is what makes a client ack mean 'durable on a
        majority' (raft commit rule). A caller-propagated deadline caps
        the wait below the configured commit timeout."""
        if not self.peers:
            return
        if self.role != LEADER:
            raise err.NotLeader(f"node {self.node_id} is {self.role}")
        seq = self.last_seq() if seq is None else seq
        if seq <= self.commit_seq:
            return
        fut = asyncio.get_event_loop().create_future()
        waiter = (seq, fut)
        self._commit_waiters.append(waiter)
        try:
            await asyncio.wait_for(fut, (deadline.cap(self.commit_timeout_s)
                                         if deadline is not None
                                         else self.commit_timeout_s))
        except asyncio.TimeoutError:
            raise err.RpcTimeout(
                f"seq {seq} not committed on a quorum within "
                f"{self.commit_timeout_s:.1f}s") from None
        finally:
            # a timed-out or cancelled waiter must not linger until its
            # seq commits (or forever, on a deposed leader) — wait_for
            # leaves the future done (cancelled) in both cases
            try:
                self._commit_waiters.remove(waiter)
            except ValueError:
                pass                # already released by _advance_commit

    # ---------------- replication (leader) ----------------

    def on_mutation(self, seq: int, op: str, args: dict,
                    term: int = 0) -> None:
        """Called by MasterFilesystem._log after a local apply+journal."""
        if self.role != LEADER:
            return
        for q in self._repl_queues.values():
            q.put_nowait((seq, op, args, term))
        if op == "raft_conf":
            # the new config takes effect when APPENDED (queued above so
            # members — including one being removed — still receive it)
            self._adopt_config(args)
        if len(self.voters) <= 1:
            # sole voter (possibly with learners): quorum is self
            self._advance_commit()

    async def _replicate_loop(self, pid: int, addr: str) -> None:
        """Per-follower/learner: heartbeats + journal entry stream +
        catch-up. Exits when deposed or when the member leaves the
        active config (its queue is unhooked by _reconcile_replication)."""
        q = self._repl_queues.get(pid)
        if q is None:
            return
        while self.role == LEADER and self._repl_queues.get(pid) is q:
            batch: list = []
            try:
                entry = await asyncio.wait_for(
                    q.get(), self.heartbeat_ms / 1000)
                batch.append(entry)
                size = _rough_size(entry)
                while (not q.empty() and len(batch) < 256
                       and size < _BATCH_SOFT_BYTES):
                    nxt = q.get_nowait()
                    batch.append(nxt)
                    size += _rough_size(nxt)
            except asyncio.TimeoutError:
                pass          # heartbeat
            if self.role != LEADER or self._repl_queues.get(pid) is not q:
                return
            try:
                conn = await self.pool.get(addr)
                prev_seq = batch[0][0] - 1 if batch else self.last_seq()
                prev_term = (self.fs.journal.term_of(prev_seq)
                             if self.fs.journal else 0)
                if prev_term is None:
                    # predecessor term fell out of the retained window:
                    # can't prove log matching — snapshot catch-up instead
                    await self._send_snapshot(pid, addr)
                    for entry in batch:
                        q.put_nowait(entry)
                    continue
                rep = await conn.call(RpcCode.RAFT_APPEND, data=pack({
                    "term": self.term, "leader": self.node_id,
                    "entries": [[s, o, a, t] for s, o, a, t in batch],
                    "prev_seq": prev_seq, "prev_term": prev_term,
                    "leader_seq": self.last_seq(),
                    "leader_last_term": self.last_term()}), timeout=2.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                    return
                if body.get("need_snapshot"):
                    # divergent/lagging log: its applied_seq must NOT
                    # count toward commit (same seq, different history)
                    await self._send_snapshot(pid, addr)
                elif pid in self.match:
                    self.match[pid] = max(self.match.get(pid, 0),
                                          body.get("applied_seq", 0))
                    self._advance_commit()
            except Exception as e:
                log.debug("replicate to %d failed: %s", pid, e)
                # requeue the batch IN SEQ ORDER ahead of anything enqueued
                # meanwhile — tail-requeueing would make the next batch
                # start past the follower's head and escalate a transient
                # blip into a full snapshot install
                pending = list(batch)
                while not q.empty():
                    pending.append(q.get_nowait())
                pending.sort(key=lambda entry: entry[0])
                for entry in pending:
                    q.put_nowait(entry)
                await asyncio.sleep(0.2)

    async def _send_snapshot(self, pid: int, addr: str) -> None:
        """Chunked snapshot install: the state streams as bounded
        RAFT_SNAPSHOT_CHUNK frames (resumable — the follower replies how
        many chunks it holds, the leader continues from there) with a
        whole-blob CRC verified before install. A namespace bigger than
        MAX_FRAME can therefore still catch a follower up, which the
        monolithic RAFT_SNAPSHOT blob never could."""
        state = self.fs._snapshot_state()
        seq, lterm = self.last_seq(), self.last_term()
        blob = msgpack.packb({"state": state}, use_bin_type=True)
        crc = zlib.crc32(blob)
        csize = self.snapshot_chunk_bytes
        total = max(1, (len(blob) + csize - 1) // csize)
        # deterministic stream id: a retransmit after a leader blip
        # resumes the same stream instead of restarting from chunk 0
        sid = f"{self.node_id}.{self.term}.{seq}"
        conn = await self.pool.get(addr)
        i, applied, stalls = 0, 0, 0
        while i < total:
            rep = await conn.call(
                RpcCode.RAFT_SNAPSHOT_CHUNK, data=msgpack.packb({
                    "term": self.term, "leader": self.node_id, "sid": sid,
                    "seq": seq, "last_term": lterm, "idx": i,
                    "total": total, "crc": crc,
                    "data": blob[i * csize:(i + 1) * csize]},
                    use_bin_type=True), timeout=10.0)
            body = unpack(rep.data) or {}
            if body.get("term", 0) > self.term:
                self._step_down(body["term"])
                return
            if self.metrics is not None:
                self.metrics.inc("raft.snapshot_chunks_sent")
            have = int(body.get("have", i + 1))
            applied = max(applied, int(body.get("applied_seq", 0)))
            if have <= i:
                # follower restarted (crc mismatch / new stream) or is
                # rewinding us; bounded so a broken peer can't spin here
                stalls += 1
                if stalls > 3:
                    raise err.AbnormalData(
                        f"snapshot stream to node {pid} not progressing "
                        f"(chunk {i}, follower has {have})")
                i = max(0, have)
                continue
            i = have
        if pid in self.match:
            self.match[pid] = max(self.match.get(pid, 0), applied)
            self._advance_commit()
        log.info("snapshot (seq=%d, %d chunk(s), %.1f MiB) sent to %s",
                 seq, total, len(blob) / 1048576, addr)

    # ---------------- leader transfer ----------------

    async def transfer_leadership(self, target: int | None = None) -> int:
        """Graceful handoff (`cv raft transfer`): pause new writes, drain
        the log to the target voter, then send TIMEOUT_NOW so it elects
        immediately — bounded failover with no election-timeout gap."""
        self.check_leader()
        candidates = self._voter_peers()
        if not candidates:
            raise err.InvalidArgument("no other voter to transfer to")
        if target is None:
            # most-caught-up voter
            target = max(candidates,
                         key=lambda pid: self.match.get(pid, 0))
        target = int(target)
        if target not in candidates:
            raise err.InvalidArgument(
                f"node {target} is not a transferable voter")
        addr = candidates[target]
        loop = asyncio.get_event_loop()
        give_up = loop.time() + self.transfer_timeout_s
        log.info("node %d: transferring leadership to %d (%s)",
                 self.node_id, target, addr)
        self._transferring = True
        try:
            while self.match.get(target, 0) < self.last_seq():
                if self.role != LEADER:
                    raise err.NotLeader("deposed during transfer")
                if loop.time() > give_up:
                    raise err.RpcTimeout(
                        f"transfer: node {target} did not catch up within "
                        f"{self.transfer_timeout_s:.1f}s")
                await asyncio.sleep(0.01)
            conn = await self.pool.get(addr)
            await conn.call(RpcCode.RAFT_TIMEOUT_NOW, data=pack({
                "term": self.term, "leader": self.node_id,
                "target": target}), timeout=2.0)
            while self.role == LEADER:
                if loop.time() > give_up:
                    raise err.RpcTimeout(
                        f"transfer: node {target} did not take over within "
                        f"{self.transfer_timeout_s:.1f}s")
                await asyncio.sleep(0.01)
        finally:
            self._transferring = False
        if self.metrics is not None:
            self.metrics.inc("raft.leader_transfers")
        return target

    # ---------------- handlers (follower) ----------------

    async def _h_vote(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term, candidate = q["term"], q["candidate"]
        cand_log = (q.get("last_term", 0), q["last_seq"])
        if term > self.term:
            self._step_down(term)
        granted = (term >= self.term
                   and candidate in self.voters   # removed/learner: refuse
                   and self.voted_for in (None, candidate)
                   and cand_log >= (self.last_term(), self.last_seq()))
        if granted:
            self.voted_for = candidate
            self._save_hard_state()       # fsync BEFORE the vote leaves
            self._touch()
        return {}, pack({"granted": granted, "term": self.term})

    async def _h_prevote(self, msg: Message, conn: ServerConn):
        """Grant iff we would plausibly vote for the candidate in a real
        election at that term AND we have NOT heard from a live leader
        within the minimum election timeout. Grants are stateless: no
        term bump, no voted_for persistence, no timer reset — a pre-vote
        round can never disturb a healthy cluster."""
        q = unpack(msg.data) or {}
        cand_log = (q.get("last_term", 0), q.get("last_seq", 0))
        now = asyncio.get_event_loop().time()
        heard_recently = (now - self._last_heard) < \
            (self.election_timeout[0] / 1000)
        granted = (self.role != LEADER          # a live leader never grants
                   and not heard_recently
                   and q.get("candidate") in self.voters
                   and q.get("term", 0) > self.term
                   and cand_log >= (self.last_term(), self.last_seq()))
        return {}, pack({"granted": granted, "term": self.term})

    async def _h_append(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term = q["term"]
        if term < self.term:
            return {}, pack({"term": self.term, "applied_seq": self.last_seq()})
        if term > self.term or self.role != FOLLOWER:
            self._step_down(term)
        self.leader_id = q["leader"]
        self._touch()
        need_snapshot = False
        entries = q.get("entries", [])
        if entries:
            # log-matching: our entry at prev_seq must carry prev_term —
            # a deposed leader with divergent history at the same seqs
            # fails this and heals via snapshot install
            prev_seq = q.get("prev_seq", entries[0][0] - 1)
            if prev_seq <= self.last_seq():
                ours = (self.fs.journal.term_of(prev_seq)
                        if self.fs.journal else 0)
                if ours is None or ours != q.get("prev_term", 0):
                    need_snapshot = True
        # collect the contiguous suffix of new entries, then journal +
        # apply them as ONE batch (one follower-side flush per RPC — the
        # follower half of group commit)
        batch: list[tuple[int, str, dict, int]] = []
        nxt = self.last_seq() + 1
        for rec in ([] if need_snapshot else entries):
            seq, op, args = rec[0], rec[1], rec[2]
            eterm = rec[3] if len(rec) > 3 else 0
            if seq < nxt:
                continue                      # already have it
            if seq != nxt:
                need_snapshot = True          # gap: ask for catch-up
                batch = []
                break
            batch.append((seq, op, args, eterm))
            nxt += 1
        if batch:
            self.fs.apply_replicated_batch(batch)
            for _seq, op, cargs, _eterm in batch:
                if op == "raft_conf":
                    # effective when appended — also on followers (this
                    # is how a removed node learns to stand down and a
                    # promoted learner learns it may elect)
                    self._adopt_config(cargs)
        # log-matching check: same head seq must mean same head term; a
        # follower that diverged (e.g. deposed leader with extra applied
        # entries, or a different term at the same seq) takes a snapshot
        # install, which REPLACES its state machine wholesale.
        if not need_snapshot:
            if q.get("leader_seq", 0) > self.last_seq():
                need_snapshot = True
            elif self.last_seq() > q.get("leader_seq", 0):
                need_snapshot = True          # we have entries leader lacks
            elif (q.get("leader_seq", 0) == self.last_seq()
                  and q.get("leader_last_term", 0) != self.last_term()):
                need_snapshot = True
        return {}, pack({"term": self.term, "applied_seq": self.last_seq(),
                         "need_snapshot": need_snapshot})

    def _snapshot_is_stale(self, snap_term: int, snap_seq: int) -> bool:
        """True when our log is already at/past the snapshot point — a
        delayed retransmit or duplicate install must be ACKED without
        REPLACING newer state (same up-to-date rule the vote check uses)."""
        return (self.last_term(), self.last_seq()) >= (snap_term, snap_seq)

    async def _h_snapshot(self, msg: Message, conn: ServerConn):
        """Legacy monolithic install (pre-chunking peers); new leaders
        send RAFT_SNAPSHOT_CHUNK streams instead."""
        q = msgpack.unpackb(bytes(msg.data), raw=False, strict_map_key=False)
        if q["term"] < self.term:
            return {}, pack({"term": self.term})
        self._touch()
        if self._snapshot_is_stale(q.get("last_term", 0), q["seq"]):
            return {}, pack({"term": self.term,
                             "applied_seq": self.last_seq(),
                             "skipped": True})
        self.fs.install_snapshot(q["state"], q["seq"],
                                 q.get("last_term", 0))
        self._adopt_config(getattr(self.fs, "raft_conf", None))
        if self.metrics is not None:
            self.metrics.inc("raft.snapshot_installs")
        log.info("node %d: installed snapshot at seq %d", self.node_id,
                 q["seq"])
        return {}, pack({"term": self.term, "applied_seq": self.last_seq()})

    async def _h_snapshot_chunk(self, msg: Message, conn: ServerConn):
        """One bounded piece of a snapshot stream. Replies ``have`` (how
        many chunks we hold) so the leader can resume/rewind; the final
        chunk triggers CRC verification + install. Stale streams — our
        log already at/past the snapshot point — are acked as complete
        without installing."""
        q = msgpack.unpackb(bytes(msg.data), raw=False, strict_map_key=False)
        if q["term"] < self.term:
            return {}, pack({"term": self.term, "have": 0,
                             "applied_seq": self.last_seq()})
        if q["term"] > self.term or self.role not in (FOLLOWER,):
            self._step_down(q["term"])
        self.leader_id = q["leader"]
        self._touch()
        total = int(q["total"])
        if self._snapshot_is_stale(q.get("last_term", 0), q["seq"]):
            self._snap_rx = None
            return {}, pack({"term": self.term, "have": total,
                             "applied_seq": self.last_seq(),
                             "skipped": True})
        rx = self._snap_rx
        if rx is None or rx["sid"] != q["sid"]:
            rx = self._snap_rx = {"sid": q["sid"], "parts": [],
                                  "total": total}
        idx = int(q["idx"])
        if idx == len(rx["parts"]):
            rx["parts"].append(bytes(q["data"]))
        have = len(rx["parts"])
        if have < rx["total"]:
            return {}, pack({"term": self.term, "have": have,
                             "applied_seq": self.last_seq()})
        blob = b"".join(rx["parts"])
        self._snap_rx = None
        if zlib.crc32(blob) != q.get("crc", 0):
            log.warning("node %d: snapshot stream %s failed CRC, "
                        "restarting", self.node_id, q["sid"])
            return {}, pack({"term": self.term, "have": 0,
                             "applied_seq": self.last_seq()})
        body = msgpack.unpackb(blob, raw=False, strict_map_key=False)
        self.fs.install_snapshot(body["state"], q["seq"],
                                 q.get("last_term", 0))
        self._adopt_config(getattr(self.fs, "raft_conf", None))
        if self.metrics is not None:
            self.metrics.inc("raft.snapshot_installs")
        log.info("node %d: installed chunked snapshot at seq %d "
                 "(%d chunks, %.1f MiB)", self.node_id, q["seq"], have,
                 len(blob) / 1048576)
        return {}, pack({"term": self.term, "have": have,
                         "applied_seq": self.last_seq()})

    async def _h_timeout_now(self, msg: Message, conn: ServerConn):
        """Leader-transfer trigger: elect immediately, skipping pre-vote
        (the live leader itself asked to be deposed)."""
        q = unpack(msg.data) or {}
        accepted = (q.get("term", 0) >= self.term
                    and self.node_id in self.voters
                    and not self.removed
                    and self.role != LEADER)
        if accepted:
            self._touch()
            if self.metrics is not None:
                self.metrics.inc("raft.timeout_now")
            self._bg = [t for t in self._bg if not t.done()]
            self._bg.append(asyncio.ensure_future(
                self._run_election(force=True)))
        return {}, pack({"accepted": accepted, "term": self.term})

    async def _h_status(self, msg: Message, conn: ServerConn):
        """RAFT_STATUS: answers on ANY node (followers included) — the
        member-discovery RPC for clients, `cv raft status` and /api/raft."""
        return {}, pack(self.status())

    def status(self) -> dict:
        inflight = (self._conf_seq is not None
                    and self._conf_seq > self.commit_seq)
        return {
            "node_id": self.node_id,
            "role": self.role,
            "term": self.term,
            "leader_id": self.leader_id,
            "leader_addr": self._addr_of(self.leader_id),
            "commit_seq": self.commit_seq,
            "last_seq": self.last_seq(),
            "conf_ver": self.conf_ver,
            "voters": {str(k): self.voters[k] for k in sorted(self.voters)},
            "learners": {str(k): self.learners[k]
                         for k in sorted(self.learners)},
            "match": ({str(k): v for k, v in sorted(self.match.items())}
                      if self.role == LEADER else {}),
            "removed": self.removed,
            "transferring": self._transferring,
            "inflight_change": bool(inflight),
        }

    # ---------------- client gate ----------------

    def check_leader(self) -> None:
        if self.role == LEADER and not self._transferring:
            return
        if self.role == LEADER:
            e = err.NotLeader(
                f"node {self.node_id}: leadership transfer in progress")
        else:
            e = err.NotLeader(
                f"node {self.node_id} is {self.role}; "
                f"leader is {self.leader_id}")
            hint = self._addr_of(self.leader_id)
            if hint:
                e.leader_hint = hint
        members = [a for a in self.voters.values() if a]
        if members:
            e.members = members
        raise e
