"""Master HA: raft-lite journal replication.

Parity: curvine-common/src/raft/ (raft_node, raft_journal, snapshot/) —
the reference replicates master metadata through the raft crate. This is
a compact re-implementation over our RPC fabric with the same observable
guarantees:

* leader election with persisted hard state (term + voted_for survive
  restarts, so a node cannot double-vote in the same term);
* log matching: every entry carries its term; AppendEntries carries the
  predecessor's (seq, term) and followers reject mismatches, falling back
  to a full snapshot install (which REPLACES follower state — the correct
  recovery for a follower whose state machine already applied divergent
  entries, since applies here are not undoable);
* commit-after-majority: client-visible acks wait until the entry's seq
  is replicated on a quorum (`wait_committed`), closing the acked-write-
  loss window the round-1/2 design documented.

The leader still applies locally before replicating (reference applies on
commit; here applies are deterministic and a deposed leader's extra
applied entries are healed by snapshot install from the new leader).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random

import msgpack

from curvine_tpu.common import errors as err
from curvine_tpu.rpc import Message, RpcCode, RpcServer, ServerConn
from curvine_tpu.rpc.client import ConnectionPool
from curvine_tpu.rpc.frame import pack, unpack

log = logging.getLogger(__name__)

FOLLOWER, CANDIDATE, LEADER = "follower", "candidate", "leader"


class RaftLite:
    def __init__(self, node_id: int, peers: dict[int, str], fs,
                 rpc: RpcServer, election_timeout_ms: tuple[int, int] =
                 (600, 1200), heartbeat_ms: int = 150,
                 state_dir: str | None = None,
                 commit_timeout_s: float = 10.0):
        self.node_id = node_id
        self.peers = dict(peers)            # id -> addr (excluding self)
        self.fs = fs
        self.rpc = rpc
        self.role = FOLLOWER
        self.term = 0
        self.voted_for: int | None = None
        self.leader_id: int | None = None
        self.election_timeout = election_timeout_ms
        self.heartbeat_ms = heartbeat_ms
        self.commit_timeout_s = commit_timeout_s
        self.pool = ConnectionPool(size=1, timeout_ms=2_000)
        self._last_heard = 0.0
        self._bg: list[asyncio.Task] = []
        self._repl_queues: dict[int, asyncio.Queue] = {}
        # commit tracking (leader): follower id -> highest acked seq
        self.match: dict[int, int] = {}
        self.commit_seq = 0
        self._commit_waiters: list[tuple[int, asyncio.Future]] = []
        # persisted hard state (term, voted_for): raft_node.rs parity
        self._state_path = os.path.join(
            state_dir or (fs.journal.dir if fs.journal else "."),
            "raft_hard_state")
        self._load_hard_state()
        rpc.register(RpcCode.RAFT_VOTE, self._h_vote)
        rpc.register(RpcCode.RAFT_PREVOTE, self._h_prevote)
        rpc.register(RpcCode.RAFT_APPEND, self._h_append)
        rpc.register(RpcCode.RAFT_SNAPSHOT, self._h_snapshot)

    # ---------------- hard state ----------------

    def _load_hard_state(self) -> None:
        try:
            with open(self._state_path, "rb") as f:
                d = msgpack.unpackb(f.read(), raw=False)
            self.term = d.get("term", 0)
            self.voted_for = d.get("voted_for")
        except (FileNotFoundError, ValueError, msgpack.UnpackException):
            pass
        if self.fs.journal is not None:
            self.fs.journal.term = self.term

    def _save_hard_state(self) -> None:
        """fsync'd before any vote/step-up takes effect: a restarted node
        must never vote twice in one term or regress its term."""
        tmp = self._state_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({"term": self.term,
                                   "voted_for": self.voted_for}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._state_path)
        if self.fs.journal is not None:
            self.fs.journal.term = self.term

    # ---------------- lifecycle ----------------

    @property
    def is_leader(self) -> bool:
        return self.role == LEADER

    @property
    def quorum(self) -> int:
        return (len(self.peers) + 1) // 2 + 1

    def last_seq(self) -> int:
        return self.fs.journal.seq if self.fs.journal else 0

    def last_term(self) -> int:
        return self.fs.journal.last_term if self.fs.journal else 0

    async def start(self) -> None:
        self._touch()
        self._bg.append(asyncio.ensure_future(self._election_loop()))

    async def stop(self) -> None:
        # Demote BEFORE cancelling: asyncio.wait_for swallows a
        # cancellation that races its inner future completing
        # (bpo-37658), and a replicate loop whose queue is hot mid-storm
        # hits that race routinely — the cancel is lost and a ZOMBIE
        # leader keeps heartbeating, suppressing every election on the
        # survivors. The role flip ends the `while self.role == LEADER`
        # loops regardless, and awaiting the tasks proves they exited.
        self.role = FOLLOWER
        tasks = list(self._bg)
        self._bg.clear()
        for t in tasks:
            t.cancel()
        for t in tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._fail_waiters(err.NotLeader("shutting down"))
        await self.pool.close()

    def _touch(self) -> None:
        self._last_heard = asyncio.get_event_loop().time()

    # ---------------- election ----------------

    async def _election_loop(self) -> None:
        while True:
            timeout = random.uniform(*self.election_timeout) / 1000
            await asyncio.sleep(timeout / 4)
            if self.role == LEADER:
                continue
            now = asyncio.get_event_loop().time()
            if now - self._last_heard < timeout:
                continue
            await self._run_election()

    async def _run_prevote(self) -> bool:
        """Pre-vote round (raft §9.6): ask peers whether they WOULD grant
        a vote for term+1, without bumping our term or persisting
        anything. Peers that heard from a live leader recently refuse, so
        a partitioned node retrying elections forever keeps its term
        frozen — when the partition heals it rejoins as a follower
        instead of deposing the healthy leader with an inflated term."""
        term = self.term + 1

        async def ask(addr: str) -> bool:
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_PREVOTE, data=pack({
                    "term": term, "candidate": self.node_id,
                    "last_seq": self.last_seq(),
                    "last_term": self.last_term()}), timeout=1.0)
                body = unpack(rep.data) or {}
                return bool(body.get("granted"))
            except Exception:
                return False

        votes = 1                         # our own
        tasks = [asyncio.ensure_future(ask(addr))
                 for addr in self.peers.values()]
        try:
            for fut in asyncio.as_completed(tasks):
                if await fut:
                    votes += 1
                if votes >= self.quorum:
                    return True
        finally:
            for t in tasks:
                t.cancel()
        return votes >= self.quorum

    async def _run_election(self) -> None:
        if self.peers and not await self._run_prevote():
            log.debug("node %d: pre-vote failed (term %d stays)",
                      self.node_id, self.term)
            return
        self.role = CANDIDATE
        self.term += 1
        self.voted_for = self.node_id
        self._save_hard_state()
        self.leader_id = None
        votes = 1
        log.info("node %d: starting election term %d (last=%d/t%d)",
                 self.node_id, self.term, self.last_seq(), self.last_term())

        async def ask(pid: int, addr: str) -> bool:
            try:
                conn = await self.pool.get(addr)
                rep = await conn.call(RpcCode.RAFT_VOTE, data=pack({
                    "term": self.term, "candidate": self.node_id,
                    "last_seq": self.last_seq(),
                    "last_term": self.last_term()}), timeout=1.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                return bool(body.get("granted"))
            except Exception:
                return False

        # Tally votes as they land: waiting on slow/dead peers must not
        # delay a quorum win (a rival's next-term request would demote us
        # first and elections would live-lock).
        term_at_start = self.term
        tasks = [asyncio.ensure_future(ask(pid, addr))
                 for pid, addr in self.peers.items()]
        try:
            for fut in asyncio.as_completed(tasks):
                granted = await fut
                if self.role != CANDIDATE or self.term != term_at_start:
                    return
                if granted:
                    votes += 1
                if votes >= self.quorum:
                    await self._become_leader()
                    return
        finally:
            for t in tasks:
                t.cancel()
        if self.role == CANDIDATE:
            self.role = FOLLOWER
            self._touch()

    def _step_down(self, term: int) -> None:
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._save_hard_state()
        if self.role == LEADER:
            log.info("node %d: stepping down in term %d", self.node_id, term)
            for t in self._bg[1:]:
                t.cancel()
            del self._bg[1:]
            self._fail_waiters(err.NotLeader("deposed"))
        self.role = FOLLOWER
        self._touch()

    async def _become_leader(self) -> None:
        log.info("node %d: leader for term %d", self.node_id, self.term)
        self.role = LEADER
        self.leader_id = self.node_id
        self._repl_queues = {pid: asyncio.Queue() for pid in self.peers}
        self.match = {pid: 0 for pid in self.peers}
        self.commit_seq = self.last_seq() if not self.peers else 0
        for pid, addr in self.peers.items():
            self._bg.append(asyncio.ensure_future(
                self._replicate_loop(pid, addr)))
        if self.peers and self.fs.journal is not None:
            # term-opening no-op (raft §5.4.2): gives the new term an entry
            # that CAN be committed by counting, which transitively commits
            # every prior-term entry beneath it
            try:
                self.fs._log("noop", {})
            except err.CurvineError:
                pass

    # ---------------- commit tracking (leader) ----------------

    def _advance_commit(self) -> None:
        acked = sorted([self.last_seq()] + list(self.match.values()),
                       reverse=True)
        new_commit = acked[self.quorum - 1]
        # Raft commit restriction: only entries of the CURRENT term may be
        # committed by replica counting (figure-8 unsafety otherwise). The
        # no-op appended at _become_leader makes this reachable right away;
        # committing a current-term entry commits everything before it.
        if new_commit > self.commit_seq:
            t = (self.fs.journal.term_of(new_commit)
                 if self.fs.journal else self.term)
            if t != self.term:
                return
            self.commit_seq = new_commit
            still = []
            for seq, fut in self._commit_waiters:
                if seq <= self.commit_seq:
                    if not fut.done():
                        fut.set_result(True)
                else:
                    still.append((seq, fut))
            self._commit_waiters = still

    def _fail_waiters(self, exc: Exception) -> None:
        for _seq, fut in self._commit_waiters:
            if not fut.done():
                fut.set_exception(exc)
        self._commit_waiters = []

    async def wait_committed(self, seq: int | None = None,
                             deadline=None) -> None:
        """Block until ``seq`` (default: the journal head) is replicated
        on a quorum. This is what makes a client ack mean 'durable on a
        majority' (raft commit rule). A caller-propagated deadline caps
        the wait below the configured commit timeout."""
        if not self.peers:
            return
        if self.role != LEADER:
            raise err.NotLeader(f"node {self.node_id} is {self.role}")
        seq = self.last_seq() if seq is None else seq
        if seq <= self.commit_seq:
            return
        fut = asyncio.get_event_loop().create_future()
        self._commit_waiters.append((seq, fut))
        wait_s = self.commit_timeout_s
        if deadline is not None:
            wait_s = deadline.cap(wait_s)
        try:
            await asyncio.wait_for(fut, wait_s)
        except asyncio.TimeoutError:
            raise err.RpcTimeout(
                f"seq {seq} not committed on a quorum within "
                f"{wait_s:.1f}s") from None

    # ---------------- replication (leader) ----------------

    def on_mutation(self, seq: int, op: str, args: dict,
                    term: int = 0) -> None:
        """Called by MasterFilesystem._log after a local apply+journal."""
        if self.role != LEADER:
            return
        for q in self._repl_queues.values():
            q.put_nowait((seq, op, args, term))

    async def _replicate_loop(self, pid: int, addr: str) -> None:
        """Per-follower: heartbeats + journal entry stream + catch-up."""
        while self.role == LEADER:
            batch: list = []
            q = self._repl_queues[pid]
            try:
                entry = await asyncio.wait_for(
                    q.get(), self.heartbeat_ms / 1000)
                batch.append(entry)
                while not q.empty() and len(batch) < 256:
                    batch.append(q.get_nowait())
            except asyncio.TimeoutError:
                pass          # heartbeat
            try:
                conn = await self.pool.get(addr)
                prev_seq = batch[0][0] - 1 if batch else self.last_seq()
                prev_term = (self.fs.journal.term_of(prev_seq)
                             if self.fs.journal else 0)
                if prev_term is None:
                    # predecessor term fell out of the retained window:
                    # can't prove log matching — snapshot catch-up instead
                    await self._send_snapshot(pid, addr)
                    for entry in batch:
                        q.put_nowait(entry)
                    continue
                rep = await conn.call(RpcCode.RAFT_APPEND, data=pack({
                    "term": self.term, "leader": self.node_id,
                    "entries": [[s, o, a, t] for s, o, a, t in batch],
                    "prev_seq": prev_seq, "prev_term": prev_term,
                    "leader_seq": self.last_seq(),
                    "leader_last_term": self.last_term()}), timeout=2.0)
                body = unpack(rep.data) or {}
                if body.get("term", 0) > self.term:
                    self._step_down(body["term"])
                    return
                if body.get("need_snapshot"):
                    # divergent/lagging log: its applied_seq must NOT
                    # count toward commit (same seq, different history)
                    await self._send_snapshot(pid, addr)
                else:
                    self.match[pid] = max(self.match.get(pid, 0),
                                          body.get("applied_seq", 0))
                    self._advance_commit()
            except Exception as e:
                log.debug("replicate to %d failed: %s", pid, e)
                # requeue the batch IN SEQ ORDER ahead of anything enqueued
                # meanwhile — tail-requeueing would make the next batch
                # start past the follower's head and escalate a transient
                # blip into a full snapshot install
                pending = list(batch)
                while not q.empty():
                    pending.append(q.get_nowait())
                pending.sort(key=lambda entry: entry[0])
                for entry in pending:
                    q.put_nowait(entry)
                await asyncio.sleep(0.2)

    async def _send_snapshot(self, pid: int, addr: str) -> None:
        state = self.fs._snapshot_state()
        conn = await self.pool.get(addr)
        rep = await conn.call(RpcCode.RAFT_SNAPSHOT, data=msgpack.packb({
            "term": self.term, "leader": self.node_id,
            "seq": self.last_seq(), "last_term": self.last_term(),
            "state": state}, use_bin_type=True),
            timeout=30.0)
        body = unpack(rep.data) or {}
        self.match[pid] = max(self.match.get(pid, 0),
                              body.get("applied_seq", 0))
        self._advance_commit()
        log.info("snapshot (seq=%d) sent to %s", self.last_seq(), addr)

    # ---------------- handlers (follower) ----------------

    async def _h_vote(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term, candidate = q["term"], q["candidate"]
        cand_log = (q.get("last_term", 0), q["last_seq"])
        if term > self.term:
            self._step_down(term)
        granted = (term >= self.term
                   and self.voted_for in (None, candidate)
                   and cand_log >= (self.last_term(), self.last_seq()))
        if granted:
            self.voted_for = candidate
            self._save_hard_state()       # fsync BEFORE the vote leaves
            self._touch()
        return {}, pack({"granted": granted, "term": self.term})

    async def _h_prevote(self, msg: Message, conn: ServerConn):
        """Grant iff we would plausibly vote for the candidate in a real
        election at that term AND we have NOT heard from a live leader
        within the minimum election timeout. Grants are stateless: no
        term bump, no voted_for persistence, no timer reset — a pre-vote
        round can never disturb a healthy cluster."""
        q = unpack(msg.data) or {}
        cand_log = (q.get("last_term", 0), q.get("last_seq", 0))
        now = asyncio.get_event_loop().time()
        heard_recently = (now - self._last_heard) < \
            (self.election_timeout[0] / 1000)
        granted = (self.role != LEADER          # a live leader never grants
                   and not heard_recently
                   and q.get("term", 0) > self.term
                   and cand_log >= (self.last_term(), self.last_seq()))
        return {}, pack({"granted": granted, "term": self.term})

    async def _h_append(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        term = q["term"]
        if term < self.term:
            return {}, pack({"term": self.term, "applied_seq": self.last_seq()})
        if term > self.term or self.role != FOLLOWER:
            self._step_down(term)
        self.leader_id = q["leader"]
        self._touch()
        need_snapshot = False
        entries = q.get("entries", [])
        if entries:
            # log-matching: our entry at prev_seq must carry prev_term —
            # a deposed leader with divergent history at the same seqs
            # fails this and heals via snapshot install
            prev_seq = q.get("prev_seq", entries[0][0] - 1)
            if prev_seq <= self.last_seq():
                ours = (self.fs.journal.term_of(prev_seq)
                        if self.fs.journal else 0)
                if ours is None or ours != q.get("prev_term", 0):
                    need_snapshot = True
        # collect the contiguous suffix of new entries, then journal +
        # apply them as ONE batch (one follower-side flush per RPC — the
        # follower half of group commit)
        batch: list[tuple[int, str, dict, int]] = []
        nxt = self.last_seq() + 1
        for rec in ([] if need_snapshot else entries):
            seq, op, args = rec[0], rec[1], rec[2]
            eterm = rec[3] if len(rec) > 3 else 0
            if seq < nxt:
                continue                      # already have it
            if seq != nxt:
                need_snapshot = True          # gap: ask for catch-up
                batch = []
                break
            batch.append((seq, op, args, eterm))
            nxt += 1
        if batch:
            self.fs.apply_replicated_batch(batch)
        # log-matching check: same head seq must mean same head term; a
        # follower that diverged (e.g. deposed leader with extra applied
        # entries, or a different term at the same seq) takes a snapshot
        # install, which REPLACES its state machine wholesale.
        if not need_snapshot:
            if q.get("leader_seq", 0) > self.last_seq():
                need_snapshot = True
            elif self.last_seq() > q.get("leader_seq", 0):
                need_snapshot = True          # we have entries leader lacks
            elif (q.get("leader_seq", 0) == self.last_seq()
                  and q.get("leader_last_term", 0) != self.last_term()):
                need_snapshot = True
        return {}, pack({"term": self.term, "applied_seq": self.last_seq(),
                         "need_snapshot": need_snapshot})

    async def _h_snapshot(self, msg: Message, conn: ServerConn):
        q = msgpack.unpackb(bytes(msg.data), raw=False, strict_map_key=False)
        if q["term"] < self.term:
            return {}, pack({"term": self.term})
        self._touch()
        self.fs.install_snapshot(q["state"], q["seq"],
                                 q.get("last_term", 0))
        log.info("node %d: installed snapshot at seq %d", self.node_id,
                 q["seq"])
        return {}, pack({"term": self.term, "applied_seq": self.last_seq()})

    # ---------------- client gate ----------------

    def check_leader(self) -> None:
        if self.role != LEADER:
            raise err.NotLeader(
                f"node {self.node_id} is {self.role}; "
                f"leader is {self.leader_id}")
