"""Master RPC server: binds RpcCode → MasterFilesystem + managers.

Parity: curvine-server/src/master/master_handler.rs + master_server.rs.
The namespace is a single-writer actor: all handlers run on one asyncio
loop, so mutations are serialized without locks (the reference uses an
actor + RwLock split; asyncio gives us the same property for free)."""

from __future__ import annotations

import asyncio
import logging

from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.common.journal import Journal
from curvine_tpu.common.types import CommitBlock, SetAttrOpts, now_ms
from curvine_tpu.common.metrics import MetricsRegistry
from curvine_tpu.common.path import norm_path
from curvine_tpu.master.acl import AclEnforcer, R, UserCtx, W, X
from curvine_tpu.master.filesystem import MasterFilesystem
from curvine_tpu.master.jobs import JobManager
from curvine_tpu.master.mount import MountManager
from curvine_tpu.master.replication import ReplicationManager
from curvine_tpu.master.retry_cache import RetryCache
from curvine_tpu.master.ttl import TtlManager
from curvine_tpu.obs.trace import Tracer
from curvine_tpu.rpc import Message, RpcCode, RpcServer, ServerConn
from curvine_tpu.rpc.frame import pack, unpack

log = logging.getLogger(__name__)


class MasterServer:
    def __init__(self, conf: ClusterConf | None = None,
                 journal: bool = True, shard_id: int | None = None,
                 shard_count: int = 1):
        self.conf = conf or ClusterConf()
        mc = self.conf.master
        # sharded namespace (master/sharding.py): shard_id is set when
        # THIS server is one shard actor of a router's fleet (striped id
        # allocation); meta_shards>1 with shard_id=None makes this
        # server the ROUTER. shards=1 never constructs any of it.
        self.shard_id = shard_id
        self.shard_count = shard_count
        self.sharded = mc.meta_shards > 1 and shard_id is None
        if self.sharded and mc.raft_peers:
            from curvine_tpu.common import errors as _err
            raise _err.InvalidArgument(
                "meta_shards>1 is mutually exclusive with raft HA "
                "(set meta_shards=1 under raft; see docs/metadata-scale.md)")
        j = Journal(mc.journal_dir, fsync=mc.journal_fsync) if journal else None
        store = None
        if mc.meta_store == "kv":
            from curvine_tpu.master.store import KvMetaStore
            meta_dir = mc.meta_dir or mc.journal_dir.rstrip("/") + "-meta"
            store = KvMetaStore(meta_dir, fsync=mc.journal_fsync,
                                cache_inodes=mc.meta_cache_inodes,
                                engine=mc.meta_engine)
        # native metadata read plane: mirror every committed namespace
        # mutation into C++ and serve stat/exists from native threads.
        # Three shapes (docs/read-plane.md):
        #   * single master — mirror its own store, serve the fast port;
        #   * shard ACTOR — mirror its partition, never bind a port (the
        #     router fronts the fleet via mm_fleet_attach);
        #   * inproc ROUTER — a front mirror holding only the mount
        #     table; reads route to the attached shard mirrors by
        #     crc32(parent) % n. The process backend keeps the front
        #     disabled: member mirrors live in child address spaces.
        self.fastmeta = None
        if mc.fast_meta and (not self.sharded
                             or mc.shard_backend == "inproc"):
            from curvine_tpu.master import fastmeta
            if fastmeta.available():
                if store is None:
                    from curvine_tpu.master.store import MemMetaStore
                    store = MemMetaStore()
                self.fastmeta = fastmeta.FastMeta(
                    acl_enabled=mc.acl_enabled, superuser=mc.superuser,
                    supergroup=mc.supergroup)
                store = fastmeta.MirroredStore(store, self.fastmeta)
        self.fs = MasterFilesystem(
            journal=j, placement=mc.block_placement_policy,
            lost_timeout_ms=mc.worker_lost_timeout_ms,
            snapshot_interval=mc.snapshot_interval_entries, store=store,
            id_stride=shard_count if shard_id is not None else 1,
            id_offset=shard_id or 0,
            ici_mesh_shape=mc.ici_mesh_shape or None)
        self.fs.audit_log = mc.audit_log
        self.mounts = MountManager(self.fs)
        self.fs.mounts = self.mounts
        self.metrics = MetricsRegistry("master")
        # group commit: installed even with journal=None (perf clusters) —
        # then only the KV write batches are grouped. RPC replies release
        # at _group_barrier, after the group's flush.
        from curvine_tpu.common.journal import GroupCommitter
        self.fs.committer = GroupCommitter(
            j, self.fs.store, window_ms=mc.journal_group_commit_ms,
            max_entries=mc.journal_group_max, metrics=self.metrics)
        self.jobs = JobManager(self.fs, self.mounts)
        self.jobs.ec_conf = self.conf.ec
        self.replication = ReplicationManager(
            self.fs, pull_budget_ms=mc.replication_pull_budget_ms,
            metrics=self.metrics)
        self.fs.on_worker_lost = self.replication.on_worker_lost
        self.ttl = TtlManager(self.fs, check_ms=mc.ttl_check_ms)
        # client read leases (master/read_leases.py): only on endpoints
        # that hold CLIENT connections — the router when sharded, the
        # master otherwise. Shard actors see only router conns; their
        # TTL expiries are relayed to the router's manager instead.
        self.leases = None
        if shard_id is None:
            from curvine_tpu.master.read_leases import ReadLeaseManager
            self.leases = ReadLeaseManager(ttl_ms=mc.meta_lease_ms,
                                           max_dirs=mc.meta_lease_dirs)
            self.ttl.on_expire = \
                lambda path: self.leases.invalidate([path])
        from curvine_tpu.master.quota import QuotaManager
        self.quota = QuotaManager(self.fs)
        from curvine_tpu.master.locks import LockManager
        self.locks = LockManager()
        self.acl = AclEnforcer(self.fs, enabled=mc.acl_enabled,
                               superuser=mc.superuser,
                               supergroup=mc.supergroup)
        self.retry_cache = RetryCache(mc.retry_cache_size, mc.retry_cache_ttl_ms)
        from curvine_tpu.master.monitor import DirWatchdog, MasterMonitor
        self.watchdog = DirWatchdog(self.metrics, self.locks,
                                    stall_s=mc.watchdog_stall_ms / 1000)
        self.monitor = MasterMonitor(self)
        self.rpc = RpcServer(mc.hostname, mc.rpc_port, "master",
                             rpc_conf=self.conf.rpc)
        # in-flight requests register at the DISPATCH level so a wedge
        # anywhere (fault hook, handler, commit barrier) is visible
        self.rpc.watchdog = self.watchdog
        # observability plane: server spans per dispatch (trace context
        # picked off the header) + per-code rpc.<name> histograms; the
        # store additionally holds spans the CLIENTS push via
        # METRICS_REPORT, so one GET_SPANS collect sees both
        self.tracer = Tracer.from_conf("master", self.conf.obs,
                                       metrics=self.metrics)
        self.rpc.obs = self.tracer
        self.rpc.metrics = self.metrics
        # multi-tenant admission control (common/qos.py): checked in the
        # conn loop before a request queues; unlimited by default
        from curvine_tpu.common.qos import AdmissionController
        self.qos = AdmissionController.from_conf(
            self.conf.qos, slow_op_ms=self.conf.obs.slow_op_ms,
            metrics=self.metrics)
        self.rpc.qos = self.qos
        self.replication.tracer = self.tracer
        # pool for the GET_SPANS fan-out to workers (trace assembly)
        from curvine_tpu.rpc.client import ConnectionPool
        self._obs_pool = ConnectionPool(size=1)
        self.raft = None
        if mc.raft_peers:
            from curvine_tpu.master.ha import RaftLite
            peers = {i + 1: addr for i, addr in enumerate(mc.raft_peers)
                     if i + 1 != mc.raft_node_id}
            if 0 < mc.raft_node_id <= len(mc.raft_peers):
                self_addr = mc.raft_peers[mc.raft_node_id - 1]
            else:
                self_addr = f"{mc.hostname}:{mc.rpc_port}"
            self.raft = RaftLite(
                mc.raft_node_id, peers, self.fs, self.rpc,
                self_addr=self_addr, learner=mc.raft_learner,
                promote_lag=mc.raft_promote_lag,
                snapshot_chunk_bytes=mc.raft_snapshot_chunk_mb * 1024 * 1024,
                transfer_timeout_s=mc.raft_transfer_timeout_ms / 1000,
                metrics=self.metrics)
            self.fs.on_mutation = self.raft.on_mutation
        self.shards = None
        if self.sharded:
            from curvine_tpu.master.sharding import ShardRouter
            self.shards = ShardRouter(self, journal=journal)
        self._register_handlers()
        if self.shards is not None:
            self._register_shard_routes()
        self._worker_counters: dict[int, dict] = {}
        # worker_id -> count of non-healthy tier dirs (from heartbeats);
        # feeds the cluster-wide dirs.unhealthy gauge
        self._dirs_unhealthy: dict[int, int] = {}
        self._bg: list[asyncio.Task] = []
        from curvine_tpu.common.executor import ScheduledExecutor
        self.executor = ScheduledExecutor("master")
        self.ufs_backup = None
        if mc.ufs_backup_uri:
            from curvine_tpu.master.ufs_backup import UfsBackup
            self.ufs_backup = UfsBackup(self.fs, mc.ufs_backup_uri)

    @property
    def addr(self) -> str:
        return self.rpc.addr

    async def start(self) -> None:
        self.fs.recover()
        if self.ufs_backup is not None:
            # disaster bootstrap: a wiped/virgin master dir restores the
            # namespace from the newest UFS snapshot (local truth wins
            # when any history exists). Parity: ufs_loader.rs.
            try:
                await self.ufs_backup.bootstrap_if_empty()
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                log.warning("ufs backup bootstrap failed: %s", e)
        self.mounts.load_from_store()
        # durable decommission intents (KV cold starts skip replay, so
        # runtime-only state would otherwise vanish on restart)
        self.fs.workers.deco_ids |= set(self.fs.store.iter_deco())
        if self.shards is not None:
            # shards (and the crash-recovery sweep) come up before the
            # endpoint accepts traffic
            await self.shards.start()
            self.executor.submit_periodic("shard-stats",
                                          self.shards.poll_stats, 2.0)
        await self.rpc.start()
        if self.raft is not None:
            await self.raft.start()
        # periodic duties ride the scheduled executor
        # (parity: curvine-common/src/executor/ ScheduledExecutor)
        interval = self.conf.master.heartbeat_check_ms / 1000
        # HA followers must not ACT on replicated state (ttl deletes,
        # evictions, lease recovery, repair dispatch): acting appends
        # divergent local journal entries. Every mutating periodic duty
        # is gated on leadership; single-node mode gates to True.
        gate = self._is_leader
        self.executor.submit_periodic("heartbeat-check",
                                      self._heartbeat_tick, interval)
        if self.fastmeta is not None and self.shard_id is not None:
            # shard actor: keep the mirror warm for the router's front
            # plane, but never bind a fast port of its own
            self.fastmeta.load_from_store(self.fs.store)
        elif self.fastmeta is not None:
            # bulk load AFTER recover (KV cold starts never replay old
            # inodes through the store wrapper), then keep serving in
            # lockstep with leadership. The plane is best-effort: a bind
            # failure degrades to Python-only, never a dead master.
            try:
                self.fastmeta.serve(self.conf.master.hostname,
                                    self.conf.master.fast_port)
                self.fastmeta.load_from_store(self.fs.store)
            except Exception as e:  # noqa: BLE001 — degrade, don't die
                log.warning("fast metadata plane disabled: %s", e)
                self.fastmeta.close()
                self.fastmeta = None
            else:
                self._fast_serving = False
                self._fast_gate_tick()
                self.executor.submit_periodic("fastmeta-gate",
                                              self._fast_gate_tick, 1.0)
        self.executor.submit_periodic("lease-recovery",
                                      self._lease_recovery_tick, 30.0)
        self.executor.submit_periodic("watchdog", self.watchdog.tick, 1.0)
        if self.ufs_backup is not None:
            async def backup_tick():
                if self._is_leader():
                    await self.ufs_backup.upload_if_advanced()
            self.executor.submit_periodic(
                "ufs-backup", backup_tick,
                self.conf.master.ufs_backup_interval_s)
        self.executor.submit("ttl", self.ttl.run(leader_gate=gate))
        self.executor.submit("replication",
                             self.replication.run(leader_gate=gate))
        self.executor.submit("jobs", self.jobs.run(leader_gate=gate))
        self.executor.submit("quota", self.quota.run(leader_gate=gate))
        log.info("master started at %s", self.addr)

    def _is_leader(self) -> bool:
        return self.raft is None or self.raft.is_leader

    def _fast_gate_tick(self) -> None:
        """Fast-path serving tracks leadership: followers mirror the
        namespace (replicated applies flow through the same store
        wrapper) but must not serve reads that bypass the leader."""
        want = self._is_leader()
        if want != self._fast_serving:
            self.fastmeta.set_serving(want)
            self._fast_serving = want
            log.info("fast metadata plane %s (port %s)",
                     "serving" if want else "gated off",
                     self.fastmeta.port)

    def _lease_recovery_tick(self) -> None:
        if self._is_leader():
            self.fs.recover_stale_leases()

    def _heartbeat_tick(self) -> None:
        # LOST bookkeeping runs everywhere (follower-served reads must
        # not return dead-worker locations); repair-dispatch side effects
        # stay leader-gated. Counter pruning is local metrics state and
        # runs everywhere too.
        self.fs.check_lost_workers(act=self._is_leader())
        # dead workers' last snapshots must not pin the gauges forever
        self._prune_worker_counters()
        # KV compaction debt: segment count waiting for merge (creation
        # bursts at namespace scale show up here before read latency does)
        kv = getattr(self.fs.store, "kv", None)
        if kv is not None:
            segs = getattr(kv, "segment_count", None)
            if segs is None:
                segs = len(getattr(kv, "segments", ()))
            self.metrics.gauge("meta.kv_segments", segs)

    def _prune_worker_counters(self) -> None:
        # draining workers still serve and still report: keep their
        # counters or the aggregate gauges flap for the whole drain
        live = {w.address.worker_id
                for w in self.fs.workers.serving_workers()}
        if any(k not in live for k in self._worker_counters):
            self._worker_counters = {k: v for k, v
                                     in self._worker_counters.items()
                                     if k in live}
        for name in ("bytes.read", "bytes.written"):
            self.metrics.gauge(name, sum(
                c.get(name, 0) for c in self._worker_counters.values()))

    async def stop(self) -> None:
        if self.raft is not None:
            await self.raft.stop()
        await self.executor.stop()
        for t in self._bg:
            t.cancel()
        self._bg.clear()
        await self.rpc.stop()
        if self.shards is not None:
            if self.fastmeta is not None:
                # join the front's native serve threads BEFORE freeing
                # the member mirrors they read from
                self.fastmeta.stop_serving()
            await self.shards.stop()
        await self._obs_pool.close()
        try:
            self.fs.flush_group()   # drain any open journal group
        except Exception as e:  # noqa: BLE001 — already-broken committer
            log.warning("final group flush failed: %s", e)
        if self.fs.journal:
            self.fs.journal.close()
        if self.fastmeta is not None:
            self.fastmeta.close()
        self.fs.store.close()

    # ---------------- handlers ----------------

    def _register_handlers(self) -> None:
        r = self.rpc.register
        C = RpcCode
        r(C.MKDIR, self._h(self._mkdir, mutate=True))
        r(C.DELETE, self._h(self._delete, mutate=True))
        r(C.CREATE_FILE, self._h(self._create_file, mutate=True))
        r(C.OPEN_FILE, self._h(self._open_file))
        r(C.APPEND_FILE, self._h(self._append_file, mutate=True))
        r(C.FILE_STATUS, self._h(self._file_status))
        r(C.LIST_STATUS, self._h(self._list_status))
        r(C.EXISTS, self._h(self._exists))
        r(C.RENAME, self._h(self._rename, mutate=True))
        r(C.ADD_BLOCK, self._h(self._add_block, mutate=True))
        r(C.COMPLETE_FILE, self._h(self._complete_file, mutate=True))
        r(C.GET_BLOCK_LOCATIONS, self._h(self._get_block_locations))
        r(C.GET_MASTER_INFO, self._h(self._master_info))
        r(C.SET_ATTR, self._h(self._set_attr, mutate=True))
        r(C.SYMLINK, self._h(self._symlink, mutate=True))
        r(C.LINK, self._h(self._link, mutate=True))
        r(C.RESIZE_FILE, self._h(self._resize, mutate=True))
        r(C.FREE, self._h(self._free, mutate=True))
        r(C.CREATE_FILES_BATCH, self._h(self._create_files_batch, mutate=True))
        r(C.ADD_BLOCKS_BATCH, self._h(self._add_blocks_batch, mutate=True))
        r(C.COMPLETE_FILES_BATCH, self._h(self._complete_files_batch, mutate=True))
        r(C.LIST_OPTIONS, self._h(self._list_options))
        r(C.CONTENT_SUMMARY, self._h(self._content_summary))
        r(C.META_BATCH, self._h(self._meta_batch, mutate=True))
        r(C.GET_LOCK, self._h(self._get_lock))
        r(C.SET_LOCK, self._h(self._set_lock))
        r(C.LIST_LOCK, self._h(self._list_lock))
        r(C.ASSIGN_WORKER, self._h(self._assign_worker))
        r(C.METRICS_REPORT, self._h(self._metrics_report))
        r(C.CLUSTER_HEALTH, self._h(self._cluster_health))
        r(C.GET_SPANS, self._h(self._get_spans))
        # worker plane
        r(C.WORKER_HEARTBEAT, self._h(self._worker_heartbeat))
        r(C.WORKER_BLOCK_REPORT, self._h(self._worker_block_report))
        r(C.REQUEST_REPLACEMENT_WORKER, self._h(self._replacement_worker))
        r(C.REPORT_UNDER_REPLICATED_BLOCKS, self._h(self._report_under_replicated))
        r(C.REPORT_BLOCK_REPLICATION_RESULT, self._h(self._replication_result))
        r(C.EC_COMMIT_STRIPE, self._h(self._ec_commit_stripe, mutate=True))
        r(C.DECOMMISSION_WORKER, self._h(self._decommission_worker,
                                         mutate=True))
        # mounts
        r(C.MOUNT, self._h(self._mount, mutate=True))
        r(C.UNMOUNT, self._h(self._umount, mutate=True))
        r(C.UPDATE_MOUNT, self._h(self._update_mount, mutate=True))
        r(C.GET_MOUNT_TABLE, self._h(self._mount_table))
        r(C.GET_MOUNT_INFO, self._h(self._mount_info))
        # jobs
        r(C.SUBMIT_JOB, self._h(self._submit_job, mutate=True))
        r(C.GET_JOB_STATUS, self._h(self._job_status))
        r(C.CANCEL_JOB, self._h(self._cancel_job, mutate=True))
        r(C.PREFETCH_WINDOW, self._h(self._prefetch_window, mutate=True))
        r(C.REPORT_TASK, self._h(self._report_task))
        # sharded namespace plane: every master answers the 2PC
        # participant protocol and stats (a shard IS a MasterServer);
        # SHARD_TABLE is only meaningful on a router
        r(C.SHARD_TX, self._h(self._shard_tx, mutate=True))
        r(C.SHARD_TX_LIST, self._h(self._shard_tx_list))
        r(C.SHARD_STATS, self._h(self._shard_stats))
        r(C.SHARD_TABLE, self._h(self._shard_table))
        r(C.TENANT_STATS, self._h(self._tenant_stats))
        # raft membership admin plane (docs/raft.md). MEMBER_CHANGE rides
        # the mutate path: leader gate + journaled config entry + commit
        # barrier (the RPC acks once the change is committed). TRANSFER
        # does its own leader gate and journals nothing. RAFT_STATUS is
        # registered by RaftLite itself so ANY node answers it.
        r(C.RAFT_MEMBER_CHANGE, self._h(self._raft_member_change,
                                        mutate=True))
        r(C.RAFT_TRANSFER, self._h(self._raft_transfer))

    def _register_shard_routes(self) -> None:
        """meta_shards>1: this endpoint is a thin router. Namespace
        codes RE-register to forwarding handlers (master/sharding.py);
        mounts, jobs, locks, health, spans and worker assignment stay
        router-local. Routed handlers skip _h's barriers — durability
        is the owning shard's group commit, and retries dedup in the
        owning shard's retry cache (routing is deterministic), except
        the multi-step 2PC ops which cache at the router."""
        sh = self.shards
        r = self.rpc.register
        C = RpcCode

        def wrap(fn, cache: bool = False, inval=None):
            # inval: the mutation code whose touched paths must be
            # lease-invalidated after the owning shard acks (the router
            # holds the client conns, so pushes originate here)
            async def handler(msg: Message, conn: ServerConn):
                req = self._norm_req(unpack(msg.data) or {})
                if cache:
                    key = (req.get("client_id"), req.get("call_id"))
                    if key[0] is not None and key[1] is not None:
                        hit = self.retry_cache.get(key)
                        if hit is not None:
                            return {}, hit
                        data = pack(await fn(req, msg))
                        if inval is not None:
                            self._lease_invalidate(inval, req)
                        self.retry_cache.put(key, data)
                        return {}, data
                leased = inval is None and self._lease_grant(msg, req, conn)
                out = await fn(req, msg)
                if inval is not None:
                    self._lease_invalidate(inval, req)
                elif leased and isinstance(out, dict):
                    out["lease"] = self.leases.token()
                return {}, pack(out)
            return handler

        def fwd(code):
            mutates = code in (C.CREATE_FILE, C.APPEND_FILE,
                               C.COMPLETE_FILE, C.RESIZE_FILE,
                               C.SYMLINK, C.MKDIR)
            return wrap(lambda q, m, c=code: sh.r_forward(c, q, m),
                        inval=code if mutates else None)

        for code in (C.CREATE_FILE, C.OPEN_FILE, C.APPEND_FILE,
                     C.ADD_BLOCK, C.COMPLETE_FILE, C.GET_BLOCK_LOCATIONS,
                     C.RESIZE_FILE, C.SYMLINK, C.MKDIR):
            r(code, fwd(code))
        r(C.FILE_STATUS, wrap(sh.r_file_status))
        r(C.EXISTS, wrap(sh.r_exists))
        r(C.LIST_STATUS, wrap(sh.r_list_status))
        r(C.LIST_OPTIONS, wrap(sh.r_list_options))
        r(C.CONTENT_SUMMARY, wrap(sh.r_content_summary))
        r(C.SET_ATTR, wrap(sh.r_set_attr, inval=C.SET_ATTR))
        r(C.FREE, wrap(sh.r_free, inval=C.FREE))
        r(C.DELETE, wrap(sh.r_delete, inval=C.DELETE))
        r(C.RENAME, wrap(sh.r_rename, cache=True, inval=C.RENAME))
        r(C.LINK, wrap(sh.r_link, cache=True, inval=C.LINK))
        for code in (C.CREATE_FILES_BATCH, C.ADD_BLOCKS_BATCH,
                     C.COMPLETE_FILES_BATCH, C.META_BATCH):
            r(code, wrap(lambda q, m, c=code: sh.r_batch(c, q, m),
                         inval=code if code != C.ADD_BLOCKS_BATCH
                         else None))
        r(C.WORKER_HEARTBEAT, wrap(
            lambda q, m: sh.r_worker_heartbeat(q, m,
                                               self._worker_heartbeat)))
        r(C.WORKER_BLOCK_REPORT, wrap(sh.r_worker_block_report))

    # Path-valued request fields, normalized ('.'/'..' resolved, root
    # escapes rejected) before ANY handler sees them — an S3-gateway key
    # like '..%2Fx' must never become a literal inode name.
    _PATH_KEYS = ("path", "src", "dst", "link", "cv_path")

    @classmethod
    def _norm_req(cls, req: dict) -> dict:
        for k in cls._PATH_KEYS:
            v = req.get(k)
            if isinstance(v, str):
                req[k] = norm_path(v)
        for sub in req.get("requests") or []:
            if isinstance(sub, dict):
                cls._norm_req(sub)
        return req

    def _h(self, fn, mutate: bool = False):
        import inspect

        async def call(req):
            rep = fn(req)
            if inspect.isawaitable(rep):
                rep = await rep
            return rep

        async def handler(msg: Message, conn: ServerConn):
            # per-code latency histograms moved to the dispatch level
            # (RpcServer.metrics → rpc.<code_name>), uniform with the
            # worker; this wrapper only keeps the mutation discipline
            req = self._norm_req(unpack(msg.data) or {})
            if mutate and self.raft is not None:
                self.raft.check_leader()
            if mutate:
                key = (req.get("client_id"), req.get("call_id"))
                if key[0] is not None and key[1] is not None:
                    cached = self.retry_cache.get(key)
                    if cached is not None:
                        return {}, cached
                    rep = await call(req)
                    await self._group_barrier()
                    await self._commit_barrier(msg.deadline)
                    self._lease_invalidate(msg.code, req)
                    data = pack(rep)
                    self.retry_cache.put(key, data)
                    return {}, data
            leased = not mutate and self._lease_grant(msg, req, conn)
            rep = await call(req)
            if mutate:
                await self._group_barrier()
                await self._commit_barrier(msg.deadline)
                self._lease_invalidate(msg.code, req)
            elif leased and isinstance(rep, dict):
                rep["lease"] = self.leases.token()
            return {}, pack(rep)
        return handler

    # reads that may carry `"lease": True` → register the conn as a
    # cache holder on the entry's parent directory (the listed dir
    # itself for LIST_STATUS) and stamp the token into the reply
    _LEASED_READS = frozenset({int(RpcCode.FILE_STATUS),
                               int(RpcCode.EXISTS),
                               int(RpcCode.LIST_STATUS)})
    # mutation code → request keys naming the namespace paths it touched
    _INVAL_KEYS = {
        int(RpcCode.MKDIR): ("path",),
        int(RpcCode.CREATE_FILE): ("path",),
        int(RpcCode.DELETE): ("path",),
        int(RpcCode.APPEND_FILE): ("path",),
        int(RpcCode.COMPLETE_FILE): ("path",),
        int(RpcCode.RENAME): ("src", "dst"),
        int(RpcCode.SET_ATTR): ("path",),
        int(RpcCode.SYMLINK): ("link",),
        int(RpcCode.LINK): ("src", "dst"),
        int(RpcCode.RESIZE_FILE): ("path",),
        int(RpcCode.FREE): ("path",),
        int(RpcCode.MOUNT): ("cv_path",),
        int(RpcCode.UNMOUNT): ("cv_path",),
        int(RpcCode.UPDATE_MOUNT): ("cv_path",),
    }
    _INVAL_BATCHES = frozenset({int(RpcCode.META_BATCH),
                                int(RpcCode.CREATE_FILES_BATCH),
                                int(RpcCode.COMPLETE_FILES_BATCH)})

    def _lease_grant(self, msg: Message, req: dict, conn) -> bool:
        """Register `conn` as a lease holder for a `"lease": True` read.
        Granted BEFORE the handler runs so ENOENT answers are leased too
        (the client caches negatives; a later create must push)."""
        if (self.leases is None or not req.get("lease")
                or int(msg.code) not in self._LEASED_READS
                or not isinstance(req.get("path"), str)):
            return False
        from curvine_tpu.master.read_leases import parent_dir
        p = req["path"]
        self.leases.grant(conn, p if int(msg.code) ==
                          int(RpcCode.LIST_STATUS) else parent_dir(p))
        return True

    def _lease_invalidate(self, code: int, req: dict) -> None:
        """A mutation landed: push META_INVALIDATE for the paths it
        touched to every conn holding a lease on an affected dir."""
        if self.leases is None:
            return
        code = int(code)
        if code in self._INVAL_BATCHES:
            paths = [r.get("path") for r in req.get("requests") or ()
                     if isinstance(r, dict)]
        else:
            keys = self._INVAL_KEYS.get(code)
            if not keys:
                return
            paths = [req.get(k) for k in keys]
        self.leases.invalidate([p for p in paths if isinstance(p, str)])

    async def _group_barrier(self) -> None:
        """Group-commit rule: a mutation is acked only after the journal
        group containing it has flushed (and its KV batch landed). This
        await is where concurrent mutations pile into one group."""
        if self.fs.committer is not None:
            await self.fs.committer.sync()

    async def _commit_barrier(self, deadline=None) -> None:
        """Raft commit rule: a mutation is acked to the client only after
        its journal entry is replicated on a quorum (closes the acked-
        write-loss window of the round-1 design). A caller deadline caps
        the wait: past it the client is gone, so holding the dispatch
        slot longer is dead work (the entry still commits in the
        background — only the ack is abandoned)."""
        if self.raft is not None:
            await self.raft.wait_committed(self.fs.journal.seq,
                                           deadline=deadline)

    # --- fs ---
    def _mkdir(self, q):
        ctx = UserCtx.from_req(q)
        if self.fs.exists(q["path"]):
            self.acl.check(ctx, q["path"], 0)     # idempotent: traverse only
        else:
            self.acl.check(ctx, q["path"], W | X, on_parent=True)
        st = self.fs.mkdir(q["path"], create_parent=q.get("create_parent", True),
                           mode=q.get("mode", 0o755),
                           owner=q.get("owner") or ctx.user,
                           group=q.get("group") or (ctx.groups[0] if ctx.groups
                                                    else ctx.user),
                           x_attr=q.get("x_attr"))
        return {"status": st.to_wire()}

    def _delete(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], W | X, on_parent=True)
        self.fs.delete(q["path"], recursive=q.get("recursive", False))
        self.quota.invalidate(q["path"])
        return {}

    def _create_file(self, q, ctx=None):
        if ctx is None:
            ctx = UserCtx.from_req(q)
        # one shared walk feeds the acl branch, the quota check, AND the
        # filesystem's own validation (no awaits in between)
        walked = self.fs.tree.walk_parent(q["path"])
        parent, _name, existing = walked
        if existing is not None:
            self.acl.check(ctx, q["path"], W)     # overwrite needs w on file
        else:
            self.acl.check(ctx, q["path"], W | X, on_parent=True)
        self.quota.check_create(q["path"], parent=parent)
        st = self.fs.create_file(
            q["path"], overwrite=q.get("overwrite", False),
            create_parent=q.get("create_parent", True),
            replicas=q.get("replicas", 1),
            block_size=q.get("block_size", self.conf.client.block_size),
            mode=q.get("mode", 0o644), owner=q.get("owner") or ctx.user,
            group=q.get("group") or (ctx.groups[0] if ctx.groups
                                     else ctx.user),
            client_name=q.get("client_name", ""),
            x_attr=q.get("x_attr"), storage_policy=q.get("storage_policy"),
            file_type=q.get("file_type", 1), walked=walked)
        if st.storage_policy.ttl_ms > 0:
            # index at create so the TTL engages without waiting for the
            # periodic O(namespace) rescan
            self.ttl.index(st.id, st.mtime, st.storage_policy.ttl_ms)
        return {"status": st.to_wire()}

    def _open_file(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], R)
        fb = self.fs.get_block_locations(q["path"])
        return {"file_blocks": fb.to_wire()}

    def _append_file(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], W)
        fb = self.fs.append_file(q["path"], client_name=q.get("client_name", ""))
        return {"file_blocks": fb.to_wire()}

    async def _file_status(self, q):
        from curvine_tpu.common import errors as cerr
        self.acl.check(UserCtx.from_req(q), q["path"], 0)   # traverse only
        try:
            return {"status": self.fs.file_status(q["path"]).to_wire()}
        except cerr.FileNotFound:
            st = await self.mounts.ufs_status(q["path"])
            if st is None:
                raise
            return {"status": st.to_wire()}

    async def _content_summary(self, q):
        """Recursive length/file/dir counts in ONE RPC, computed on the
        master's inode tree (the reference's ContentSummary aggregates
        client-side over N ListStatus calls — content_summary.rs). The
        walk yields to the event loop periodically (a big subtree must
        not stall heartbeats), requires R|X on every directory like HDFS
        getContentSummary, and refuses subtrees intersecting mounts —
        their totals live (partly) in the UFS, so clients aggregate the
        unified listing instead (CurvineClient.content_summary does)."""
        import asyncio as _aio
        from curvine_tpu.common import errors as cerr
        path = q["path"]
        ctx = UserCtx.from_req(q)
        # traverse check FIRST: FileNotFound vs PermissionDenied must not
        # become an existence oracle inside unreadable directories
        self.acl.check(ctx, path, 0)
        node = self.fs.tree.resolve(path)
        if node is None:
            raise cerr.FileNotFound(path)
        if self.mounts is not None:
            prefix = (path.rstrip("/") or "") + "/"
            if self.mounts.get_mount(path) is not None or any(
                    m.cv_path.startswith(prefix)
                    for m in self.mounts.table()):
                raise cerr.Unsupported(
                    f"{path} intersects mounts: aggregate the unified "
                    "listing client-side")
        if node.is_dir:
            self.acl.check(ctx, path, R)
        # Weakly consistent (HDFS-style): the walk yields to the event
        # loop every 2048 nodes, so concurrent delete/rename can detach
        # subtrees mid-traversal — counts reflect no single namespace
        # snapshot (path_of tolerates detached nodes: it stops at the
        # first missing parent).
        length = file_count = dir_count = visited = 0
        stack = [node]
        while stack:
            n = stack.pop()
            if n.is_dir:
                if not self.acl.allows(n, ctx, R | X):
                    raise cerr.PermissionDenied(
                        f"user={ctx.user} needs r-x on "
                        f"{self.fs.tree.path_of(n)}")
                dir_count += 1
                stack.extend(ch for _nm, ch in self.fs.tree.children(n))
            else:
                file_count += 1
                length += n.len
            visited += 1
            if visited % 2048 == 0:
                await _aio.sleep(0)
        return {"length": length, "file_count": file_count,
                "directory_count": dir_count}

    async def _list_status(self, q):
        """Cached entries merged with the mounted UFS listing (unified
        metadata view — UFS objects appear before they are ever cached).
        Parity: reference sync_ufs_meta / unified listing."""
        from curvine_tpu.common import errors as cerr
        path = q["path"]
        node = self.fs.tree.resolve(path)
        self.acl.check(UserCtx.from_req(q), path,
                       R if node is not None and node.is_dir else 0)
        try:
            cached = self.fs.list_status(path)
        except cerr.FileNotFound:
            if await self.mounts.ufs_status(path) is None:
                raise
            cached = []
        merged = {s.name: s for s in await self.mounts.ufs_list(path)}
        merged.update({s.name: s for s in cached})
        return {"statuses": [merged[k].to_wire() for k in sorted(merged)]}

    async def _exists(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], 0)   # traverse
        if self.fs.exists(q["path"]):
            return {"exists": True}
        st = await self.mounts.ufs_status(q["path"])
        return {"exists": st is not None}

    def _rename(self, q):
        ctx = UserCtx.from_req(q)
        self.acl.check(ctx, q["src"], W | X, on_parent=True)
        self.acl.check(ctx, q["dst"], W | X, on_parent=True)
        out = {"result": self.fs.rename(q["src"], q["dst"])}
        self.quota.invalidate(q["src"])
        self.quota.invalidate(q["dst"])
        return out

    def _check_write_lease(self, q) -> None:
        """Writes to an OPEN file are restricted to the lease holder (the
        client that created/appended it, which was ACL-authorized then);
        everyone else needs W — and traverse is always enforced so open
        files can't be probed through unreadable dirs."""
        ctx = UserCtx.from_req(q)
        self.acl.check(ctx, q["path"], 0)             # traverse, always
        node = self.fs.tree.resolve(q["path"])
        if node is not None and not node.is_complete and node.client_name:
            caller = q.get("client_name") or q.get("client_id")
            if caller == node.client_name or self.acl._is_super(ctx):
                return                                # lease holder
            from curvine_tpu.common import errors as cerr
            raise cerr.LeaseConflict(
                f"{q['path']} is open by another client")
        self.acl.check(ctx, q["path"], W)

    def _add_block(self, q):
        self._check_write_lease(q)
        node = self.fs.tree.resolve(q["path"])
        if node is not None:
            self.quota.check_create(q["path"], new_bytes=node.block_size,
                                    new_files=0)
        lb = self.fs.add_block(
            q["path"], client_host=q.get("client_host", ""),
            exclude_workers=q.get("exclude_workers"),
            commit_blocks=[CommitBlock.from_wire(c)
                           for c in q.get("commit_blocks", [])],
            ici_coords=q.get("ici_coords"),
            abandon_block=q.get("abandon_block"))
        return {"block": lb.to_wire()}

    def _complete_file(self, q):
        self._check_write_lease(q)
        ok = self.fs.complete_file(
            q["path"], q.get("len", 0),
            commit_blocks=[CommitBlock.from_wire(c)
                           for c in q.get("commit_blocks", [])],
            client_name=q.get("client_name", ""),
            only_flush=q.get("only_flush", False))
        return {"result": ok}

    def _get_block_locations(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], R)
        return {"file_blocks": self.fs.get_block_locations(q["path"]).to_wire()}

    def _master_info(self, q):
        info = self.fs.master_info(self.addr)
        # advertise only a SERVING plane: a follower's fast port answers
        # fast-gated for everything, and a client attached to a follower
        # for reads would otherwise keep rediscovering the useless addr
        if (self.fastmeta is not None and self.fastmeta.port
                and self._is_leader()):
            host = self.addr.rsplit(":", 1)[0]
            info.fast_addr = f"{host}:{self.fastmeta.port}"
        wire = info.to_wire()
        if self.shards is not None:
            # the router's own tree is (near) empty: report the fleet
            rows = [s for s in self.shards.stats if s.get("state") == "up"]
            if rows:
                wire["inode_num"] = sum(s.get("inodes", 0) for s in rows)
                wire["block_num"] = sum(s.get("blocks", 0) for s in rows)
            wire["meta_shards"] = self.conf.master.meta_shards
        return {"info": wire}

    # --- sharded namespace plane (master/sharding.py) ---

    def _shard_tx(self, q):
        """2PC participant protocol, executed on this shard's actor
        loop; mutate=True dispatch means every phase's journal entry is
        group-committed before the coordinator sees the reply."""
        from curvine_tpu.common import errors as cerr
        phase = q["phase"]
        if phase == "prepare_src":
            return {"rec": self.fs.tx_prepare(
                q["txid"], q["op"], q["src"], q["dst"], role="src")}
        if phase == "prepare_dst":
            self.fs.tx_prepare(q["txid"], q["op"], q["src"], q["dst"],
                               role="dst", rec=q["rec"])
        elif phase == "commit":
            self.fs.tx_commit(q["txid"])
        elif phase == "abort":
            self.fs.tx_abort(q["txid"])
        elif phase == "forget":
            self.fs.tx_forget(q["txid"])
        else:
            raise cerr.InvalidArgument(f"unknown shard tx phase {phase!r}")
        return {}

    def _shard_tx_list(self, q):
        return {"txs": self.fs.list_tx()}

    def _shard_stats(self, q):
        import os as _os
        fs = self.fs
        com = fs.committer
        handled = sum(h.count for name, h in self.metrics.histograms.items()
                      if name.startswith("rpc."))
        if fs.journal is not None:
            seq = fs.journal.seq
        elif fs.store.kind == "kv":
            seq = fs.store.get_counter("applied_seq", 0)
        else:
            seq = 0
        return {"shard_id": -1 if self.shard_id is None else self.shard_id,
                "inodes": fs.tree.count(), "blocks": fs.blocks.count(),
                "journal_seq": seq,
                "queue_depth": max(0, com._dirty - com._synced) if com else 0,
                "groups": com.groups if com else 0,
                "entries": com.entries if com else 0,
                "handled": handled, "pid": _os.getpid(),
                "uptime_ms": now_ms() - fs.start_ms}

    async def _shard_table(self, q):
        """Shard rows plus the read fan-out plane's rollup: lease-
        manager state, aggregated client.meta_cache.* counters pushed
        via METRICS_REPORT, and native fast-meta counters. One RPC
        feeds both the shard table and the read-plane rows of
        `cv report` (docs/read-plane.md)."""
        out: dict = {"shards": []}
        if self.shards is not None:
            out["shards"] = await self.shards.poll_stats()
        if self.leases is not None:
            out["leases"] = self.leases.stats()
        pre = "client.meta_cache."
        cache = {k[len(pre):]: v for k, v in self.metrics.counters.items()
                 if k.startswith(pre)}
        if cache:
            out["meta_cache"] = cache
        if self.fastmeta is not None:
            out["fastmeta"] = self.fastmeta.counters()
        # write-pipeline fault-tolerance rollup (client.write.* counters
        # pushed via METRICS_REPORT): failovers absorbed, bytes replayed
        # after total replica loss, degraded commits awaiting healing
        pre_w = "client.write."
        wp = {k[len(pre_w):]: v for k, v in self.metrics.counters.items()
              if k.startswith(pre_w)}
        if wp:
            out["write_plane"] = wp
        # data-plane read rollup (client.read.* counters pushed via
        # METRICS_REPORT): shm short-circuit hits/fallbacks and bytes
        # delivered zero-copy (docs/data-plane.md)
        pre_r = "client.read."
        rp = {k[len(pre_r):]: v for k, v in self.metrics.counters.items()
              if k.startswith(pre_r)}
        if rp:
            out["read_plane"] = rp
        # healing-rail rollup: replicate/evacuate/reconstruct outcomes +
        # scrub verdicts (master-side counters), and the EC stripe plane
        for prefix, key in (("replication.", "replication"),
                            ("ec.", "ec_plane")):
            vals = {k[len(prefix):]: v
                    for k, v in self.metrics.counters.items()
                    if k.startswith(prefix)}
            if vals:
                out[key] = vals
        # cache-intelligence rollup (docs/caching.md): workers heartbeat
        # flattened "cache.<tier>.<stat>" admission counters (hits,
        # misses, ghost_hits, scan_evicted, admits) and per-tenant
        # tier-0 occupancy as "cache.tier0.<tenant>" — summed across
        # workers into per-tier dicts for `cv report`'s Cache plane line
        cp: dict = {}
        for counters in self._worker_counters.values():
            for k, v in counters.items():
                if not k.startswith("cache."):
                    continue
                tier, _, stat = k[len("cache."):].partition(".")
                if stat:
                    grp = cp.setdefault(tier, {})
                    grp[stat] = grp.get(stat, 0) + v
        if cp:
            out["cache_plane"] = cp
        # ICI-plane rollup (docs/ici-plane.md): worker "ici.*" heartbeat
        # counters (peer pulls, tcp fallbacks, hbm exports) + client
        # "client.ici.*" broadcast counters pushed via METRICS_REPORT +
        # the master's own replication.ici_* dispatch counters
        ici: dict = {}
        for counters in self._worker_counters.values():
            for k, v in counters.items():
                if k.startswith("ici."):
                    stat = k[len("ici."):]
                    ici[stat] = ici.get(stat, 0) + v
        pre_i = "client.ici."
        for k, v in self.metrics.counters.items():
            if k.startswith(pre_i):
                stat = k[len(pre_i):]
                ici[stat] = ici.get(stat, 0) + v
        for name, stat in (("replication.ici_hinted", "hinted"),
                           ("replication.ici_transfers", "transfers")):
            v = self.metrics.counters.get(name, 0)
            if v:
                ici[stat] = ici.get(stat, 0) + v
        if ici:
            out["ici_plane"] = ici
        return out

    def _tenant_stats(self, q):
        return self.qos.snapshot()

    def _raft_member_change(self, q):
        """cv raft add/remove (+ the auto-promote path when driven by
        hand): journal a single-server membership change. The mutate
        wrapper's commit barrier makes the ack mean 'config committed'."""
        if self.raft is None:
            from curvine_tpu.common import errors as cerr
            raise cerr.Unsupported("raft is not enabled on this master")
        return self.raft.propose_member_change(
            q.get("action", ""), q.get("node_id", 0), q.get("addr", ""))

    async def _raft_transfer(self, q):
        """cv raft transfer: drain to the target voter + TIMEOUT_NOW."""
        if self.raft is None:
            from curvine_tpu.common import errors as cerr
            raise cerr.Unsupported("raft is not enabled on this master")
        target = await self.raft.transfer_leadership(q.get("target"))
        return {"target": target}

    def _set_attr(self, q):
        opts = SetAttrOpts.from_wire(q.get("opts", {}))
        self.acl.check_set_attr(UserCtx.from_req(q), q["path"], opts)
        self.fs.set_attr(q["path"], opts)
        node = self.fs.tree.resolve(q["path"])
        if node is not None:
            self.ttl.index(node.id, node.mtime, node.storage_policy.ttl_ms)
        return {}

    def _symlink(self, q):
        self.acl.check(UserCtx.from_req(q), q["link"], W | X, on_parent=True)
        return {"status": self.fs.symlink(q["target"], q["link"]).to_wire()}

    def _link(self, q):
        ctx = UserCtx.from_req(q)
        self.acl.check(ctx, q["src"], 0)
        self.acl.check(ctx, q["dst"], W | X, on_parent=True)
        return {"status": self.fs.link(q["src"], q["dst"]).to_wire()}

    def _resize(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], W)
        self.fs.resize_file(q["path"], q["len"])
        return {}

    def _free(self, q):
        self.acl.check(UserCtx.from_req(q), q["path"], W)
        freed = self.fs.free(q["path"], q.get("recursive", False))
        self.quota.invalidate(q["path"])
        return {"freed": freed}

    def _list_options(self, q):
        """Filtered/paged listing. Parity: list_options in filesystem.rs —
        supports glob filtering, dirs-only/files-only, offset+limit."""
        import fnmatch
        statuses = self.fs.list_status(q["path"])
        pattern = q.get("pattern")
        if pattern:
            statuses = [s for s in statuses
                        if fnmatch.fnmatch(s.name, pattern)]
        if q.get("dirs_only"):
            statuses = [s for s in statuses if s.is_dir]
        if q.get("files_only"):
            statuses = [s for s in statuses if not s.is_dir]
        offset = q.get("offset", 0)
        limit = q.get("limit", 0)
        total = len(statuses)
        if limit:
            statuses = statuses[offset:offset + limit]
        elif offset:
            statuses = statuses[offset:]
        return {"statuses": [s.to_wire() for s in statuses], "total": total}

    def _get_lock(self, q):
        return {"locks": [l.to_wire()
                          for l in self.locks.get_lock(q["path"])]}

    def _set_lock(self, q):
        if q.get("release"):
            return {"released": self.locks.release(q["path"], q["owner"])}
        info = self.locks.set_lock(q["path"], q["owner"],
                                   kind=q.get("kind", "exclusive"),
                                   ttl_ms=q.get("ttl_ms", 60_000))
        return {"lock": info.to_wire()}

    def _list_lock(self, q):
        return {"locks": [l.to_wire() for l in self.locks.list_locks()]}

    def _assign_worker(self, q):
        """Pick a worker for a client (short-circuit target / load work).
        Parity: RpcCode::AssignWorker."""
        chosen = self.fs.policy.choose(
            self.fs.workers.live_workers(), 1,
            client_host=q.get("client_host", ""),
            exclude=set(q.get("exclude_workers", [])),
            ici_coords=q.get("ici_coords"))
        return {"worker": chosen[0].address.to_wire()}

    def _metrics_report(self, q):
        """Clients push counters (aggregated into master metrics) and
        their finished trace spans (ingested into the master's span
        store so trace assembly sees the client side of every request).
        Parity: RpcCode::MetricsReport."""
        for name, value in (q.get("counters") or {}).items():
            self.metrics.inc(f"client.{name}", value)
        spans = q.get("spans")
        if spans:
            self.tracer.ingest(spans)
        return {}

    def _get_spans(self, q):
        """One trace's spans from this master's store; with
        ``collect=True`` the request fans out to the workers too and
        returns the merged set (web /api/trace and `cv trace` use
        this)."""
        tid = str(q.get("trace_id", ""))
        if q.get("collect"):
            return self.collect_trace(tid)        # awaited by _h
        return {"spans": self.tracer.spans_for(tid)}

    async def collect_trace(self, trace_id: str) -> dict:
        """Merge this master's spans (incl. client-pushed ones) with
        every serving worker's over GET_SPANS; a slow/dead worker costs
        the collect timeout, never the assembly."""
        spans = list(self.tracer.spans_for(trace_id))
        timeout = self.conf.obs.trace_collect_timeout_ms / 1000.0
        payload = pack({"trace_id": trace_id})

        async def fetch(w):
            a = w.address
            conn = await self._obs_pool.get(
                f"{a.ip_addr or a.hostname}:{a.rpc_port}")
            rep = await conn.call(RpcCode.GET_SPANS, data=payload,
                                  timeout=timeout)
            return (unpack(rep.data) or {}).get("spans", [])

        workers = self.fs.workers.serving_workers()
        if workers:
            results = await asyncio.wait_for(
                asyncio.gather(*(fetch(w) for w in workers),
                               return_exceptions=True),
                timeout + 1.0)
            for r in results:
                if isinstance(r, list):
                    spans.extend(r)
                else:
                    log.debug("span collect from a worker failed: %s", r)
        return {"spans": spans}

    def _cluster_health(self, q):
        """Cluster-health rollup (monitor + watchdog snapshot).
        Parity: master_monitor.rs state + fs_dir_watchdog.rs sentinel."""
        return self.monitor.health()

    @staticmethod
    def _with_identity(q: dict, r: dict) -> dict:
        """Batch RPCs carry identity on the OUTER request; it must be
        stamped onto every inner one (and win over anything smuggled
        there) or ACL/lease checks would see the default superuser."""
        ident = {k: q[k] for k in ("user", "groups", "client_name",
                                   "client_id") if k in q}
        return {**r, **ident}

    def _create_files_batch(self, q):
        # identity fields and the caller ctx are batch-invariant: hoist
        # them out of the per-item loop (hot at namespace-bench rates)
        ident = {k: q[k] for k in ("user", "groups", "client_name",
                                   "client_id") if k in q}
        ctx = UserCtx.from_req(q)
        return {"responses": [self._create_file({**r, **ident}, ctx=ctx)
                              for r in q["requests"]]}

    _META_BATCH_OPS = None      # lazily bound: op name -> handler

    def _meta_batch(self, q):
        """Heterogeneous metadata batch (META_BATCH): mkdir/create/delete
        lists amortize per-op round trips into the same journal groups.
        Per-item domain errors come back as {"error", "error_code"} so one
        bad path doesn't fail its batch-mates."""
        from curvine_tpu.common import errors as err
        if self._META_BATCH_OPS is None:
            self._META_BATCH_OPS = {"mkdir": self._mkdir,
                                    "create": self._create_file,
                                    "delete": self._delete}
        out = []
        for r in q["requests"]:
            r = self._with_identity(q, r)
            fn = self._META_BATCH_OPS.get(r.get("op"))
            try:
                if fn is None:
                    raise err.InvalidArgument(
                        f"meta_batch: unknown op {r.get('op')!r}")
                out.append(fn(r))
            except err.CurvineError as e:
                out.append({"error": str(e), "error_code": int(e.code)})
        return {"responses": out}

    def _add_blocks_batch(self, q):
        return {"responses": [self._add_block(self._with_identity(q, r))
                              for r in q["requests"]]}

    def _complete_files_batch(self, q):
        return {"responses": [self._complete_file(self._with_identity(q, r))
                              for r in q["requests"]]}

    # --- worker plane ---
    def _worker_heartbeat(self, q):
        cmds = self.fs.worker_heartbeat(q["info"])
        self.metrics.gauge("workers.live", len(self.fs.workers.live_workers()))
        wid_hb = q["info"]["address"]["worker_id"]
        evac = q.get("evac_blocks")
        if evac:
            # blocks stranded on this worker's quarantined dirs: copy
            # them elsewhere, then retire the quarantined replica. The
            # worker repeats the (bounded) set every beat until it
            # drains, so nothing here needs to be persisted.
            self.replication.enqueue_evacuation(
                wid_hb, [int(b) for b in evac])
        unhealthy = sum(1 for s in (q["info"].get("storages") or [])
                        if s.get("health", "healthy") != "healthy")
        if unhealthy or wid_hb in self._dirs_unhealthy:
            self._dirs_unhealthy[wid_hb] = unhealthy
            self.metrics.gauge("dirs.unhealthy",
                               sum(self._dirs_unhealthy.values()))
        # ICI plane: bounded snapshot of the worker's HBM export table —
        # soft state for the replication manager's device-path hints,
        # refreshed (or cleared) every beat like evac_blocks
        self.replication.note_hbm_blocks(
            wid_hb, [int(b) for b in q.get("hbm_blocks") or []])
        wm = q.get("metrics")
        if wm:
            # aggregate worker-plane byte counters (dashboard throughput);
            # lost/decommissioned workers are pruned so their final
            # snapshots don't inflate the gauges forever
            wid = q["info"]["address"]["worker_id"]
            self._worker_counters[wid] = wm
            self._prune_worker_counters()
        return cmds

    def _worker_block_report(self, q):
        return self.fs.worker_block_report(
            q["worker_id"], q.get("blocks", {}), q.get("storage_types", {}),
            incremental=q.get("incremental", False))

    def _replacement_worker(self, q):
        w = self.replication.replacement_worker(
            q["block_id"], set(q.get("exclude_workers", [])))
        return {"worker": w.address.to_wire()}

    def _decommission_worker(self, q):
        """cv node decommission/recommission: journaled intent, so it
        survives restarts and failovers. Admin (superuser) only."""
        ctx = UserCtx.from_req(q)
        if self.acl.enabled and not self.acl._is_super(ctx):
            from curvine_tpu.common import errors as cerr
            raise cerr.PermissionDenied(
                f"user={ctx.user}: decommission is superuser-only")
        self.fs.decommission_worker(q["worker_id"],
                                    on=q.get("on", True))
        w = self.fs.workers.workers.get(q["worker_id"])
        return {"state": int(w.state) if w is not None else -1}

    def _report_under_replicated(self, q):
        if not self._is_leader():
            # reject so the worker rotates to the leader instead of the
            # report being silently dropped by the gated repair queue
            from curvine_tpu.common import errors as cerr
            raise cerr.NotLeader("repair reports go to the leader")
        # a corrupt replica is FLAGGED, never summarily deleted: it
        # stops counting toward the live replica total (forcing
        # re-replication) but stays on disk as a verified last-resort
        # source until the block is back at desired strength — only then
        # does the replication manager retire the location and order the
        # physical delete. Dropping it any earlier turns possible
        # bit-rot into certain data loss if the remaining holder dies
        # mid-heal (or the mismatch was a transient read fault).
        wid = q.get("worker_id")
        bids = q.get("block_ids", [])
        # scrub verdicts (BlockStore.verify_detail): "mismatch" = bit-rot
        # (an EC cell is re-encoded from survivors), "truncated" = short
        # write (re-pull the full copy). Recorded before enqueue so the
        # dispatcher classifies with the verdict in hand.
        verdicts = q.get("verdicts")
        if verdicts:
            self.replication.note_verdicts(
                {int(k): v for k, v in verdicts.items()})
        if wid is not None:
            self.replication.enqueue_evacuation(wid, bids)
        else:
            # clients report the lost cell behind a degraded EC read
            # this way (no worker attribution — the holder is gone)
            ec_cells = getattr(self.fs, "ec_cells", {})
            lost = sum(1 for b in bids if b in ec_cells)
            if lost:
                self.metrics.inc("ec.degraded_reads", lost)
            self.replication.enqueue(bids)
        out = {"success": True}
        # degraded-commit liveness check: a writer about to commit on a
        # reduced replica set asks which survivors this master still
        # considers LIVE — a worker that died between its finish ack and
        # the commit must count as lost, not as the block's sole copy
        confirm = q.get("confirm_live")
        if confirm is not None:
            live = {w.address.worker_id for w in self.fs.workers.live_workers()}
            out["live"] = [w for w in confirm if w in live]
        return out

    def _replication_result(self, q):
        self.replication.on_result(q["block_id"], q["worker_id"],
                                   q.get("success", False),
                                   q.get("message", ""),
                                   via=q.get("via", ""))
        return {}

    def _ec_commit_stripe(self, q):
        """EC_COMMIT_STRIPE: a converting (or reconstructing) worker
        finished writing cells. Journals the stripe map (first commit)
        and registers the runtime cell locations; the replicated copies
        retire copy-first-delete-last via heartbeat pending_deletes."""
        cells = [[int(c["block_id"]), int(c["worker_id"]),
                  int(c.get("storage_type", 1))] for c in q.get("cells", [])]
        self.fs.ec_commit(q["block_id"], cells)
        self.metrics.inc("ec.stripes_committed")
        return {"success": True}

    # --- mounts ---
    def _mount(self, q):
        info = self.mounts.mount(q["cv_path"], q["ufs_path"],
                                 properties=q.get("properties"),
                                 auto_cache=q.get("auto_cache", False),
                                 write_type=q.get("write_type", 0),
                                 ttl_ms=q.get("ttl_ms", 0),
                                 ttl_action=q.get("ttl_action", 0),
                                 storage_type=q.get("storage_type", ""),
                                 block_size=q.get("block_size", 0),
                                 replicas=q.get("replicas", 0),
                                 access_mode=q.get("access_mode", "rw"))
        return {"mount": info.to_wire()}

    def _umount(self, q):
        self.mounts.umount(q["cv_path"])
        return {}

    def _update_mount(self, q):
        info = self.mounts.update(q["cv_path"], properties=q.get("properties"),
                                  auto_cache=q.get("auto_cache"),
                                  ttl_ms=q.get("ttl_ms"),
                                  ttl_action=q.get("ttl_action"),
                                  access_mode=q.get("access_mode"))
        return {"mount": info.to_wire()}

    def _mount_table(self, q):
        return {"mounts": [m.to_wire() for m in self.mounts.table()]}

    def _mount_info(self, q):
        m = self.mounts.get_mount(q["path"])
        return {"mount": m.to_wire() if m else None}

    # --- jobs ---
    def _submit_job(self, q):
        job = self.jobs.submit(q.get("kind", "load"), q["path"],
                               recursive=q.get("recursive", True),
                               replicas=q.get("replicas", 1))
        return {"job_id": job.job_id}

    def _prefetch_window(self, q):
        """Epoch-aware prefetch advise (docs/caching.md): the client
        names its read cursor in the deterministic epoch order; the
        job manager keeps a rolling window of upcoming shards warm."""
        job = self.jobs.advise_prefetch(
            q["path"], cursor=int(q.get("cursor", 0)),
            window=int(q.get("window", 8)), epoch=int(q.get("epoch", 0)),
            seed=int(q.get("seed", 0)))
        return {"job_id": job.job_id, "state": int(job.state),
                "cursor": job.cursor, "window": job.window,
                "planned": getattr(job, "_next", 0),
                "total": job.total_files}

    def _job_status(self, q):
        return {"job": self.jobs.status(q["job_id"]).to_wire()}

    def _cancel_job(self, q):
        self.jobs.cancel(q["job_id"])
        return {}

    def _report_task(self, q):
        self.jobs.report_task(q["task"])
        return {}
