"""Retry cache: dedupe retried non-idempotent RPCs.

Parity: curvine-server/src/master/fs/fs_retry_cache.rs. Keyed by the
client-supplied (client_id, call_id); remembers the serialized response for
a TTL so a retransmitted mutation isn't applied twice."""

from __future__ import annotations

import time
from collections import OrderedDict


class RetryCache:
    def __init__(self, capacity: int = 100_000, ttl_ms: int = 600_000):
        self.capacity = capacity
        self.ttl_ms = ttl_ms
        self._entries: OrderedDict[tuple, tuple[float, object]] = OrderedDict()

    def get(self, key: tuple) -> object | None:
        ent = self._entries.get(key)
        if ent is None:
            return None
        ts, value = ent
        if (time.time() - ts) * 1000 > self.ttl_ms:
            del self._entries[key]
            return None
        self._entries.move_to_end(key)
        return value

    def put(self, key: tuple, value: object) -> None:
        self._entries[key] = (time.time(), value)
        self._entries.move_to_end(key)
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
