"""Sharded namespace: a multi-core metadata plane (HDFS-federation
style hash partition of the inode tree).

With ``master.meta_shards = N > 1`` the master RPC endpoint becomes a
thin ROUTER: every namespace request is forwarded over a local framed
connection (the coalesced transport — rpc/transport.py) to one of N
shard processes, each a full single-writer metadata actor with its own
event loop, InodeTree partition, journal directory and GroupCommitter.
The partition function is the hash of the PARENT directory path, so a
create and its parent walk land on one shard, and one directory's
listing is owned by one shard.

Invariants and protocol:

- **Every-dir-everywhere**: directory inodes are broadcast to every
  shard (MKDIR fans out), so path resolution works on any shard; only
  FILES are partitioned. The router keeps an LRU of directories it has
  already ensured everywhere and re-broadcasts an idempotent mkdir
  (superuser identity, skeleton only) on misses — e.g. after a router
  restart or for parents created implicitly by ``create_parent``.
- **Striped ids**: shard k of N allocates inode/block ids ≡ k (mod N)
  (InodeTree id_stride/id_offset), so ids are globally unique with no
  cross-shard coordination and journal replay stays deterministic.
- **Cross-shard rename/link** run a presumed-abort two-phase commit:
  prepare is journaled on both participants (a durable tx record each),
  then commit lands on the dst shard FIRST (its record flips to
  "committed" and is retained), then on the src shard, then a forget
  clears the dst record. The recovery sweep on router start resolves
  in-doubt txs: any "committed" record ⇒ roll forward everywhere,
  otherwise abort everywhere. Directory renames are Unsupported in
  sharded mode (they would re-hash every descendant).
- **Workers** heartbeat the router, which re-broadcasts to every shard
  so each shard's WorkerMap (placement input) stays live; block-report
  orphans are the INTERSECTION across shards (a block is garbage only
  if no shard owns it); per-shard pending delete commands are unioned
  into the heartbeat reply.
- ``meta_shards = 1`` never constructs any of this — the in-process
  path is byte-for-byte unchanged — and sharding is mutually exclusive
  with raft HA (enforced at MasterServer init; see
  docs/metadata-scale.md for the matrix).
"""

from __future__ import annotations

import asyncio
import copy
import logging
import os
import uuid
import zlib
from collections import OrderedDict

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import now_ms
from curvine_tpu.rpc.codes import RpcCode
from curvine_tpu.rpc.frame import pack, unpack

log = logging.getLogger(__name__)

# identity fields a router-synthesized request must carry forward
_IDENT_KEYS = ("user", "groups", "client_name", "client_id")


def shard_of(path: str, n: int) -> int:
    """Stable shard index for a normalized path: hash of the parent
    directory, so all direct entries of one directory co-locate."""
    if n <= 1:
        return 0
    parent = path.rsplit("/", 1)[0] or "/"
    return zlib.crc32(parent.encode("utf-8")) % n


def parent_of(path: str) -> str:
    return path.rsplit("/", 1)[0] or "/"


def derive_shard_conf(conf, idx: int):
    """A shard child's ClusterConf: own journal/meta dirs under the
    router's, ephemeral port, no raft, no nested sharding. Inproc
    shards keep their native read mirror (built but never served —
    the router's FRONT mirror routes to them via mm_fleet_attach);
    process-backend children would maintain a mirror nothing can
    reach, so theirs is disabled."""
    sc = copy.deepcopy(conf)
    mc = sc.master
    base = mc.journal_dir.rstrip("/")
    mc.journal_dir = f"{base}/shard{idx}"
    mc.meta_dir = (mc.meta_dir.rstrip("/") or base + "-meta") + f"/shard{idx}"
    mc.rpc_port = 0
    if mc.shard_backend != "inproc":
        mc.fast_meta = False
    mc.raft_peers = []
    mc.meta_shards = 1
    return sc


def shard_entry(conf, idx: int, count: int, journal: bool, conn) -> None:
    """Child-process main (multiprocessing spawn target): run one shard
    MasterServer until SIGTERM, reporting the bound port through the
    pipe. Lives at module top level so spawn can import it."""
    import signal

    logging.basicConfig(
        level=logging.WARNING,
        format=f"%(asctime)s shard{idx} %(levelname)s %(name)s %(message)s")

    async def main():
        from curvine_tpu.master.server import MasterServer
        server = MasterServer(conf, journal=journal,
                              shard_id=idx, shard_count=count)
        await server.start()
        conn.send(server.rpc.port)
        conn.close()
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop.set)
        ppid = os.getppid()
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), timeout=2.0)
            except asyncio.TimeoutError:
                if os.getppid() != ppid:      # router died; don't orphan
                    break
        await server.stop()

    asyncio.run(main())


class _ProcShard:
    """A shard living in a multiprocessing (spawn) child."""

    def __init__(self, idx: int, proc, addr: str):
        self.idx = idx
        self.proc = proc
        self.addr = addr
        self.pid = proc.pid

    async def stop(self) -> None:
        if self.proc.is_alive():
            self.proc.terminate()
            for _ in range(50):                  # 5s graceful window
                if not self.proc.is_alive():
                    break
                await asyncio.sleep(0.1)
            if self.proc.is_alive():
                self.proc.kill()
        self.proc.join(timeout=5)


class _InprocShard:
    """A shard MasterServer sharing the router's loop (tests and
    single-core boxes: same wire protocol, no process isolation)."""

    def __init__(self, idx: int, server):
        self.idx = idx
        self.server = server
        self.addr = server.addr
        self.pid = os.getpid()

    async def stop(self) -> None:
        await self.server.stop()


class ShardRouter:
    """Routes namespace RPCs from the master endpoint to shard actors,
    runs the cross-shard 2PC coordinator and the stats poller."""

    def __init__(self, master, journal: bool = True):
        self.master = master
        self.conf = master.conf
        mc = self.conf.master
        self.n = mc.meta_shards
        self.journal = journal
        self.backend = mc.shard_backend
        self.shards: list = []
        self._pools: list = []
        # directories already broadcast-created on every shard
        self._ensured: OrderedDict[str, bool] = OrderedDict()
        self._ensured_cap = max(256, mc.shard_dir_cache)
        # test hook: called at 2PC phase boundaries; raising simulates a
        # coordinator crash between phases (recovery sweep cleans up)
        self.fault_hook = None
        self._stats_prev: list[dict] = [{} for _ in range(self.n)]
        self._stats_prev_ts = 0.0
        self._stats_cache: list[dict] = [{} for _ in range(self.n)]

    # ------------------------------------------------------------------
    # lifecycle

    async def start(self) -> None:
        from curvine_tpu.rpc.client import ConnectionPool
        if self.backend == "inproc":
            from curvine_tpu.master.server import MasterServer
            for i in range(self.n):
                s = MasterServer(derive_shard_conf(self.conf, i),
                                 journal=self.journal,
                                 shard_id=i, shard_count=self.n)
                await s.start()
                if self.master.leases is not None:
                    # shard-side TTL reclaim pushes META_INVALIDATE
                    # through the ROUTER's lease plane (clients hold
                    # leases on router conns, not shard conns). The
                    # process backend can't reach it — there the lease
                    # TTL alone bounds staleness.
                    s.ttl.on_expire = \
                        lambda path: self.master.leases.invalidate([path])
                self.shards.append(_InprocShard(i, s))
            front = self.master.fastmeta
            if front is not None:
                members = [s.server.fastmeta for s in self.shards]
                if all(m is not None for m in members):
                    # the fast-port front answers from the shard fleet's
                    # mirrors; MasterServer.start serves it AFTER this
                    for m in members:
                        front.fleet_attach(m)
                else:
                    # a member failed to build: the front would serve
                    # holes — disable the whole plane instead
                    front.close()
                    self.master.fastmeta = None
        else:
            import multiprocessing
            ctx = multiprocessing.get_context("spawn")
            waits = []
            for i in range(self.n):
                rd, wr = ctx.Pipe(duplex=False)
                p = ctx.Process(
                    target=shard_entry,
                    args=(derive_shard_conf(self.conf, i), i, self.n,
                          self.journal, wr),
                    daemon=True, name=f"cv-shard{i}")
                p.start()
                wr.close()
                waits.append((i, p, rd))
            loop = asyncio.get_running_loop()
            for i, p, rd in waits:
                port = await loop.run_in_executor(
                    None, self._await_port, p, rd)
                self.shards.append(_ProcShard(
                    i, p, f"{self.conf.master.hostname}:{port}"))
        self._pools = [ConnectionPool(size=2) for _ in range(self.n)]
        await self.recovery_sweep()
        log.info("shard router up: %d shards (%s backend) at %s",
                 self.n, self.backend, [s.addr for s in self.shards])

    @staticmethod
    def _await_port(proc, rd, timeout: float = 60.0) -> int:
        if rd.poll(timeout):
            port = rd.recv()
            rd.close()
            return port
        proc.terminate()
        raise err.CurvineError(
            f"shard child pid={proc.pid} failed to report its port "
            f"within {timeout}s")

    async def stop(self) -> None:
        for pool in self._pools:
            await pool.close()
        self._pools = []
        for s in self.shards:
            try:
                await s.stop()
            except Exception as e:  # noqa: BLE001 — teardown best-effort
                log.warning("shard %d stop: %s", s.idx, e)
        self.shards = []

    # ------------------------------------------------------------------
    # plumbing

    def shard_for(self, path: str) -> int:
        return shard_of(path, self.n)

    async def call(self, idx: int, code: int, req: dict,
                   deadline=None) -> dict:
        conn = await self._pools[idx].get(self.shards[idx].addr)
        rep = await conn.call(code, data=pack(req), deadline=deadline)
        return unpack(rep.data) or {}

    async def _gather(self, code: int, req: dict, deadline=None,
                      idxs=None) -> list:
        """Fan a request out; per-shard CurvineErrors come back in-slot
        (callers merge), anything else propagates."""
        idxs = range(self.n) if idxs is None else idxs
        outs = await asyncio.gather(
            *(self.call(i, code, req, deadline) for i in idxs),
            return_exceptions=True)
        for o in outs:
            if isinstance(o, BaseException) and \
                    not isinstance(o, err.CurvineError):
                raise o
        return list(outs)

    @staticmethod
    def _merge_or_raise(outs: list) -> list:
        oks = [o for o in outs if not isinstance(o, BaseException)]
        if not oks:
            raise next(o for o in outs if isinstance(o, BaseException))
        return oks

    def _ident(self, q: dict) -> dict:
        return {k: q[k] for k in _IDENT_KEYS if k in q}

    def _note_dir(self, path: str) -> None:
        self._ensured[path] = True
        self._ensured.move_to_end(path)
        while len(self._ensured) > self._ensured_cap:
            self._ensured.popitem(last=False)

    def _drop_dirs(self, path: str) -> None:
        """Forget a deleted/renamed directory subtree."""
        pre = path.rstrip("/") + "/"
        for k in [k for k in self._ensured if k == path or k.startswith(pre)]:
            self._ensured.pop(k, None)

    async def ensure_parent(self, path: str, deadline=None) -> None:
        """Every-dir-everywhere: make sure the parent directory chain of
        `path` exists on every shard. Idempotent mkdir broadcast under
        the superuser (skeleton replication, not a user create — real
        ACL enforcement happened when the directory was first made)."""
        parent = parent_of(path)
        if parent == "/" or parent in self._ensured:
            return
        mc = self.conf.master
        req = {"path": parent, "create_parent": True,
               "user": mc.superuser, "groups": [mc.supergroup]}
        self._merge_or_raise(
            await self._gather(RpcCode.MKDIR, req, deadline))
        self._note_dir(parent)

    # ------------------------------------------------------------------
    # routed handlers (installed by MasterServer._register_shard_routes)

    async def r_forward(self, code: int, q: dict, msg) -> dict:
        """Single-shard ops routed by the path's parent directory."""
        key = "link" if code == RpcCode.SYMLINK else "path"
        path = q[key]
        if code in (RpcCode.CREATE_FILE, RpcCode.APPEND_FILE,
                    RpcCode.RESIZE_FILE, RpcCode.SYMLINK):
            # read-only-mount enforcement lives at the router: shards
            # hold no mount table
            self.master.fs._mount_write_guard(path)
        if code in (RpcCode.CREATE_FILE, RpcCode.MKDIR, RpcCode.SYMLINK):
            await self.ensure_parent(path, msg.deadline)
        if code == RpcCode.MKDIR:
            return await self.r_mkdir(q, msg)
        return await self.call(self.shard_for(path), code, q, msg.deadline)

    async def r_mkdir(self, q: dict, msg) -> dict:
        outs = self._merge_or_raise(
            await self._gather(RpcCode.MKDIR, q, msg.deadline))
        self._note_dir(q["path"])
        return outs[0]

    async def r_file_status(self, q: dict, msg) -> dict:
        try:
            return await self.call(self.shard_for(q["path"]),
                                   RpcCode.FILE_STATUS, q, msg.deadline)
        except err.FileNotFound:
            st = await self.master.mounts.ufs_status(q["path"])
            if st is None:
                raise
            return {"status": st.to_wire()}

    async def r_exists(self, q: dict, msg) -> dict:
        out = await self.call(self.shard_for(q["path"]), RpcCode.EXISTS,
                              q, msg.deadline)
        if not out.get("exists"):
            st = await self.master.mounts.ufs_status(q["path"])
            return {"exists": st is not None}
        return out

    async def r_list_status(self, q: dict, msg) -> dict:
        outs = await self._gather(RpcCode.LIST_STATUS, q, msg.deadline)
        oks = [o for o in outs if not isinstance(o, BaseException)]
        if not oks:
            # surface UFS-only listings like the single-shard path would
            if await self.master.mounts.ufs_status(q["path"]) is None:
                raise next(o for o in outs if isinstance(o, BaseException))
            oks = [{"statuses": []}]
        merged: dict[str, dict] = {}
        for s in await self.master.mounts.ufs_list(q["path"]):
            merged[s.name] = s.to_wire()
        for o in oks:
            for w in o.get("statuses", []):
                merged[w.get("name") or w.get("path", "")] = w
        return {"statuses": [merged[k] for k in sorted(merged)]}

    async def r_list_options(self, q: dict, msg) -> dict:
        sub = {k: v for k, v in q.items() if k not in ("offset", "limit")}
        outs = self._merge_or_raise(
            await self._gather(RpcCode.LIST_OPTIONS, sub, msg.deadline))
        merged: dict[str, dict] = {}
        for o in outs:
            for w in o.get("statuses", []):
                merged[w.get("name") or w.get("path", "")] = w
        names = sorted(merged)
        total = len(names)
        offset, limit = q.get("offset", 0), q.get("limit")
        names = names[offset:offset + limit] if limit else names[offset:]
        return {"statuses": [merged[k] for k in names], "total": total}

    async def r_content_summary(self, q: dict, msg) -> dict:
        # mount-intersection refusal is the ROUTER's job (shards hold no
        # mount table) — mirror of the in-process handler's check
        path = q["path"]
        mounts = self.master.mounts
        prefix = (path.rstrip("/") or "") + "/"
        if mounts.get_mount(path) is not None or any(
                m.cv_path.startswith(prefix) for m in mounts.table()):
            raise err.Unsupported(
                f"{path} intersects mounts: aggregate the unified "
                "listing client-side")
        outs = self._merge_or_raise(
            await self._gather(RpcCode.CONTENT_SUMMARY, q, msg.deadline))
        return {
            "length": sum(o.get("length", 0) for o in outs),
            "file_count": sum(o.get("file_count", 0) for o in outs),
            # every shard holds the full directory skeleton: take max,
            # not sum, or each dir would count once per shard
            "directory_count": max(o.get("directory_count", 0)
                                   for o in outs),
        }

    async def r_set_attr(self, q: dict, msg) -> dict:
        self.master.fs._mount_write_guard(q["path"])
        # uniform broadcast: for files only the owner shard succeeds;
        # for directories every shard applies (skeleton attrs in sync)
        outs = self._merge_or_raise(
            await self._gather(RpcCode.SET_ATTR, q, msg.deadline))
        return outs[0]

    async def r_free(self, q: dict, msg) -> dict:
        outs = self._merge_or_raise(
            await self._gather(RpcCode.FREE, q, msg.deadline))
        return {"freed": sum(o.get("freed", 0) for o in outs)}

    async def r_delete(self, q: dict, msg) -> dict:
        path, recursive = q["path"], q.get("recursive", False)
        self.master.fs._mount_write_guard(path, subtree=recursive)
        owner = self.shard_for(path)
        st = (await self.call(owner, RpcCode.FILE_STATUS, q,
                              msg.deadline))["status"]
        if not st["is_dir"]:
            return await self.call(owner, RpcCode.DELETE, q, msg.deadline)
        if not recursive:
            # non-recursive dir delete: the emptiness gate runs at the
            # router over ALL shards (each shard only sees its own
            # entries); the broadcast below then force-clears the
            # skeleton. Weakly consistent like the rest of the plane.
            listing = await self.r_list_options(
                {**q, "limit": 1}, msg)
            if listing["total"]:
                raise err.DirNotEmpty(path)
        bq = {**q, "recursive": True}
        outs = await self._gather(RpcCode.DELETE, bq, msg.deadline)
        self._merge_or_raise(
            [o for o in outs if not isinstance(o, err.FileNotFound)]
            or outs)
        self._drop_dirs(path)
        return {}

    async def r_rename(self, q: dict, msg) -> dict:
        src, dst = q["src"], q["dst"]
        self.master.fs._mount_write_guard(src, subtree=True)
        self.master.fs._mount_write_guard(dst)
        s_idx, d_idx = self.shard_for(src), self.shard_for(dst)
        st = (await self.call(s_idx, RpcCode.FILE_STATUS,
                              {**self._ident(q), "path": src},
                              msg.deadline))["status"]
        if st["is_dir"]:
            raise err.Unsupported(
                "directory rename in sharded namespace (meta_shards>1): "
                "it would re-hash every descendant path")
        await self.ensure_parent(dst, msg.deadline)
        if s_idx == d_idx:
            return await self.call(s_idx, RpcCode.RENAME, q, msg.deadline)
        await self._two_phase("rename", src, dst, s_idx, d_idx, q,
                              msg.deadline)
        return {"result": True}

    async def r_link(self, q: dict, msg) -> dict:
        src, dst = q["src"], q["dst"]
        self.master.fs._mount_write_guard(dst)
        s_idx, d_idx = self.shard_for(src), self.shard_for(dst)
        await self.ensure_parent(dst, msg.deadline)
        if s_idx == d_idx:
            return await self.call(s_idx, RpcCode.LINK, q, msg.deadline)
        await self._two_phase("link", src, dst, s_idx, d_idx, q,
                              msg.deadline)
        return await self.call(d_idx, RpcCode.FILE_STATUS,
                               {**self._ident(q), "path": dst},
                               msg.deadline)

    # --- batches: split by owner shard, forward concurrently, stitch
    # the per-item responses back into request order ---

    async def r_batch(self, code: int, q: dict, msg) -> dict:
        reqs = q["requests"]
        outer = {k: v for k, v in q.items() if k != "requests"}
        key = "path"
        buckets: dict[int, list[tuple[int, dict]]] = {}
        parents = set()
        for pos, r in enumerate(reqs):
            if code == RpcCode.META_BATCH and r.get("op") != "create":
                # mkdir/delete items follow broadcast semantics: give
                # every shard a copy, answer from the path's owner
                for i in range(self.n):
                    buckets.setdefault(i, []).append((pos, r))
                if r.get("op") == "mkdir":
                    self._note_dir(r["path"])
                continue
            if code in (RpcCode.CREATE_FILES_BATCH, RpcCode.META_BATCH):
                parents.add(parent_of(r[key]))
            buckets.setdefault(self.shard_for(r[key]), []).append((pos, r))
        for p in sorted(parents):
            if p != "/" and p not in self._ensured:
                await self.ensure_parent(p + "/x", msg.deadline)
        idxs = sorted(buckets)
        outs = await asyncio.gather(
            *(self.call(i, code,
                        {**outer, "requests": [r for _p, r in buckets[i]]},
                        msg.deadline) for i in idxs))
        merged: list = [None] * len(reqs)
        for i, out in zip(idxs, outs):
            for (pos, r), rep in zip(buckets[i], out["responses"]):
                owner = self.shard_for(r.get(key, "/"))
                if merged[pos] is None or owner == i:
                    merged[pos] = rep
        return {"responses": merged}

    # --- worker plane: router-local + shard broadcast ---

    async def r_worker_heartbeat(self, q: dict, msg, local) -> dict:
        cmds = local(q)                       # router worker map + gauges
        outs = await self._gather(RpcCode.WORKER_HEARTBEAT, q, msg.deadline)
        deletes = set(cmds.get("delete_blocks", []))
        report_now = bool(cmds.get("report_now"))
        for o in outs:
            if isinstance(o, BaseException):
                continue
            deletes.update(o.get("delete_blocks", []))
            report_now = report_now or bool(o.get("report_now"))
        cmds["delete_blocks"] = sorted(deletes)
        if report_now:
            cmds["report_now"] = True
        return cmds

    async def r_worker_block_report(self, q: dict, msg) -> dict:
        outs = self._merge_or_raise(
            await self._gather(RpcCode.WORKER_BLOCK_REPORT, q,
                               msg.deadline))
        # a block is an orphan only if EVERY shard disowns it
        orphans = None
        for o in outs:
            got = set(o.get("delete_blocks", []))
            orphans = got if orphans is None else (orphans & got)
        return {"delete_blocks": sorted(orphans or ())}

    # ------------------------------------------------------------------
    # cross-shard two-phase coordinator

    def _crash_point(self, stage: str) -> None:
        if self.fault_hook is not None:
            self.fault_hook(stage)

    async def _two_phase(self, op: str, src: str, dst: str,
                         s_idx: int, d_idx: int, q: dict,
                         deadline=None) -> None:
        txid = uuid.uuid4().hex
        ident = self._ident(q)
        base = {**ident, "txid": txid, "op": op, "src": src, "dst": dst}
        payload = await self.call(
            s_idx, RpcCode.SHARD_TX, {**base, "phase": "prepare_src"},
            deadline)
        self._crash_point("after_prepare_src")
        try:
            await self.call(
                d_idx, RpcCode.SHARD_TX,
                {**base, "phase": "prepare_dst", "rec": payload["rec"]},
                deadline)
        except err.CurvineError:
            await self.call(s_idx, RpcCode.SHARD_TX,
                            {**base, "phase": "abort"}, deadline)
            raise
        self._crash_point("after_prepare_dst")
        # commit point: dst first — its retained "committed" record is
        # what the recovery sweep keys roll-forward on
        await self.call(d_idx, RpcCode.SHARD_TX,
                        {**base, "phase": "commit"}, deadline)
        self._crash_point("after_commit_dst")
        await self.call(s_idx, RpcCode.SHARD_TX,
                        {**base, "phase": "commit"}, deadline)
        self._crash_point("after_commit_src")
        await self.call(d_idx, RpcCode.SHARD_TX,
                        {**base, "phase": "forget"}, deadline)

    async def recovery_sweep(self) -> None:
        """Resolve in-doubt cross-shard txs after a crash: roll forward
        any tx with a committed participant, abort the rest (presumed
        abort). Runs on every router start; idempotent."""
        txs: dict[str, list[tuple[int, dict]]] = {}
        for i in range(self.n):
            try:
                out = await self.call(i, RpcCode.SHARD_TX_LIST, {})
            except Exception as e:  # noqa: BLE001 — sweep is best-effort
                log.warning("tx sweep: shard %d unreadable: %s", i, e)
                continue
            for rec in out.get("txs", []):
                txs.setdefault(rec["txid"], []).append((i, rec))
        for txid, parts in txs.items():
            committed = any(r["state"] == "committed" for _i, r in parts)
            phase = "commit" if committed else "abort"
            log.info("tx sweep: %s %s (%d participant records)",
                     phase, txid, len(parts))
            for i, rec in parts:
                if rec["state"] == "prepared":
                    await self.call(i, RpcCode.SHARD_TX,
                                    {"txid": txid, "phase": phase})
            if committed:
                # src committed above; clear the dst marker(s) last
                for i, rec in parts:
                    if rec["state"] == "committed":
                        await self.call(i, RpcCode.SHARD_TX,
                                        {"txid": txid, "phase": "forget"})

    # ------------------------------------------------------------------
    # observability

    async def poll_stats(self) -> list[dict]:
        """Refresh per-shard stats; computes qps from the handled-count
        delta since the previous poll. Feeds /metrics gauges, the
        SHARD_TABLE handler, `cv report` and the web UI."""
        now = now_ms() / 1000.0
        dt = max(1e-3, now - self._stats_prev_ts) \
            if self._stats_prev_ts else 0.0
        outs = await self._gather(RpcCode.SHARD_STATS, {})
        table = []
        metrics = self.master.metrics
        for i, o in enumerate(outs):
            if isinstance(o, BaseException):
                row = {"shard": i, "addr": self.shards[i].addr,
                       "state": "unreachable", "error": str(o)}
                table.append(row)
                continue
            prev = self._stats_prev[i]
            qps = 0.0
            if dt and "handled" in prev:
                qps = max(0.0, (o.get("handled", 0) -
                                prev.get("handled", 0)) / dt)
            row = {"shard": i, "addr": self.shards[i].addr,
                   "pid": self.shards[i].pid, "state": "up",
                   "qps": round(qps, 1), **o}
            table.append(row)
            self._stats_prev[i] = o
            for k in ("inodes", "blocks", "journal_seq", "queue_depth"):
                metrics.gauge(f"shard.{i}.{k}", o.get(k, 0))
            metrics.gauge(f"shard.{i}.qps", qps)
        self._stats_prev_ts = now
        self._stats_cache = table
        return table

    @property
    def stats(self) -> list[dict]:
        return self._stats_cache
