"""Quota management + master-driven cache eviction.

Parity: curvine-server/src/master/quota/ (quota_manager.rs,
eviction/{evictor,lfu}.rs). Two responsibilities:

* per-directory quotas — byte/file limits stored on the inode
  (``quota.bytes`` / ``quota.files`` x-attrs), enforced against the
  subtree's usage on create/add_block;
* cluster cache pressure — when aggregate available capacity drops below
  the watermark, free the coldest complete files (LRU by atime, LFU tie
  break via access counter) until below the low watermark. Freed files
  keep their metadata (UFS-backed data stays reachable)."""

from __future__ import annotations

import asyncio
import logging

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import StorageState

log = logging.getLogger(__name__)

QUOTA_BYTES = "quota.bytes"
QUOTA_FILES = "quota.files"


class QuotaManager:
    def __init__(self, fs, high_water: float = 0.92, low_water: float = 0.80,
                 check_interval_s: float = 5.0, usage_ttl_s: float = 2.0):
        self.fs = fs
        self.high_water = high_water
        self.low_water = low_water
        self.check_interval_s = check_interval_s
        self.usage_ttl_s = usage_ttl_s
        # quota'd-dir usage cache: inode id -> [bytes, files, expiry].
        # The subtree walk is O(subtree) — unaffordable per create on big
        # namespaces — so enforcement reads a TTL'd snapshot and bumps it
        # optimistically for admissions inside the window (bursts between
        # walks still count against the quota).
        self._usage_cache: dict[int, list] = {}

    # ---------------- quotas ----------------

    def set_quota(self, path: str, max_bytes: int | None = None,
                  max_files: int | None = None) -> None:
        node = self.fs.tree.resolve(path)
        if node is None or not node.is_dir:
            raise err.NotADirectory(path)
        from curvine_tpu.common.types import SetAttrOpts
        add, remove = {}, []
        for key, v in ((QUOTA_BYTES, max_bytes), (QUOTA_FILES, max_files)):
            if v is None:
                remove.append(key)
            else:
                add[key] = str(v).encode()
        self.fs.set_attr(path, SetAttrOpts(add_x_attr=add,
                                           remove_x_attr=remove))

    def get_quota(self, path: str) -> dict:
        node = self.fs.tree.resolve(path)
        if node is None:
            raise err.FileNotFound(path)
        usage_bytes, usage_files = self._usage(node)
        return {
            "bytes": _int_attr(node, QUOTA_BYTES),
            "files": _int_attr(node, QUOTA_FILES),
            "used_bytes": usage_bytes,
            "used_files": usage_files,
        }

    def _usage(self, node) -> tuple[int, int]:
        if not node.is_dir:
            return node.len, 1
        b = f = 0
        for _name, child in self.fs.tree.children(node):
            cb, cf = self._usage(child)
            b += cb
            f += cf
        return b, f

    def _cached_usage(self, node) -> list:
        """[bytes, files, expiry, walked_clean] for a quota'd dir,
        rewalked past TTL. walked_clean: the snapshot came straight from
        a walk (no optimistic bumps since), so a denial may trust it."""
        import time
        ent = self._usage_cache.get(node.id)
        now = time.monotonic()
        if ent is None or ent[2] <= now:
            b, f = self._usage(node)
            ent = self._usage_cache[node.id] = [b, f,
                                                now + self.usage_ttl_s, True]
        return ent

    def invalidate(self, path: str) -> None:
        """Drop cached usage for every ancestor of `path` — called after
        deletes/frees/renames so freed quota is admissible immediately
        (the deny path trusts clean snapshots inside their TTL)."""
        parent, _ = self.fs.tree.resolve_parent(path)
        node = parent
        while node is not None:
            self._usage_cache.pop(node.id, None)
            node = self.fs.tree.get(node.parent_id) \
                if node.parent_id else None

    def check_create(self, path: str, new_bytes: int = 0,
                     new_files: int = 1, parent=None) -> None:
        """Walk ancestors of `path`; any quota'd dir must have room.
        Callers that already resolved the parent pass it to skip the
        path walk (create hot path)."""
        if parent is None:
            parent, _ = self.fs.tree.resolve_parent(path)
        node = parent
        while node is not None:
            xa = node.x_attr
            if not xa or (QUOTA_BYTES not in xa and QUOTA_FILES not in xa):
                node = self.fs.tree.get(node.parent_id) \
                    if node.parent_id else None
                continue
            qb = _int_attr(node, QUOTA_BYTES)
            qf = _int_attr(node, QUOTA_FILES)
            if qb is not None or qf is not None:
                import time
                ent = self._cached_usage(node)
                over = ((qb is not None and ent[0] + new_bytes > qb)
                        or (qf is not None and ent[1] + new_files > qf))
                if over and not ent[3]:
                    # a denial must be EXACT: optimistic bumps may have
                    # overshot and deletes may have freed quota inside the
                    # TTL window — rewalk ONCE before refusing. A clean
                    # walked snapshot inside its TTL is trusted, so a
                    # client hammering a full dir can't force a walk per
                    # attempt.
                    b, f = self._usage(node)
                    ent[:] = [b, f, time.monotonic() + self.usage_ttl_s,
                              True]
                ub, uf = ent[0], ent[1]
                if qb is not None and ub + new_bytes > qb:
                    raise err.QuotaExceeded(
                        f"{self.fs.tree.path_of(node)}: bytes quota {qb} "
                        f"(used {ub}, requested +{new_bytes})")
                if qf is not None and uf + new_files > qf:
                    raise err.QuotaExceeded(
                        f"{self.fs.tree.path_of(node)}: file quota {qf} "
                        f"(used {uf})")
                # count this admission against the window's snapshot
                ent[0] += new_bytes
                ent[1] += new_files
                ent[3] = False          # bumped: a denial must rewalk
            node = self.fs.tree.get(node.parent_id) \
                if node.parent_id else None

    # ---------------- cache pressure eviction ----------------

    def pressure(self) -> float:
        cap, avail = self.fs.workers.capacity()
        return (cap - avail) / cap if cap else 0.0

    def evict_once(self) -> int:
        """Free cold files until usage falls under low_water. Only files
        whose data also lives in UFS (storage state BOTH/UFS) or that are
        explicitly evictable are freed. Returns files freed."""
        cap, avail = self.fs.workers.capacity()
        if not cap or (cap - avail) / cap < self.high_water:
            return 0
        target_used = int(cap * self.low_water)
        used = cap - avail
        # coldest first: (atime, -len) — old and large go first
        candidates = sorted(
            (n for n in self.fs.tree.iter_files()
             if n.is_complete and n.blocks),
            key=lambda n: (n.atime, -n.len))
        freed = 0
        for node in candidates:
            if used <= target_used:
                break
            path = self.fs.tree.path_of(node)
            mount = self.fs.mounts.get_mount(path) if self.fs.mounts else None
            if mount is None and node.storage_policy.state == StorageState.CV:
                continue      # cache-only data: freeing would lose it
            try:
                self.fs.free(path)
                used -= node.len
                freed += 1
            except err.CurvineError as e:
                log.debug("evict %s failed: %s", path, e)
        if freed:
            log.info("cache pressure: freed %d cold files", freed)
        return freed

    async def run(self, leader_gate=None) -> None:
        while True:
            await asyncio.sleep(self.check_interval_s)
            try:
                if leader_gate is None or leader_gate():
                    self.evict_once()
            except Exception:
                log.exception("quota eviction loop")


def _int_attr(node, key: str) -> int | None:
    raw = node.x_attr.get(key)
    if raw is None:
        return None
    try:
        return int(raw.decode() if isinstance(raw, bytes) else raw)
    except (ValueError, AttributeError):
        return None
