"""Worker registry + liveness.

Parity: curvine-server/src/master/fs/state/worker_map.rs and
worker_manager.rs + heartbeat_checker.rs."""

from __future__ import annotations

import logging

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import (
    StorageInfo, WorkerAddress, WorkerInfo, WorkerState, now_ms,
)

log = logging.getLogger(__name__)


class WorkerMap:
    def __init__(self, lost_timeout_ms: int = 30_000):
        self.workers: dict[int, WorkerInfo] = {}
        self.lost_timeout_ms = lost_timeout_ms
        # decommission intents survive re-registration (and, journaled
        # through MasterFilesystem, restarts and failovers)
        self.deco_ids: set[int] = set()
        # per-worker: last block-report time vs last registration/return-
        # from-LOST time — drain completion needs report > return
        self.report_ms: dict[int, int] = {}
        self.return_ms: dict[int, int] = {}

    def heartbeat(self, address: WorkerAddress, storages: list[StorageInfo],
                  ici_coords: list[int] | None = None) -> WorkerInfo:
        info = self.workers.get(address.worker_id)
        if info is None:
            info = WorkerInfo(address=address)
            self.workers[address.worker_id] = info
            # no block report seen yet for this incarnation
            self.return_ms[address.worker_id] = now_ms()
            log.info("worker registered: %s", address)
        info.address = address
        info.storages = storages
        info.last_heartbeat_ms = now_ms()
        if ici_coords is not None:
            info.ici_coords = list(ici_coords)
        if info.state == WorkerState.LOST:
            # back from the dead: its block-map entries were purged on
            # LOST, so nothing it holds is countable until its next full
            # report (drain completion gates on this)
            self.return_ms[address.worker_id] = now_ms()
        if address.worker_id in self.deco_ids:
            # a heartbeat must never resurrect a draining worker to LIVE
            if info.state in (WorkerState.LIVE, WorkerState.LOST):
                info.state = WorkerState.DECOMMISSIONING
        elif info.state != WorkerState.LIVE:
            if info.state == WorkerState.LOST:
                log.info("worker %d back alive", address.worker_id)
            info.state = WorkerState.LIVE
        return info

    def mark_reported(self, worker_id: int) -> None:
        self.report_ms[worker_id] = now_ms()

    def has_current_report(self, worker_id: int) -> bool:
        """True when a block report has arrived since the worker's last
        registration / return from LOST — i.e. the block map's view of
        its holdings is trustworthy."""
        return self.report_ms.get(worker_id, 0) \
            > self.return_ms.get(worker_id, 0)

    def get(self, worker_id: int) -> WorkerInfo:
        info = self.workers.get(worker_id)
        if info is None:
            raise err.WorkerNotFound(f"worker {worker_id} not registered")
        return info

    def live_workers(self) -> list[WorkerInfo]:
        return [w for w in self.workers.values() if w.state == WorkerState.LIVE]

    def lost_workers(self) -> list[WorkerInfo]:
        return [w for w in self.workers.values() if w.state == WorkerState.LOST]

    def retired_workers(self) -> list[WorkerInfo]:
        """Fully drained workers: DECOMMISSIONED is the safe-to-remove
        signal, so these must stay visible in cluster reports."""
        return [w for w in self.workers.values()
                if w.state == WorkerState.DECOMMISSIONED]

    def check_lost(self) -> list[WorkerInfo]:
        """Mark workers whose heartbeat expired; returns newly-lost ones.
        A DECOMMISSIONING worker that stops heartbeating is LOST too —
        its replicas are gone, not merely draining."""
        deadline = now_ms() - self.lost_timeout_ms
        newly_lost = []
        for w in self.workers.values():
            if w.state in (WorkerState.LIVE, WorkerState.DECOMMISSIONING) \
                    and w.last_heartbeat_ms < deadline:
                w.state = WorkerState.LOST
                newly_lost.append(w)
                log.warning("worker %d lost (no heartbeat for %dms)",
                            w.address.worker_id, self.lost_timeout_ms)
        return newly_lost

    def decommission(self, worker_id: int) -> None:
        """Stop placing new blocks on the worker; existing replicas keep
        serving while the drain re-replicates them elsewhere. Parity:
        curvine-cli node --add-decommission."""
        self.deco_ids.add(worker_id)
        w = self.workers.get(worker_id)
        if w is not None and w.state == WorkerState.LIVE:
            w.state = WorkerState.DECOMMISSIONING

    def recommission(self, worker_id: int) -> None:
        self.deco_ids.discard(worker_id)
        w = self.workers.get(worker_id)
        if w is not None and w.state in (WorkerState.DECOMMISSIONING,
                                         WorkerState.DECOMMISSIONED):
            w.state = WorkerState.LIVE

    def decommissioning_workers(self) -> list[WorkerInfo]:
        return [w for w in self.workers.values()
                if w.state == WorkerState.DECOMMISSIONING]

    def serving_workers(self) -> list[WorkerInfo]:
        """Workers whose replicas are readable (LIVE + draining)."""
        return [w for w in self.workers.values()
                if w.state in (WorkerState.LIVE,
                               WorkerState.DECOMMISSIONING)]

    def capacity(self) -> tuple[int, int]:
        cap = avail = 0
        for w in self.live_workers():
            cap += w.capacity
            avail += w.available
        return cap, avail
