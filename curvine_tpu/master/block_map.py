"""Block → location map.

Parity: curvine-server/src/master/fs/state/block_map.rs. Tracks committed
block replicas per worker; reconciled by worker block reports; feeds the
replication manager's under-replicated scan."""

from __future__ import annotations

from dataclasses import dataclass, field

from curvine_tpu.common.types import BlockLocation, StorageType


@dataclass
class BlockMeta:
    block_id: int
    len: int = 0
    inode_id: int = 0
    replicas: int = 1              # desired
    locs: dict = field(default_factory=dict)   # worker_id -> BlockLocation


class BlockMap:
    def __init__(self) -> None:
        self.blocks: dict[int, BlockMeta] = {}
        # worker_id -> set of block ids (for loss handling)
        self.worker_blocks: dict[int, set[int]] = {}

    def get(self, block_id: int) -> BlockMeta | None:
        return self.blocks.get(block_id)

    def commit(self, block_id: int, length: int, worker_id: int,
               storage_type: StorageType, inode_id: int = 0,
               replicas: int = 1) -> BlockMeta:
        meta = self.blocks.get(block_id)
        if meta is None:
            meta = BlockMeta(block_id=block_id, len=length, inode_id=inode_id,
                             replicas=replicas)
            self.blocks[block_id] = meta
        meta.len = max(meta.len, length)
        if inode_id:
            meta.inode_id = inode_id
        meta.locs[worker_id] = BlockLocation(worker_id=worker_id,
                                             storage_type=storage_type)
        self.worker_blocks.setdefault(worker_id, set()).add(block_id)
        return meta

    def remove_block(self, block_id: int) -> BlockMeta | None:
        meta = self.blocks.pop(block_id, None)
        if meta:
            for wid in meta.locs:
                self.worker_blocks.get(wid, set()).discard(block_id)
        return meta

    def remove_replica(self, block_id: int, worker_id: int) -> None:
        meta = self.blocks.get(block_id)
        if meta:
            meta.locs.pop(worker_id, None)
        self.worker_blocks.get(worker_id, set()).discard(block_id)

    def worker_lost(self, worker_id: int) -> list[int]:
        """Drop all replicas on a lost worker; returns affected block ids."""
        affected = list(self.worker_blocks.pop(worker_id, set()))
        for bid in affected:
            meta = self.blocks.get(bid)
            if meta:
                meta.locs.pop(worker_id, None)
        return affected

    def under_replicated(self) -> list[BlockMeta]:
        return [m for m in self.blocks.values() if 0 < len(m.locs) < m.replicas]

    def apply_report(self, worker_id: int, held: dict[int, int],
                     storage_types: dict[int, int],
                     incremental: bool = False) -> list[int]:
        """Block report from a worker: {block_id: len}. Returns block ids
        the worker holds that the master doesn't know (orphans to GC).
        Full reports also retire replicas the worker no longer holds."""
        known = self.worker_blocks.setdefault(worker_id, set())
        orphans = []
        for bid, length in held.items():
            meta = self.blocks.get(bid)
            if meta is None:
                orphans.append(bid)
                continue
            st = StorageType(storage_types.get(bid, int(StorageType.MEM)))
            meta.locs[worker_id] = BlockLocation(worker_id=worker_id,
                                                 storage_type=st)
            meta.len = max(meta.len, length)
            known.add(bid)
        if not incremental:
            # replicas the master thinks this worker has but it doesn't
            for bid in list(known - set(held)):
                self.remove_replica(bid, worker_id)
        return orphans

    def count(self) -> int:
        return len(self.blocks)
