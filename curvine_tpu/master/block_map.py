"""Block → location map.

Parity: curvine-server/src/master/fs/state/block_map.rs. Durable block
meta (len, owning inode, desired replicas) lives in the MetaStore (KV or
RAM); replica LOCATIONS are runtime state kept in RAM only — they are
rebuilt from worker block reports after a restart, so their footprint is
bounded by the data workers actually hold, not by namespace size."""

from __future__ import annotations

from dataclasses import dataclass, field

from curvine_tpu.common.types import BlockLocation, StorageType


@dataclass
class BlockMeta:
    block_id: int
    len: int = 0
    inode_id: int = 0
    replicas: int = 1              # desired
    locs: dict = field(default_factory=dict)   # worker_id -> BlockLocation


class BlockMap:
    def __init__(self, store=None) -> None:
        from curvine_tpu.master.store import MemMetaStore
        self.store = store if store is not None else MemMetaStore()
        # runtime replica locations: block_id -> {worker_id: BlockLocation}
        self.locs: dict[int, dict[int, BlockLocation]] = {}
        # worker_id -> set of block ids (for loss handling)
        self.worker_blocks: dict[int, set[int]] = {}
        # desired replica count cache: lets the periodic under-replication
        # scan run on RAM instead of one KV point-get per located block
        self.desired: dict[int, int] = {}

    def get(self, block_id: int) -> BlockMeta | None:
        durable = self.store.block_get(block_id)
        if durable is None:
            return None
        length, inode_id, replicas = durable
        return BlockMeta(block_id=block_id, len=length, inode_id=inode_id,
                         replicas=replicas,
                         locs=self.locs.get(block_id, {}))

    def put(self, block_id: int, length: int, inode_id: int,
            replicas: int) -> None:
        self.store.block_put(block_id, length, inode_id, replicas)

    def commit(self, block_id: int, length: int, worker_id: int,
               storage_type: StorageType, inode_id: int = 0,
               replicas: int = 1) -> None:
        durable = self.store.block_get(block_id)
        if durable is None:
            self.store.block_put(block_id, length, inode_id, replicas)
            self.desired[block_id] = replicas
        else:
            old_len, old_iid, old_rep = durable
            self.store.block_put(block_id, max(old_len, length),
                                 inode_id or old_iid, old_rep)
            self.desired[block_id] = old_rep
        self.add_replica(block_id, worker_id, storage_type)

    def add_replica(self, block_id: int, worker_id: int,
                    storage_type: StorageType) -> None:
        self.locs.setdefault(block_id, {})[worker_id] = BlockLocation(
            worker_id=worker_id, storage_type=storage_type)
        self.worker_blocks.setdefault(worker_id, set()).add(block_id)

    def remove_block(self, block_id: int) -> BlockMeta | None:
        meta = self.get(block_id)
        if meta is None:
            return None
        self.store.block_remove(block_id)
        self.desired.pop(block_id, None)
        for wid in self.locs.pop(block_id, {}):
            self.worker_blocks.get(wid, set()).discard(block_id)
        return meta

    def remove_replica(self, block_id: int, worker_id: int) -> None:
        self.locs.get(block_id, {}).pop(worker_id, None)
        self.worker_blocks.get(worker_id, set()).discard(block_id)

    def worker_lost(self, worker_id: int) -> list[int]:
        """Drop all replicas on a lost worker; returns affected block ids."""
        affected = list(self.worker_blocks.pop(worker_id, set()))
        for bid in affected:
            self.locs.get(bid, {}).pop(worker_id, None)
        return affected

    def desired_of(self, block_id: int) -> int:
        d = self.desired.get(block_id)
        if d is None:
            durable = self.store.block_get(block_id)
            d = self.desired[block_id] = durable[2] if durable else 1
        return d

    def under_replicated(self) -> list[BlockMeta]:
        out = []
        for bid, locs in self.locs.items():
            if not locs:
                continue
            if len(locs) < self.desired_of(bid):
                meta = self.get(bid)
                if meta is not None:
                    out.append(meta)
        return out

    def apply_report(self, worker_id: int, held: dict[int, int],
                     storage_types: dict[int, int],
                     incremental: bool = False) -> list[int]:
        """Block report from a worker: {block_id: len}. Returns block ids
        the worker holds that the master doesn't know (orphans to GC).
        Full reports also retire replicas the worker no longer holds."""
        known = self.worker_blocks.setdefault(worker_id, set())
        orphans = []
        for bid, length in held.items():
            durable = self.store.block_get(bid)
            if durable is None:
                orphans.append(bid)
                continue
            old_len, iid, rep = durable
            self.desired[bid] = rep
            if length > old_len:
                self.store.block_put(bid, length, iid, rep)
            st = StorageType(storage_types.get(bid, int(StorageType.MEM)))
            self.add_replica(bid, worker_id, st)
        if not incremental:
            # replicas the master thinks this worker has but it doesn't
            for bid in list(known - set(held)):
                self.remove_replica(bid, worker_id)
        return orphans

    def count(self) -> int:
        return self.store.block_count()
