"""POSIX permission enforcement on master metadata ops.

Parity: curvine-server/src/master/meta/feature/acl_feature.rs — the
reference checks owner/group/mode on every namespace op with a superuser
bypass. Same model here: requests carry (user, groups); every path op
checks traverse (x) on ancestors plus the op's permission on the target
or its parent. Owner-only rules apply to chmod/chown (chown itself is
superuser-only, chgrp needs membership of the target group), matching
POSIX semantics.

Enforcement lives at the RPC handler layer (leader side): journal replay
and raft followers re-apply already-authorized mutations and must not
re-check.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from curvine_tpu.common import errors as err

R, W, X = 4, 2, 1


def posix_bits(owner: str, group: str, mode: int, user: str,
               groups: list[str]) -> int:
    """The permission triplet that applies to (user, groups) — shared by
    the master enforcer and the FUSE access(2) path."""
    if user == owner:
        return (mode >> 6) & 7
    if group in groups:
        return (mode >> 3) & 7
    return mode & 7


@dataclass
class UserCtx:
    user: str = "root"
    groups: list[str] = field(default_factory=list)

    @staticmethod
    def from_req(q: dict) -> "UserCtx":
        return UserCtx(user=q.get("user") or "root",
                       groups=list(q.get("groups") or []))


class AclEnforcer:
    def __init__(self, fs, enabled: bool = True, superuser: str = "root",
                 supergroup: str = "supergroup"):
        self.fs = fs
        self.enabled = enabled
        self.superuser = superuser
        self.supergroup = supergroup

    # ---------------- core ----------------

    def _is_super(self, ctx: UserCtx) -> bool:
        return ctx.user == self.superuser or self.supergroup in ctx.groups

    @staticmethod
    def _bits(node, ctx: UserCtx) -> int:
        return posix_bits(node.owner, node.group, node.mode,
                          ctx.user, ctx.groups)

    def _deny(self, ctx: UserCtx, path: str, what: str):
        raise err.PermissionDenied(
            f"user={ctx.user} lacks {what} on {path}")

    def _walk(self, path: str):
        """Yield (inode, sub-path) for every EXISTING component of path,
        root first (missing tail components are the op's business)."""
        node = self.fs.tree.root
        yield node, "/"
        cur = ""
        for comp in path.strip("/").split("/"):
            if not comp:
                continue
            if not node.is_dir:
                return
            child = self.fs.tree.child(node, comp)
            if child is None:
                return
            cur += "/" + comp
            yield child, cur
            node = child

    def _check_traverse(self, ctx: UserCtx, path: str):
        """x on every existing directory on the way to `path` — including
        the deepest existing dir when the tail is missing, so a missing
        name and an existing name fail identically (EACCES, no existence
        oracle inside unreadable directories)."""
        chain = list(self._walk(path))
        full = ("/" + path.strip("/")).rstrip("/") or "/"
        for node, sub in chain:
            is_target = sub.rstrip("/") == full or sub == full
            if is_target:
                continue          # the target's own x is the op's business
            if node.is_dir and not self._bits(node, ctx) & X:
                self._deny(ctx, sub, "traverse (x)")
        return chain

    # ---------------- op checks ----------------

    def check(self, ctx: UserCtx, path: str, perm: int,
              on_parent: bool = False) -> None:
        """Require `perm` (R|W|X bitmask) on `path` — or on its deepest
        existing ancestor when on_parent (create/delete-style ops)."""
        if not self.enabled or self._is_super(ctx):
            return
        chain = self._check_traverse(ctx, path)
        if not chain:
            return
        node, sub = chain[-1]
        target_is_path = sub.rstrip("/") == ("/" + path.strip("/")).rstrip("/")
        if on_parent:
            # permission applies to the parent dir of the path tail
            if target_is_path and len(chain) > 1:
                node, sub = chain[-2]
            if not node.is_dir:
                return          # parent-is-a-file errors surface later
            if (self._bits(node, ctx) & perm) != perm:
                self._deny(ctx, sub, _perm_str(perm))
            return
        if not target_is_path:
            return              # target doesn't exist: op raises NotFound
        if (self._bits(node, ctx) & perm) != perm:
            self._deny(ctx, sub, _perm_str(perm))

    def allows(self, node, ctx: UserCtx, perm: int) -> bool:
        """Non-raising bit check on an already-resolved inode (subtree
        walks check each directory without re-resolving paths)."""
        if not self.enabled or self._is_super(ctx):
            return True
        return (self._bits(node, ctx) & perm) == perm

    def check_set_attr(self, ctx: UserCtx, path: str, opts) -> None:
        """chmod: owner or superuser. chown: superuser only. chgrp: owner
        AND member of the target group (or superuser). Everything else
        (times, ttl, xattrs, replicas): write permission."""
        if not self.enabled or self._is_super(ctx):
            return
        chain = self._check_traverse(ctx, path)
        if not chain:
            return
        node, sub = chain[-1]
        if sub.rstrip("/") != ("/" + path.strip("/")).rstrip("/"):
            return
        if opts.owner is not None and opts.owner != node.owner:
            self._deny(ctx, sub, "chown (superuser only)")
        is_owner = ctx.user == node.owner
        if opts.mode is not None and not is_owner:
            self._deny(ctx, sub, "chmod (owner only)")
        if opts.group is not None and opts.group != node.group:
            if not (is_owner and opts.group in ctx.groups):
                self._deny(ctx, sub, "chgrp (owner + member)")
        plain = (opts.replicas is not None or opts.ttl_ms is not None
                 or opts.ttl_action is not None or opts.atime is not None
                 or opts.mtime is not None or opts.add_x_attr
                 or opts.remove_x_attr)
        if plain and not self._bits(node, ctx) & W and not is_owner:
            self._deny(ctx, sub, "w")

def _perm_str(perm: int) -> str:
    return "".join(c for bit, c in ((R, "r"), (W, "w"), (X, "x"))
                   if perm & bit)
