from curvine_tpu.vector.index import IvfIndex, PqCodebook
from curvine_tpu.vector.serving import AnnServer
from curvine_tpu.vector.table import VectorTable

__all__ = ["AnnServer", "IvfIndex", "PqCodebook", "VectorTable"]
