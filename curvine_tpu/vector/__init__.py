from curvine_tpu.vector.serving import AnnServer
from curvine_tpu.vector.table import VectorTable

__all__ = ["AnnServer", "VectorTable"]
