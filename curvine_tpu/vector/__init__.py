from curvine_tpu.vector.table import VectorTable

__all__ = ["VectorTable"]
