"""IVF-flat ANN index for VectorTable, TPU-native.

Parity surface: the reference's curvine-lancedb re-exports the upstream
Lance `index` module (IVF_PQ etc. — curvine-lancedb/src/lib.rs:25), so
reference users get ANN indexes over cached tables. This is that
capability re-owned TPU-first instead of wrapping a CPU ANN library:

* BUILD — k-means by Lloyd iterations where BOTH steps are MXU work:
  assignment is one [N, D] x [D, C] matmul + argmax, the centroid update
  is a one-hot [C, N] x [N, D] matmul (segment-sum as matmul). Runs
  entirely on device, jitted once per shape.
* LAYOUT — inverted lists as ONE dense [C, L] int32 matrix (global row
  ids, -1 padding), L = longest list. XLA wants static shapes; padding
  trades a bounded memory factor for a search that compiles once and
  never re-traces. Persisted as an ordinary cached file so it rides the
  same short-circuit/mmap path as row groups.
* SEARCH — two chained device stages with NO host round-trip between
  them: queries x centroids -> top-nprobe lists, take() the candidate
  id matrix [Q, nprobe*L], gather candidate vectors from the pinned
  table, batched dot + top_k. All static shapes.

Freshness follows the Lance model: an index is built at a table
(version, row_groups, deletes) snapshot; table mutations leave it STALE
and knn falls back to the exact brute-force scan until reindexing
(VectorTable.create_index again).
"""

from __future__ import annotations

import json

import numpy as np

from curvine_tpu.common import errors as err

_BUILD_FNS: dict = {}
_SEARCH_FNS: dict = {}


def _kmeans_step_fn(n: int, d: int, c: int):
    """One Lloyd iteration, jitted per (N, D, C)."""
    key = (n, d, c)
    fn = _BUILD_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def step(vectors, centroids):
            # assignment: nearest centroid by L2 == argmax of the
            # 2*v.c - |c|^2 surrogate — one MXU matmul
            scores = 2.0 * (vectors @ centroids.T) \
                - jnp.sum(centroids * centroids, axis=1)[None, :]
            assign = jnp.argmax(scores, axis=1)
            onehot = jax.nn.one_hot(assign, c, dtype=vectors.dtype)
            sums = onehot.T @ vectors            # [C, D] matmul update
            counts = jnp.sum(onehot, axis=0)[:, None]
            new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                            centroids)           # empty list keeps its seed
            shift = jnp.max(jnp.abs(new - centroids))
            return new, assign, shift

        fn = _BUILD_FNS[key] = jax.jit(step)
    return fn


def _search_fn(metric: str, k: int, nprobe: int, qchunk: int = 16):
    key = (metric, k, nprobe, qchunk)
    fn = _SEARCH_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def one_chunk(q, centroids, lists, v_pad, ids_pad):
            """q [Qc,D]; centroids [C,D]; lists [C,L] dense-row ids into
            v_pad (-1 pad); v_pad/ids_pad are the table's ONE pinned
            sentinel-padded array pair ([N+1,D] with a zero row at index
            N / [N+1] with -1) — shared with the exact scan, no second
            device copy."""
            qn = jnp.linalg.norm(q, axis=1, keepdims=True).clip(1e-12)
            if metric == "cosine":
                cn = centroids / jnp.linalg.norm(
                    centroids, axis=1, keepdims=True).clip(1e-12)
                cs = (q / qn) @ cn.T
            else:
                cs = 2.0 * (q @ centroids.T) \
                    - jnp.sum(centroids * centroids, axis=1)[None, :]
            _, probe = jax.lax.top_k(cs, nprobe)        # [Qc, nprobe]
            cand = jnp.take(lists, probe, axis=0)       # [Qc, nprobe, L]
            cand = cand.reshape(q.shape[0], -1)         # [Qc, nprobe*L]
            sentinel = v_pad.shape[0] - 1
            slot = jnp.where(cand < 0, sentinel, cand)
            cv = jnp.take(v_pad, slot, axis=0)          # [Qc, M, D]
            # mirror the exact scan's arithmetic EXACTLY (same casts:
            # bf16 q × bf16 table, f32 accumulation; norms in f32) —
            # scores must not shift when the index goes stale and knn
            # falls back to the exact scan
            dots = jnp.einsum("qd,qmd->qm", q.astype(cv.dtype), cv,
                              preferred_element_type=jnp.float32)
            if metric == "cosine":
                scores = dots / qn
            else:
                cvf = cv.astype(jnp.float32)
                scores = -(jnp.sum(q * q, axis=1)[:, None]
                           - 2.0 * dots + jnp.sum(cvf * cvf, axis=2))
            scores = jnp.where(cand < 0, -jnp.inf, scores)
            kk = min(k, int(scores.shape[1]))
            s, idx = jax.lax.top_k(scores, kk)
            rows = jnp.take_along_axis(slot, idx, axis=1)
            return s, jnp.take(ids_pad, rows)

        def search(q, centroids, lists, v_pad, ids_pad):
            """Batched entry: large query batches are processed in
            `qchunk`-query slices via lax.map INSIDE the one compiled
            program (one dispatch per batch) — the [Qc, nprobe·L, D]
            candidate gather is the peak-memory term, so serving batches
            of 256+ queries must not materialize it for the whole batch
            at once (500K rows × nprobe 8 would be gigabytes)."""
            Q = q.shape[0]
            if Q <= qchunk:
                return one_chunk(q, centroids, lists, v_pad, ids_pad)
            pad = (-Q) % qchunk
            qp = jnp.pad(q, ((0, pad), (0, 0))) if pad else q
            qs = qp.reshape(-1, qchunk, q.shape[1])
            s, i = jax.lax.map(
                lambda qq: one_chunk(qq, centroids, lists, v_pad, ids_pad),
                qs)
            return (s.reshape(-1, s.shape[-1])[:Q],
                    i.reshape(-1, i.shape[-1])[:Q])

        fn = _SEARCH_FNS[key] = jax.jit(search)
    return fn


class IvfIndex:
    """Device-side state + persistence for one table's IVF index."""

    def __init__(self, nlist: int, centroids: np.ndarray,
                 lists: np.ndarray, built_at: dict):
        self.nlist = nlist
        self.centroids = centroids        # [C, D] f32 (unnormalized)
        self.lists = lists                # [C, L] i32 dense-row ids, -1 pad
        self.built_at = built_at          # table snapshot id
        self._dev: dict = {}

    # ---------------- build ----------------

    @staticmethod
    def build(vectors: np.ndarray, dense_ids: np.ndarray, nlist: int,
              built_at: dict, iters: int = 10, device=None,
              seed: int = 0) -> "IvfIndex":
        """K-means on device over the LIVE vectors ([N, D] host array,
        dense row index i ↔ dense_ids[i] position in the pinned table)."""
        import jax

        n, d = vectors.shape
        nlist = max(1, min(nlist, n))
        rng = np.random.default_rng(seed)
        seeds = vectors[rng.choice(n, size=nlist, replace=False)]
        dev = device if device is not None else jax.devices()[0]
        v = jax.device_put(np.asarray(vectors, dtype=np.float32), dev)
        cent = jax.device_put(np.asarray(seeds, dtype=np.float32), dev)
        step = _kmeans_step_fn(n, d, nlist)
        assign = None
        for _ in range(iters):
            cent, assign, shift = step(v, cent)
            if float(shift) < 1e-4:
                break
        assign = np.asarray(assign)
        centroids = np.asarray(cent)
        # dense [C, L] id matrix: rows ARE dense indices into the pinned
        # table (the search takes vectors by these), padded with -1
        counts = np.bincount(assign, minlength=nlist)
        cap = int(counts.max()) if counts.size else 1
        lists = np.full((nlist, max(cap, 1)), -1, dtype=np.int32)
        cursor = np.zeros(nlist, dtype=np.int64)
        for dense_row, c in enumerate(assign):
            lists[c, cursor[c]] = dense_row
            cursor[c] += 1
        return IvfIndex(nlist, centroids, lists, built_at)

    # ---------------- persistence ----------------

    def to_bytes(self) -> bytes:
        meta = json.dumps({
            "nlist": self.nlist, "dim": int(self.centroids.shape[1]),
            "list_cap": int(self.lists.shape[1]),
            "built_at": self.built_at}).encode()
        return b"".join([
            np.int64(len(meta)).tobytes(), meta,
            self.centroids.astype(np.float32).tobytes(),
            self.lists.astype(np.int32).tobytes()])

    @staticmethod
    def from_bytes(buf) -> "IvfIndex":
        view = np.frombuffer(buf, dtype=np.uint8)
        mlen = int(view[:8].view(np.int64)[0])
        meta = json.loads(view[8:8 + mlen].tobytes())
        off = 8 + mlen
        c, d, cap = meta["nlist"], meta["dim"], meta["list_cap"]
        cent = view[off:off + c * d * 4].view(np.float32).reshape(c, d)
        off += c * d * 4
        lists = view[off:off + c * cap * 4].view(np.int32).reshape(c, cap)
        return IvfIndex(c, cent, lists, meta["built_at"])

    # ---------------- search ----------------

    def search(self, query: np.ndarray, v_pinned, ids_pinned, k: int,
               metric: str, nprobe: int, device):
        """v_pinned/ids_pinned: the table's ONE pinned sentinel-padded
        device array pair (LIVE rows + zero/-1 sentinel, normalized per
        metric) — shared with the exact scan; only centroids+lists add
        device residency here."""
        import jax

        nprobe = max(1, min(nprobe, self.nlist))
        dev_key = getattr(device, "id", device)
        got = self._dev.get(dev_key)
        if got is None:
            got = (jax.device_put(self.centroids, device),
                   jax.device_put(self.lists, device))
            self._dev = {dev_key: got}
        cent, lists = got
        q = jax.device_put(
            np.atleast_2d(np.asarray(query, dtype=np.float32)), device)
        return _search_fn(metric, k, nprobe)(q, cent, lists, v_pinned,
                                             ids_pinned)


def table_snapshot(table) -> dict:
    """The freshness id an index is built against."""
    return {"version": table.version, "row_groups": table.row_groups,
            "deletes": len(table._deletes or ())}
