"""IVF ANN index for VectorTable, TPU-native: IVF-flat and IVF-PQ.

Parity surface: the reference's curvine-lancedb re-exports the upstream
Lance `index` module (IVF_PQ etc. — curvine-lancedb/src/lib.rs:25), so
reference users get ANN indexes over cached tables. This is that
capability re-owned TPU-first instead of wrapping a CPU ANN library:

* BUILD — k-means by Lloyd iterations where BOTH steps are MXU work:
  assignment is one [N, D] x [D, C] matmul + argmax, the centroid update
  is a one-hot [C, N] x [N, D] matmul (segment-sum as matmul). Runs
  entirely on device, jitted once per shape. PQ codebooks (Jégou et al.,
  product quantization) train the SAME Lloyd step per subspace.
* LAYOUT — inverted lists as ONE dense [C', L] int32 matrix (global row
  ids, -1 padding). XLA wants static shapes; the round-3 layout padded
  every list to the LONGEST list, so one hot cluster made every probe
  pay its worst case. Now L is clipped at a percentile of the list
  lengths (`cap_pct`) and overflow rows go to SPILL lists: extra matrix
  rows whose centroid entry duplicates their parent's, so they compete
  for probe slots at the parent's score and the search code never
  special-cases them. Probed work becomes ~nprobe·p95 instead of
  nprobe·max. Persisted as an ordinary cached file so it rides the same
  short-circuit/mmap path as row groups.
* SEARCH — chained device stages with NO host round-trip between them.
  IVF-flat: queries x centroids -> top-nprobe lists, take() the
  candidate id matrix, gather candidate vectors from the pinned table,
  batched dot + top_k. IVF-PQ adds the ScaNN-style two-stage scan: an
  ADC pass over 8-bit PQ codes via per-query lookup tables (1 byte per
  subspace of HBM traffic instead of 4·dsub), top-R survivors, then an
  exact fp32/bf16 re-rank whose arithmetic mirrors the brute-force scan
  so returned scores never shift between paths. All static shapes,
  jitted once per shape; the ADC inner loop can run as a fused Pallas
  kernel (tpu/pallas_ops.pq_lut_scan) on TPU.

Freshness follows the Lance model: an index is built at a table
(version, row_groups, deletes) snapshot; table mutations leave it STALE
and knn falls back to the exact brute-force scan until reindexing
(VectorTable.create_index again).
"""

from __future__ import annotations

import json

import numpy as np

from curvine_tpu.common import errors as err

_BUILD_FNS: dict = {}
_SEARCH_FNS: dict = {}
_PQ_SEARCH_FNS: dict = {}
_PQ_ENC_FNS: dict = {}


def _kmeans_step_fn(n: int, d: int, c: int):
    """One Lloyd iteration, jitted per (N, D, C)."""
    key = (n, d, c)
    fn = _BUILD_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def step(vectors, centroids):
            # assignment: nearest centroid by L2 == argmax of the
            # 2*v.c - |c|^2 surrogate — one MXU matmul
            scores = 2.0 * (vectors @ centroids.T) \
                - jnp.sum(centroids * centroids, axis=1)[None, :]
            assign = jnp.argmax(scores, axis=1)
            onehot = jax.nn.one_hot(assign, c, dtype=vectors.dtype)
            sums = onehot.T @ vectors            # [C, D] matmul update
            counts = jnp.sum(onehot, axis=0)[:, None]
            new = jnp.where(counts > 0, sums / jnp.maximum(counts, 1.0),
                            centroids)           # empty list keeps its seed
            shift = jnp.max(jnp.abs(new - centroids))
            return new, assign, shift

        fn = _BUILD_FNS[key] = jax.jit(step)
    return fn


# ---------------------------------------------------------------- PQ


def _pq_encode_fn(n: int, m: int, dsub: int, ksub: int):
    """Nearest-codeword assignment for all subspaces at once: one
    [N, M, dsub] x [M, ksub, dsub] einsum + argmax, jitted per shape."""
    key = (n, m, dsub, ksub)
    fn = _PQ_ENC_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def enc(v, cbs):
            scores = 2.0 * jnp.einsum("nmd,mkd->nmk", v, cbs,
                                      preferred_element_type=jnp.float32) \
                - jnp.sum(cbs * cbs, axis=2)[None, :, :]
            return jnp.argmax(scores, axis=2).astype(jnp.uint8)

        fn = _PQ_ENC_FNS[key] = jax.jit(enc)
    return fn


class PqCodebook:
    """Product-quantization codebooks: M subspaces of dsub dims, each
    with ksub (<=256) centroids, codes 1 byte per subspace."""

    def __init__(self, codebooks: np.ndarray):
        self.codebooks = np.asarray(codebooks, dtype=np.float32)
        self.m, self.ksub, self.dsub = self.codebooks.shape

    @staticmethod
    def train(vectors: np.ndarray, m: int, ksub: int = 256,
              iters: int = 8, device=None, seed: int = 0,
              sample: int = 65536) -> "PqCodebook":
        """Per-subspace k-means on (a sample of) the vectors; each
        subspace reuses the MXU Lloyd step."""
        import jax

        n, d = vectors.shape
        if d % m:
            raise err.InvalidArgument(f"dim {d} not divisible by pq_m {m}")
        dsub = d // m
        rng = np.random.default_rng(seed)
        if n > sample:
            train_v = vectors[rng.choice(n, size=sample, replace=False)]
        else:
            train_v = vectors
        tn = train_v.shape[0]
        ksub = max(1, min(ksub, 256, tn))
        sub = np.ascontiguousarray(
            train_v.reshape(tn, m, dsub).transpose(1, 0, 2))
        dev = device if device is not None else jax.devices()[0]
        step = _kmeans_step_fn(tn, dsub, ksub)
        cbs = []
        for mi in range(m):
            v = jax.device_put(
                np.ascontiguousarray(sub[mi], dtype=np.float32), dev)
            seeds = sub[mi][rng.choice(tn, size=ksub, replace=False)]
            cent = jax.device_put(np.asarray(seeds, dtype=np.float32), dev)
            for _ in range(iters):
                cent, _, shift = step(v, cent)
                if float(shift) < 1e-4:
                    break
            cbs.append(np.asarray(cent))
        return PqCodebook(np.stack(cbs))

    def encode(self, vectors: np.ndarray, device=None,
               chunk: int = 16384, anchors=None) -> np.ndarray:
        """[N, D] -> [N, M] uint8 codes, chunked so the [chunk, M, ksub]
        score tensor never exceeds a few hundred MB on device.

        anchors=(centers [C, D], assign [N]) encodes RESIDUALS
        vectors[i] - centers[assign[i]] (the Jégou IVF-ADC form —
        codewords only need to cover the residual scale, not the whole
        space) without ever materializing the [N, D] residual array."""
        import jax

        n, d = vectors.shape
        if d != self.m * self.dsub:
            raise err.InvalidArgument(
                f"encode dim {d} != {self.m}x{self.dsub}")
        dev = device if device is not None else jax.devices()[0]
        cbs = jax.device_put(self.codebooks, dev)
        out = np.empty((n, self.m), dtype=np.uint8)
        chunk = min(chunk, max(1, n))
        fn = _pq_encode_fn(chunk, self.m, self.dsub, self.ksub)
        for off in range(0, n, chunk):
            part = np.asarray(vectors[off:off + chunk], dtype=np.float32)
            if anchors is not None:
                centers, assign = anchors
                part = part - centers[assign[off:off + chunk]]
            if part.shape[0] < chunk:      # pad the tail to the one shape
                part = np.concatenate([part, np.zeros(
                    (chunk - part.shape[0], d), dtype=np.float32)])
            codes = np.asarray(fn(jax.device_put(
                part.reshape(chunk, self.m, self.dsub), dev), cbs))
            out[off:off + chunk] = codes[:min(chunk, n - off)]
        return out

    def decode(self, codes: np.ndarray) -> np.ndarray:
        """[N, M] uint8 -> reconstructed [N, D] f32 (codeword lookup)."""
        codes = np.asarray(codes)
        parts = [self.codebooks[mi][codes[:, mi].astype(np.int64)]
                 for mi in range(self.m)]
        return np.concatenate(parts, axis=1)


# ---------------------------------------------------------------- search


def _search_fn(metric: str, k: int, nprobe: int, qchunk: int = 16):
    key = (metric, k, nprobe, qchunk)
    fn = _SEARCH_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def one_chunk(q, centroids, lists, v_pad, ids_pad):
            """q [Qc,D]; centroids [C',D]; lists [C',L] dense-row ids into
            v_pad (-1 pad); v_pad/ids_pad are the table's ONE pinned
            sentinel-padded array pair ([N+1,D] with a zero row at index
            N / [N+1] with -1) — shared with the exact scan, no second
            device copy."""
            qn = jnp.linalg.norm(q, axis=1, keepdims=True).clip(1e-12)
            if metric == "cosine":
                cn = centroids / jnp.linalg.norm(
                    centroids, axis=1, keepdims=True).clip(1e-12)
                cs = (q / qn) @ cn.T
            else:
                cs = 2.0 * (q @ centroids.T) \
                    - jnp.sum(centroids * centroids, axis=1)[None, :]
            _, probe = jax.lax.top_k(cs, nprobe)        # [Qc, nprobe]
            cand = jnp.take(lists, probe, axis=0)       # [Qc, nprobe, L]
            cand = cand.reshape(q.shape[0], -1)         # [Qc, nprobe*L]
            sentinel = v_pad.shape[0] - 1
            slot = jnp.where(cand < 0, sentinel, cand)
            cv = jnp.take(v_pad, slot, axis=0)          # [Qc, M, D]
            # mirror the exact scan's arithmetic EXACTLY (same casts:
            # bf16 q × bf16 table, f32 accumulation; norms in f32) —
            # scores must not shift when the index goes stale and knn
            # falls back to the exact scan
            dots = jnp.einsum("qd,qmd->qm", q.astype(cv.dtype), cv,
                              preferred_element_type=jnp.float32)
            if metric == "cosine":
                scores = dots / qn
            else:
                cvf = cv.astype(jnp.float32)
                scores = -(jnp.sum(q * q, axis=1)[:, None]
                           - 2.0 * dots + jnp.sum(cvf * cvf, axis=2))
            scores = jnp.where(cand < 0, -jnp.inf, scores)
            kk = min(k, int(scores.shape[1]))
            s, idx = jax.lax.top_k(scores, kk)
            rows = jnp.take_along_axis(slot, idx, axis=1)
            return s, jnp.take(ids_pad, rows)

        def search(q, centroids, lists, v_pad, ids_pad):
            """Batched entry: large query batches are processed in
            `qchunk`-query slices via lax.map INSIDE the one compiled
            program (one dispatch per batch) — the [Qc, nprobe·L, D]
            candidate gather is the peak-memory term, so serving batches
            of 256+ queries must not materialize it for the whole batch
            at once (500K rows × nprobe 8 would be gigabytes)."""
            Q = q.shape[0]
            if Q <= qchunk:
                return one_chunk(q, centroids, lists, v_pad, ids_pad)
            pad = (-Q) % qchunk
            qp = jnp.pad(q, ((0, pad), (0, 0))) if pad else q
            qs = qp.reshape(-1, qchunk, q.shape[1])
            s, i = jax.lax.map(
                lambda qq: one_chunk(qq, centroids, lists, v_pad, ids_pad),
                qs)
            return (s.reshape(-1, s.shape[-1])[:Q],
                    i.reshape(-1, i.shape[-1])[:Q])

        fn = _SEARCH_FNS[key] = jax.jit(search)
    return fn


def _pq_search_fn(metric: str, k: int, nprobe: int, rerank: int,
                  use_pallas: bool, interpret: bool, qchunk: int = 16):
    """Two-stage IVF-PQ search, jitted per shape-determining config:
    (1) queries × centroids → top-nprobe lists; (2) residual-ADC scan —
    x ≈ c_list + r̂(code), so the score splits into a per-list constant
    (one [Qc, C'] matmul, shared with probing) plus a per-query LUT
    [M, ksub] over RESIDUAL codewords, and every candidate is scored by
    summing M one-byte table lookups (codes arrive pre-offset int32 so
    the scan is one gather + one reduce, no index arithmetic passes);
    (3) top-`rerank` ADC survivors are re-scored EXACTLY against the
    pinned fp32/bf16 table with the same arithmetic as the brute-force
    scan, then top-k. No host round-trip between stages."""
    key = (metric, k, nprobe, rerank, use_pallas, interpret, qchunk)
    fn = _PQ_SEARCH_FNS.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp

        def one_chunk(q, centroids, lists, cbs, codes_pad, norms_pad,
                      v_pad, ids_pad):
            m, ksub, dsub = cbs.shape
            L = lists.shape[1]
            qn = jnp.linalg.norm(q, axis=1, keepdims=True).clip(1e-12)
            cdot = q @ centroids.T                        # [Qc, C']
            if metric == "cosine":
                cnorm = jnp.linalg.norm(centroids, axis=1).clip(1e-12)
                cs = (cdot / qn) / cnorm[None, :]
            else:
                cnorm2 = jnp.sum(centroids * centroids, axis=1)
                cs = 2.0 * cdot - cnorm2[None, :]
            _, probe = jax.lax.top_k(cs, nprobe)
            cand = jnp.take(lists, probe, axis=0).reshape(q.shape[0], -1)
            sentinel = v_pad.shape[0] - 1
            slot = jnp.where(cand < 0, sentinel, cand)    # [Qc, W]

            # --- stage 2: residual ADC (M bytes of code traffic per
            # candidate instead of 4·D for fp32 rows). x ≈ c + r̂:
            #   cosine: q·x ≈ q·c (per-list const) + Σ_m q_m·r̂_m (LUT)
            #   l2 (2q·x - |x|² surrogate): 2q·c + Σ_m 2q_m·r̂_m
            #        - |x̂|² (per-row norms, built with the codes)
            qs = q.reshape(q.shape[0], m, dsub)
            lut = jnp.einsum("qmd,mkd->qmk", qs, cbs,
                             preferred_element_type=jnp.float32)
            cprobe = jnp.take_along_axis(cdot, probe, axis=1)
            if metric == "l2":
                lut = 2.0 * lut
                cprobe = 2.0 * cprobe
            const = jnp.repeat(cprobe, L, axis=1)         # [Qc, W]
            codes = jnp.take(codes_pad, slot, axis=0)     # [Qc, W, M] i32
            if use_pallas:
                from curvine_tpu.tpu.pallas_ops import pq_lut_scan
                adc = jax.vmap(
                    lambda lt, cd: pq_lut_scan(
                        lt, cd, interpret=interpret,
                        pre_offset=True))(lut, codes)     # [Qc, W]
            else:
                adc = jnp.sum(jnp.take_along_axis(
                    lut.reshape(q.shape[0], 1, m * ksub),
                    codes, axis=2), axis=2)               # [Qc, W]
            adc = adc + const
            if metric == "l2":
                adc = adc - jnp.take(norms_pad, slot)
            adc = jnp.where(cand < 0, -jnp.inf, adc)

            # --- stage 3: exact re-rank of the top-R ADC survivors,
            # arithmetic identical to the brute-force scan so scores do
            # not shift between the PQ, flat, and exact paths
            rr = min(rerank, int(adc.shape[1]))
            _, r_idx = jax.lax.top_k(adc, rr)             # [Qc, R]
            r_slot = jnp.take_along_axis(slot, r_idx, axis=1)
            r_cand = jnp.take_along_axis(cand, r_idx, axis=1)
            cv = jnp.take(v_pad, r_slot, axis=0)          # [Qc, R, D]
            dots = jnp.einsum("qd,qrd->qr", q.astype(cv.dtype), cv,
                              preferred_element_type=jnp.float32)
            if metric == "cosine":
                scores = dots / qn
            else:
                cvf = cv.astype(jnp.float32)
                scores = -(jnp.sum(q * q, axis=1)[:, None]
                           - 2.0 * dots + jnp.sum(cvf * cvf, axis=2))
            scores = jnp.where(r_cand < 0, -jnp.inf, scores)
            kk = min(k, rr)
            s, idx = jax.lax.top_k(scores, kk)
            rows = jnp.take_along_axis(r_slot, idx, axis=1)
            return s, jnp.take(ids_pad, rows)

        def search(q, centroids, lists, cbs, codes_pad, norms_pad,
                   v_pad, ids_pad):
            Q = q.shape[0]
            if Q <= qchunk:
                return one_chunk(q, centroids, lists, cbs, codes_pad,
                                 norms_pad, v_pad, ids_pad)
            pad = (-Q) % qchunk
            qp = jnp.pad(q, ((0, pad), (0, 0))) if pad else q
            qs = qp.reshape(-1, qchunk, q.shape[1])
            s, i = jax.lax.map(
                lambda qq: one_chunk(qq, centroids, lists, cbs,
                                     codes_pad, norms_pad, v_pad,
                                     ids_pad), qs)
            return (s.reshape(-1, s.shape[-1])[:Q],
                    i.reshape(-1, i.shape[-1])[:Q])

        fn = _PQ_SEARCH_FNS[key] = jax.jit(search)
    return fn


def _capped_layout(assign: np.ndarray, nlist: int, cap_pct: float
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Pack cluster members into a dense [C+S, cap] id matrix. cap is
    the cap_pct-percentile list length; clusters longer than cap get
    SPILL rows appended after the primaries, and `owner[row]` names the
    centroid each matrix row belongs to (owner[c]=c for primaries).
    Falls back to the plain max-length layout when capping would not
    shrink the matrix by >=10% (tiny/uniform tables)."""
    counts = np.bincount(assign, minlength=nlist)
    max_len = max(int(counts.max()) if counts.size else 1, 1)
    cap = max_len
    if cap_pct < 100.0 and counts.size:
        pcap = max(1, int(np.ceil(np.percentile(counts, cap_pct))))
        if pcap < max_len:
            spills = int(np.sum(np.maximum(
                np.ceil(counts / pcap).astype(np.int64) - 1, 0)))
            if (nlist + spills) * pcap < 0.9 * nlist * max_len:
                cap = pcap
    order = np.argsort(assign, kind="stable").astype(np.int32)
    bounds = np.concatenate([[0], np.cumsum(counts)])
    extra = np.maximum(np.ceil(counts / cap).astype(np.int64) - 1, 0)
    total = nlist + int(extra.sum())
    lists = np.full((total, cap), -1, dtype=np.int32)
    owner = np.arange(total, dtype=np.int32)
    spill = nlist
    for c in range(nlist):
        members = order[bounds[c]:bounds[c + 1]]
        lists[c, :min(cap, members.size)] = members[:cap]
        for off in range(cap, members.size, cap):
            part = members[off:off + cap]
            lists[spill, :part.size] = part
            owner[spill] = c
            spill += 1
    return lists, owner


class IvfIndex:
    """Device-side state + persistence for one table's IVF index
    (flat or PQ)."""

    def __init__(self, nlist: int, centroids: np.ndarray,
                 lists: np.ndarray, built_at: dict,
                 pq: PqCodebook | None = None,
                 codes: np.ndarray | None = None,
                 norms: np.ndarray | None = None):
        self.nlist = nlist                # logical k-means lists
        self.centroids = centroids        # [C+S, D] f32 (spill rows
        #                                   duplicate their parent's)
        self.lists = lists                # [C+S, L] i32 dense-row ids,
        #                                   -1 pad
        self.built_at = built_at          # table snapshot id
        self.pq = pq                      # PqCodebook | None
        self.codes = codes                # [N, M] uint8 RESIDUAL codes,
        #                                   dense-row order
        self.norms = norms                # [N] f32 |ĉ+r̂|² (l2 ADC term)
        self._dev: dict = {}

    @property
    def nlist_total(self) -> int:
        """Physical list count including spill lists."""
        return int(self.lists.shape[0])

    # ---------------- build ----------------

    @staticmethod
    def build(vectors: np.ndarray, dense_ids: np.ndarray, nlist: int,
              built_at: dict, iters: int = 10, device=None,
              seed: int = 0, cap_pct: float = 95.0,
              pq_m: int | None = None, pq_ksub: int = 256,
              pq_iters: int = 8, pq_sample: int = 65536) -> "IvfIndex":
        """K-means on device over the LIVE vectors ([N, D] host array,
        dense row index i ↔ dense_ids[i] position in the pinned table).
        pq_m != None additionally trains PQ codebooks (pq_m subspaces,
        pq_ksub codewords each) and packs one uint8 code row per
        vector."""
        import jax

        n, d = vectors.shape
        nlist = max(1, min(nlist, n))
        rng = np.random.default_rng(seed)
        seeds = vectors[rng.choice(n, size=nlist, replace=False)]
        dev = device if device is not None else jax.devices()[0]
        v = jax.device_put(np.asarray(vectors, dtype=np.float32), dev)
        cent = jax.device_put(np.asarray(seeds, dtype=np.float32), dev)
        step = _kmeans_step_fn(n, d, nlist)
        assign = None
        for _ in range(iters):
            cent, assign, shift = step(v, cent)
            if float(shift) < 1e-4:
                break
        assign = np.asarray(assign)
        centroids = np.asarray(cent)
        # dense [C+S, cap] id matrix: rows ARE dense indices into the
        # pinned table (the search takes vectors by these); spill rows
        # share their parent's centroid so top-nprobe naturally probes
        # them without any chain-following
        lists, owner = _capped_layout(assign, nlist, cap_pct)
        pq = None
        codes = None
        norms = None
        if pq_m:
            # PQ on RESIDUALS x - c_assigned (Jégou IVF-ADC): codewords
            # cover the residual scale, not the whole space, so within-
            # list ranking survives quantization. Train on a sample;
            # encode chunked (no [N, D] residual array is materialized).
            sidx = rng.choice(n, size=min(n, pq_sample), replace=False)
            resid_sample = vectors[sidx] - centroids[assign[sidx]]
            pq = PqCodebook.train(resid_sample, pq_m, ksub=pq_ksub,
                                  iters=pq_iters, device=dev, seed=seed,
                                  sample=pq_sample)
            codes = pq.encode(vectors, device=dev,
                              anchors=(centroids, assign))
            # per-row |x̂|² for the l2 ADC term, chunked like encode
            norms = np.empty(n, dtype=np.float32)
            for off in range(0, n, 16384):
                part = codes[off:off + 16384]
                recon = pq.decode(part) \
                    + centroids[assign[off:off + 16384]]
                norms[off:off + 16384] = np.sum(recon * recon, axis=1)
        centroids = centroids[owner]
        return IvfIndex(nlist, centroids, lists, built_at, pq=pq,
                        codes=codes, norms=norms)

    # ---------------- persistence ----------------

    def to_bytes(self) -> bytes:
        meta = {
            "fmt": 2, "nlist": self.nlist,
            "nlist_total": int(self.lists.shape[0]),
            "dim": int(self.centroids.shape[1]),
            "list_cap": int(self.lists.shape[1]),
            "built_at": self.built_at, "pq": None}
        if self.pq is not None:
            meta["pq"] = {"m": self.pq.m, "ksub": self.pq.ksub,
                          "dsub": self.pq.dsub,
                          "rows": int(self.codes.shape[0])}
        mb = json.dumps(meta).encode()
        parts = [np.int64(len(mb)).tobytes(), mb,
                 self.centroids.astype(np.float32).tobytes(),
                 self.lists.astype(np.int32).tobytes()]
        if self.pq is not None:
            parts.append(self.pq.codebooks.astype(np.float32).tobytes())
            parts.append(self.codes.astype(np.uint8).tobytes())
            parts.append(self.norms.astype(np.float32).tobytes())
        return b"".join(parts)

    @staticmethod
    def from_bytes(buf) -> "IvfIndex":
        view = np.frombuffer(buf, dtype=np.uint8)
        mlen = int(view[:8].view(np.int64)[0])
        meta = json.loads(view[8:8 + mlen].tobytes())
        off = 8 + mlen
        d, cap = meta["dim"], meta["list_cap"]
        # fmt 1 (pre-PQ) files have no nlist_total/pq keys
        ct = meta.get("nlist_total", meta["nlist"])
        cent = view[off:off + ct * d * 4].view(np.float32).reshape(ct, d)
        off += ct * d * 4
        lists = view[off:off + ct * cap * 4].view(np.int32).reshape(
            ct, cap)
        off += ct * cap * 4
        pq = None
        codes = None
        norms = None
        pmeta = meta.get("pq")
        if pmeta:
            m, ksub, dsub = pmeta["m"], pmeta["ksub"], pmeta["dsub"]
            cbs = view[off:off + m * ksub * dsub * 4].view(
                np.float32).reshape(m, ksub, dsub)
            off += m * ksub * dsub * 4
            rows = pmeta["rows"]
            codes = view[off:off + rows * m].reshape(rows, m)
            off += rows * m
            norms = view[off:off + rows * 4].view(np.float32)
            pq = PqCodebook(np.array(cbs))
        return IvfIndex(meta["nlist"], cent, lists, meta["built_at"],
                        pq=pq, codes=codes, norms=norms)

    # ---------------- search ----------------

    def _device_state(self, device):
        import jax

        dev_key = getattr(device, "id", device)
        got = self._dev.get(dev_key)
        if got is None:
            got = {"cent": jax.device_put(self.centroids, device),
                   "lists": jax.device_put(self.lists, device)}
            if self.pq is not None:
                # sentinel-padded codes pinned as PRE-OFFSET int32:
                # codes[i, m] + m·ksub indexes the flattened [M·ksub]
                # LUT directly, so the per-query ADC is one gather + one
                # reduce with no widening/offset passes over the [W, M]
                # tensor. Row N is the sentinel the -1 list padding maps
                # to (masked out of the ADC scores, same convention as
                # the pinned vector sentinel row).
                offs = (np.arange(self.pq.m, dtype=np.int32)
                        * self.pq.ksub)[None, :]
                codes_pad = np.concatenate(
                    [self.codes.astype(np.int32) + offs,
                     np.broadcast_to(offs, (1, self.pq.m))])
                norms_pad = np.concatenate(
                    [np.asarray(self.norms, dtype=np.float32),
                     np.zeros(1, dtype=np.float32)])
                got["cbs"] = jax.device_put(self.pq.codebooks, device)
                got["codes"] = jax.device_put(codes_pad, device)
                got["norms"] = jax.device_put(norms_pad, device)
            self._dev = {dev_key: got}
        return got

    def search(self, query: np.ndarray, v_pinned, ids_pinned, k: int,
               metric: str, nprobe: int, device,
               use_pq: bool | str = "auto", rerank: int | None = None,
               pallas: bool | str = "auto"):
        """v_pinned/ids_pinned: the table's ONE pinned sentinel-padded
        device array pair (LIVE rows + zero/-1 sentinel, normalized per
        metric) — shared with the exact scan; only centroids + lists
        (+ PQ codes) add device residency here.

        use_pq: "auto" uses the ADC path iff PQ codes were built;
        rerank: ADC survivors re-scored exactly (default max(4k, 32));
        pallas: "auto" fuses the ADC scan as a Pallas kernel on TPU
        (interpret-mode fallback if forced on elsewhere)."""
        import jax

        if use_pq == "auto":
            use_pq = self.pq is not None
        elif use_pq and self.pq is None:
            raise err.InvalidArgument(
                "index has no PQ codes (create_index(pq_m=...))")
        nprobe = max(1, min(nprobe, self.nlist_total))
        state = self._device_state(device)
        q = jax.device_put(
            np.atleast_2d(np.asarray(query, dtype=np.float32)), device)
        if not use_pq:
            return _search_fn(metric, k, nprobe)(
                q, state["cent"], state["lists"], v_pinned, ids_pinned)
        width = nprobe * int(self.lists.shape[1])
        rr = max(k, min(rerank if rerank else max(4 * k, 32), width))
        platform = getattr(device, "platform", "")
        use_pallas = pallas is True or (pallas == "auto"
                                        and platform == "tpu")
        interpret = platform != "tpu"
        fn = _pq_search_fn(metric, k, nprobe, rr, use_pallas, interpret)
        return fn(q, state["cent"], state["lists"], state["cbs"],
                  state["codes"], state["norms"], v_pinned, ids_pinned)


def table_snapshot(table) -> dict:
    """The freshness id an index is built against."""
    return {"version": table.version, "row_groups": table.row_groups,
            "deletes": len(table._deletes or ())}
