"""Batched ANN serving over a VectorTable.

Parity surface: the reference exposes Lance's ANN indexes for query
serving (curvine-lancedb/src/lib.rs:25 re-exports `index`); this is the
serving half rebuilt TPU-first. One query per device dispatch benches at
tunnel-RTT speed (~100 QPS), not MXU speed — so the server MICRO-BATCHES:

* callers await ``query()``; a collector coalesces everything that
  arrives within ``max_wait_ms`` (or until ``max_batch``) into one
  [Q, D] batch,
* batches are PADDED to the next power of two so XLA compiles a handful
  of shapes once and never re-traces,
* the table/centroids/lists/PQ codes stay pinned on device across calls
  (VectorTable._device_vectors + IvfIndex._dev caches),
* ``use_pq``/``rerank`` select the two-stage ADC + exact-rerank search
  when the index carries PQ codes (docs/ann-serving.md has the QPS
  ladder and roofline).

The micro-batch collector runs one batch at a time (coalesce →
dispatch → sync); its win is the batching itself. ``query_many()`` is
the THROUGHPUT path: it feeds the same pinned device state directly
with caller-sized batches (no padding, no queueing) and pipelines
``depth`` dispatches before syncing, so transfer and compute overlap.

Observability follows the io_engine/hbm stats() pattern: batch
occupancy, queue wait, and the recall-relevant config (nprobe, use_pq,
rerank) are counters a scraper can diff — plus the table's
stale_fallbacks so a stale index degrading every query to the
brute-force scan shows up instead of hiding inside latency."""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from curvine_tpu.common import errors as err

log = logging.getLogger(__name__)


class AnnServer:
    def __init__(self, table, k: int = 10, metric: str = "cosine",
                 nprobe: int = 8, device=None, max_batch: int = 256,
                 max_wait_ms: float = 2.0, use_index: bool = True,
                 dtype: str = "f32", warm_all: bool = True,
                 use_pq: bool | str = "auto", rerank: int | None = None,
                 pallas: bool | str = "auto"):
        self.table = table
        self.k = k
        self.metric = metric
        self.nprobe = nprobe
        self.device = device
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.use_index = use_index
        self.dtype = dtype
        self.use_pq = use_pq
        self.rerank = rerank
        self.pallas = pallas
        # warm_all=False: only the 1 and max_batch shapes pre-compile —
        # for bulk-only callers (query_many at a fixed batch) the other
        # pow2 shapes would be compile time spent on nothing
        self.warm_all = warm_all
        self._queue: asyncio.Queue = asyncio.Queue()
        self._collector: asyncio.Task | None = None
        self._closed = False
        self._warmed: set[int] = set()
        self._counters = {"queries": 0, "batches": 0, "batch_rows": 0,
                          "queue_wait_ms": 0.0, "max_queue_wait_ms": 0.0}

    async def start(self) -> "AnnServer":
        """Pin the table (and index) on device and pre-compile the padded
        batch shapes so the first real queries don't eat a trace. The
        warm-up knn calls are DISPATCHED without a per-call host sync
        (materialize=False) and blocked on once at the end — one
        device round-trip for the whole ladder instead of one per pow2
        shape — and shapes already warmed by a previous start() of this
        server are skipped, so stop()/start() cycles don't re-pay
        compile time."""
        import jax
        dev = self.device if self.device is not None else jax.devices()[0]
        self.device = dev
        # _run_batch pads to powers of two — warm EVERY shape it can
        # emit (warm_all), or the first 3-query batch eats a JIT trace
        # as latency; bulk-only callers warm just 1 and max_batch
        warm = np.zeros((1, self.table.dim), dtype=np.float32)
        pend = []
        q = 1
        while True:
            if (self.warm_all or q in (1, self.max_batch)) \
                    and q not in self._warmed:
                pend.append(await self.table.knn(
                    np.repeat(warm, q, axis=0), k=self.k,
                    metric=self.metric, device=dev, materialize=False,
                    use_index=self.use_index, nprobe=self.nprobe,
                    dtype=self.dtype, use_pq=self.use_pq,
                    rerank=self.rerank, pallas=self.pallas))
                self._warmed.add(q)
            if q >= self.max_batch:
                break
            q = min(q * 2, self.max_batch)
        if pend:
            await asyncio.to_thread(jax.block_until_ready, pend)
        self._closed = False
        self._collector = asyncio.ensure_future(self._collect_loop())
        return self

    async def stop(self) -> None:
        self._closed = True
        if self._collector:
            self._collector.cancel()
            try:
                await self._collector
            except asyncio.CancelledError:
                pass
            self._collector = None
        # reject every waiter still queued (or whose batch was cut down
        # mid-flight by the cancellation) — nobody hangs on a dead server
        while not self._queue.empty():
            item = self._queue.get_nowait()
            if not item[1].done():
                item[1].set_exception(
                    err.InvalidArgument("AnnServer stopped"))

    def stats(self) -> dict:
        """Serving counters + the recall-relevant config, io_engine
        stats()-style. batch_occupancy near 1/max_batch means callers
        are not concurrent enough for micro-batching to pay."""
        c = dict(self._counters)
        batches = c.pop("batches")
        rows = c.pop("batch_rows")
        wait = c.pop("queue_wait_ms")
        out = {
            "queries": c["queries"], "batches": batches,
            "batch_occupancy": rows / (batches * self.max_batch)
            if batches else 0.0,
            "avg_batch": rows / batches if batches else 0.0,
            "avg_queue_wait_ms": wait / c["queries"]
            if c["queries"] else 0.0,
            "max_queue_wait_ms": c["max_queue_wait_ms"],
            "stale_fallbacks": getattr(self.table, "stale_fallbacks", 0),
            "config": {"k": self.k, "metric": self.metric,
                       "nprobe": self.nprobe, "use_index": self.use_index,
                       "use_pq": self.use_pq, "rerank": self.rerank,
                       "dtype": self.dtype, "max_batch": self.max_batch,
                       "max_wait_ms": self.max_wait_ms},
        }
        return out

    # ---------------- single-query path (micro-batched) ----------------

    async def query(self, q: np.ndarray):
        """One [D] query → (ids [k], scores [k]). Coalesced with
        concurrent callers into one device batch."""
        if self._closed:
            raise err.InvalidArgument("AnnServer is stopped")
        q = np.asarray(q, dtype=np.float32)
        if q.shape != (self.table.dim,):
            # validate BEFORE enqueueing: one malformed query must not
            # poison every innocent waiter coalesced into its batch
            raise err.InvalidArgument(
                f"query shape {q.shape} != ({self.table.dim},)")
        loop = asyncio.get_running_loop()
        fut: asyncio.Future = loop.create_future()
        await self._queue.put((q, fut, loop.time()))
        ids, scores = await fut
        return ids, scores

    async def _collect_loop(self) -> None:
        while True:
            batch = [await self._queue.get()]
            try:
                deadline = asyncio.get_running_loop().time() \
                    + self.max_wait_ms / 1000.0
                while len(batch) < self.max_batch:
                    timeout = deadline - asyncio.get_running_loop().time()
                    if timeout <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self._queue.get(), timeout))
                    except asyncio.TimeoutError:
                        break
                await self._run_batch(batch)
            except asyncio.CancelledError:
                # stop() while coalescing OR mid-batch: reject every
                # waiter already popped from the queue (the queued rest
                # are rejected by stop itself), then propagate
                for item in batch:
                    if not item[1].done():
                        item[1].set_exception(
                            err.InvalidArgument("AnnServer stopped"))
                raise
            except Exception as e:  # noqa: BLE001 — fail the waiters
                for item in batch:
                    if not item[1].done():
                        item[1].set_exception(e)

    async def _run_batch(self, batch) -> None:
        now = asyncio.get_running_loop().time()
        c = self._counters
        c["queries"] += len(batch)
        c["batches"] += 1
        c["batch_rows"] += len(batch)
        for _, _, t_enq in batch:
            wait_ms = (now - t_enq) * 1000.0
            c["queue_wait_ms"] += wait_ms
            if wait_ms > c["max_queue_wait_ms"]:
                c["max_queue_wait_ms"] = wait_ms
        qs = np.stack([q for q, _, _ in batch])
        n = qs.shape[0]
        # pad to the next power of two: a handful of compiled shapes
        padded = 1
        while padded < n:
            padded *= 2
        padded = min(padded, self.max_batch)
        if padded > n:
            qs = np.concatenate(
                [qs, np.zeros((padded - n, qs.shape[1]), qs.dtype)])
        i_dev, s_dev = await self.table.knn(
            qs, k=self.k, metric=self.metric, device=self.device,
            materialize=False, use_index=self.use_index,
            nprobe=self.nprobe, dtype=self.dtype, use_pq=self.use_pq,
            rerank=self.rerank, pallas=self.pallas)
        # device→host sync off the event loop so OTHER tasks (bulk
        # query_many pipelines, RPC handlers) keep running during it
        ids, scores = await asyncio.to_thread(
            lambda: (np.asarray(i_dev), np.asarray(s_dev)))
        for j, (_, fut, _) in enumerate(batch):
            if not fut.done():
                fut.set_result((ids[j], scores[j]))

    # ---------------- bulk path ----------------

    async def query_many(self, queries: np.ndarray,
                         batch: int = 0, depth: int = 4):
        """[Q, D] queries → (ids [Q, k], scores [Q, k]). Splits into
        device batches and pipelines `depth` dispatches before syncing —
        remote-dispatch RTT amortizes across the stream."""
        queries = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        batch = batch or self.max_batch
        pend: list = []
        out_i, out_s = [], []

        async def drain(n_keep: int) -> None:
            while len(pend) > n_keep:
                i_dev, s_dev = pend.pop(0)
                i, s = await asyncio.to_thread(
                    lambda a=i_dev, b=s_dev: (np.asarray(a), np.asarray(b)))
                out_i.append(i)
                out_s.append(s)

        for off in range(0, queries.shape[0], batch):
            part = queries[off:off + batch]
            pend.append(await self.table.knn(
                part, k=self.k, metric=self.metric, device=self.device,
                materialize=False, use_index=self.use_index,
                nprobe=self.nprobe, dtype=self.dtype, use_pq=self.use_pq,
                rerank=self.rerank, pallas=self.pallas))
            await drain(depth)
        await drain(0)
        return np.concatenate(out_i), np.concatenate(out_s)
