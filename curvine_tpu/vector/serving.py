"""Batched ANN serving over a VectorTable.

Parity surface: the reference exposes Lance's ANN indexes for query
serving (curvine-lancedb/src/lib.rs:25 re-exports `index`); this is the
serving half rebuilt TPU-first. One query per device dispatch benches at
tunnel-RTT speed (~100 QPS), not MXU speed — so the server MICRO-BATCHES:

* callers await ``query()``; a collector coalesces everything that
  arrives within ``max_wait_ms`` (or until ``max_batch``) into one
  [Q, D] batch,
* batches are PADDED to the next power of two so XLA compiles a handful
  of shapes once and never re-traces,
* the table/centroids/lists stay pinned on device across calls
  (VectorTable._device_vectors + IvfIndex._dev caches).

The micro-batch collector runs one batch at a time (coalesce →
dispatch → sync); its win is the batching itself. ``query_many()`` is
the THROUGHPUT path: it feeds the same pinned device state directly
with caller-sized batches (no padding, no queueing) and pipelines
``depth`` dispatches before syncing, so transfer and compute overlap.
"""

from __future__ import annotations

import asyncio
import logging

import numpy as np

from curvine_tpu.common import errors as err

log = logging.getLogger(__name__)


class AnnServer:
    def __init__(self, table, k: int = 10, metric: str = "cosine",
                 nprobe: int = 8, device=None, max_batch: int = 256,
                 max_wait_ms: float = 2.0, use_index: bool = True,
                 dtype: str = "f32", warm_all: bool = True):
        self.table = table
        self.k = k
        self.metric = metric
        self.nprobe = nprobe
        self.device = device
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.use_index = use_index
        self.dtype = dtype
        # warm_all=False: only the 1 and max_batch shapes pre-compile —
        # for bulk-only callers (query_many at a fixed batch) the other
        # pow2 shapes would be compile time spent on nothing
        self.warm_all = warm_all
        self._queue: asyncio.Queue = asyncio.Queue()
        self._collector: asyncio.Task | None = None
        self._closed = False

    async def start(self) -> "AnnServer":
        """Pin the table (and index) on device and pre-compile the padded
        batch shapes so the first real queries don't eat a trace."""
        import jax
        dev = self.device if self.device is not None else jax.devices()[0]
        self.device = dev
        # _run_batch pads to powers of two — warm EVERY shape it can
        # emit (warm_all), or the first 3-query batch eats a JIT trace
        # as latency; bulk-only callers warm just 1 and max_batch
        warm = np.zeros((1, self.table.dim), dtype=np.float32)
        q = 1
        while True:
            if self.warm_all or q in (1, self.max_batch):
                await self.table.knn(np.repeat(warm, q, axis=0), k=self.k,
                                     metric=self.metric, device=dev,
                                     use_index=self.use_index,
                                     nprobe=self.nprobe, dtype=self.dtype)
            if q >= self.max_batch:
                break
            q = min(q * 2, self.max_batch)
        self._collector = asyncio.ensure_future(self._collect_loop())
        return self

    async def stop(self) -> None:
        self._closed = True
        if self._collector:
            self._collector.cancel()
            try:
                await self._collector
            except asyncio.CancelledError:
                pass
        # reject every waiter still queued (or whose batch was cut down
        # mid-flight by the cancellation) — nobody hangs on a dead server
        while not self._queue.empty():
            _, fut = self._queue.get_nowait()
            if not fut.done():
                fut.set_exception(
                    err.InvalidArgument("AnnServer stopped"))

    # ---------------- single-query path (micro-batched) ----------------

    async def query(self, q: np.ndarray):
        """One [D] query → (ids [k], scores [k]). Coalesced with
        concurrent callers into one device batch."""
        if self._closed:
            raise err.InvalidArgument("AnnServer is stopped")
        q = np.asarray(q, dtype=np.float32)
        if q.shape != (self.table.dim,):
            # validate BEFORE enqueueing: one malformed query must not
            # poison every innocent waiter coalesced into its batch
            raise err.InvalidArgument(
                f"query shape {q.shape} != ({self.table.dim},)")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._queue.put((q, fut))
        ids, scores = await fut
        return ids, scores

    async def _collect_loop(self) -> None:
        while True:
            batch = [await self._queue.get()]
            try:
                deadline = asyncio.get_running_loop().time() \
                    + self.max_wait_ms / 1000.0
                while len(batch) < self.max_batch:
                    timeout = deadline - asyncio.get_running_loop().time()
                    if timeout <= 0:
                        break
                    try:
                        batch.append(await asyncio.wait_for(
                            self._queue.get(), timeout))
                    except asyncio.TimeoutError:
                        break
                await self._run_batch(batch)
            except asyncio.CancelledError:
                # stop() while coalescing OR mid-batch: reject every
                # waiter already popped from the queue (the queued rest
                # are rejected by stop itself), then propagate
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(
                            err.InvalidArgument("AnnServer stopped"))
                raise
            except Exception as e:  # noqa: BLE001 — fail the waiters
                for _, fut in batch:
                    if not fut.done():
                        fut.set_exception(e)

    async def _run_batch(self, batch) -> None:
        qs = np.stack([q for q, _ in batch])
        n = qs.shape[0]
        # pad to the next power of two: a handful of compiled shapes
        padded = 1
        while padded < n:
            padded *= 2
        padded = min(padded, self.max_batch)
        if padded > n:
            qs = np.concatenate(
                [qs, np.zeros((padded - n, qs.shape[1]), qs.dtype)])
        i_dev, s_dev = await self.table.knn(
            qs, k=self.k, metric=self.metric, device=self.device,
            materialize=False, use_index=self.use_index,
            nprobe=self.nprobe, dtype=self.dtype)
        # device→host sync off the event loop so OTHER tasks (bulk
        # query_many pipelines, RPC handlers) keep running during it
        ids, scores = await asyncio.to_thread(
            lambda: (np.asarray(i_dev), np.asarray(s_dev)))
        for j, (_, fut) in enumerate(batch):
            if not fut.done():
                fut.set_result((ids[j], scores[j]))

    # ---------------- bulk path ----------------

    async def query_many(self, queries: np.ndarray,
                         batch: int = 0, depth: int = 4):
        """[Q, D] queries → (ids [Q, k], scores [Q, k]). Splits into
        device batches and pipelines `depth` dispatches before syncing —
        remote-dispatch RTT amortizes across the stream."""
        queries = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        batch = batch or self.max_batch
        pend: list = []
        out_i, out_s = [], []

        async def drain(n_keep: int) -> None:
            while len(pend) > n_keep:
                i_dev, s_dev = pend.pop(0)
                i, s = await asyncio.to_thread(
                    lambda a=i_dev, b=s_dev: (np.asarray(a), np.asarray(b)))
                out_i.append(i)
                out_s.append(s)

        for off in range(0, queries.shape[0], batch):
            part = queries[off:off + batch]
            pend.append(await self.table.knn(
                part, k=self.k, metric=self.metric, device=self.device,
                materialize=False, use_index=self.use_index,
                nprobe=self.nprobe, dtype=self.dtype))
            await drain(depth)
        await drain(0)
        return np.concatenate(out_i), np.concatenate(out_s)
