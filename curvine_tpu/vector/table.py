"""Vector tables on the distributed cache.

Parity: curvine-lancedb/ (Lance columnar tables cached by Curvine, scanned
for embedding lookup). TPU-native rework: row groups are fixed-schema
columnar blobs cached as ordinary files (so they ride the short-circuit
mmap path), and KNN search runs as one bf16 matmul on the TPU — the MXU
does the scan, not a CPU ANN index.

Layout under `<path>/`:
  schema.json                  {"dim": D, "columns": {...}, "row_groups": N}
  rg-00000.vec ...             row groups: [n, D] float32 + packed columns
"""

from __future__ import annotations

import json

import numpy as np

from curvine_tpu.client import CurvineClient
from curvine_tpu.common import errors as err

_DTYPES = {"f32": np.float32, "i32": np.int32, "i64": np.int64}


class VectorTable:
    def __init__(self, client: CurvineClient, path: str, dim: int,
                 columns: dict[str, str], row_groups: int):
        self.client = client
        self.path = path.rstrip("/")
        self.dim = dim
        self.columns = columns
        self.row_groups = row_groups

    # ---------------- lifecycle ----------------

    @staticmethod
    async def create(client: CurvineClient, path: str, dim: int,
                     columns: dict[str, str] | None = None) -> "VectorTable":
        columns = columns or {}
        for name, dt in columns.items():
            if dt not in _DTYPES:
                raise err.InvalidArgument(f"column {name}: bad dtype {dt}")
        t = VectorTable(client, path, dim, columns, 0)
        await client.meta.mkdir(path)
        await t._write_schema()
        return t

    @staticmethod
    async def open(client: CurvineClient, path: str) -> "VectorTable":
        raw = await (await client.open(f"{path.rstrip('/')}/schema.json")
                     ).read_all()
        s = json.loads(raw)
        return VectorTable(client, path, s["dim"], s["columns"],
                           s["row_groups"])

    async def _write_schema(self) -> None:
        await self.client.write_all(
            f"{self.path}/schema.json",
            json.dumps({"dim": self.dim, "columns": self.columns,
                        "row_groups": self.row_groups}).encode())

    # ---------------- append / scan ----------------

    async def append(self, vectors: np.ndarray,
                     columns: dict[str, np.ndarray] | None = None) -> int:
        """Append one row group; returns its index."""
        columns = columns or {}
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise err.InvalidArgument(
                f"vectors must be [n, {self.dim}], got {vectors.shape}")
        n = vectors.shape[0]
        parts = [np.int64(n).tobytes(), vectors.tobytes()]
        for name, dt in self.columns.items():
            col = np.ascontiguousarray(columns[name], dtype=_DTYPES[dt])
            if col.shape[0] != n:
                raise err.InvalidArgument(f"column {name} length mismatch")
            parts.append(col.tobytes())
        rg = self.row_groups
        await self.client.write_all(f"{self.path}/rg-{rg:05d}.vec",
                                    b"".join(parts))
        self.row_groups += 1
        await self._write_schema()
        return rg

    async def read_group(self, rg: int) -> tuple[np.ndarray, dict]:
        reader = await self.client.open(f"{self.path}/rg-{rg:05d}.vec")
        view = await reader.mmap_view(0, reader.len)
        if view is None:
            view = np.frombuffer(await reader.read_all(), dtype=np.uint8)
        n = int(view[:8].view(np.int64)[0])
        off = 8
        vec_bytes = n * self.dim * 4
        vectors = view[off:off + vec_bytes].view(np.float32).reshape(
            n, self.dim)
        off += vec_bytes
        cols = {}
        for name, dt in self.columns.items():
            dtype = np.dtype(_DTYPES[dt])
            cols[name] = view[off:off + n * dtype.itemsize].view(dtype)
            off += n * dtype.itemsize
        return vectors, cols

    async def scan(self):
        """Async iterator over (vectors, columns) per row group."""
        for rg in range(self.row_groups):
            yield await self.read_group(rg)

    async def count(self) -> int:
        total = 0
        async for vectors, _ in self.scan():
            total += vectors.shape[0]
        return total

    # ---------------- TPU knn ----------------

    async def knn(self, query: np.ndarray, k: int = 10,
                  metric: str = "cosine", device=None):
        """Top-k nearest rows to `query` [D] or [Q, D]. The scan is a
        single [Q, D] × [D, N] matmul per row group on the device (MXU),
        with partial top-k merged across groups."""
        import jax
        import jax.numpy as jnp

        query = np.atleast_2d(np.asarray(query, dtype=np.float32))
        if query.shape[1] != self.dim:
            raise err.InvalidArgument(f"query dim {query.shape[1]} != {self.dim}")
        dev = device if device is not None else jax.devices()[0]
        q = jax.device_put(query, dev)
        if metric == "cosine":
            q = q / jnp.linalg.norm(q, axis=1, keepdims=True).clip(1e-12)

        best_scores = None
        best_ids = None
        row_base = 0
        async for vectors, _cols in self.scan():
            v = jax.device_put(vectors, dev)
            if metric == "cosine":
                v = v / jnp.linalg.norm(v, axis=1, keepdims=True).clip(1e-12)
                scores = q @ v.T
            elif metric == "l2":
                scores = -(jnp.sum(q * q, 1)[:, None]
                           - 2 * q @ v.T + jnp.sum(v * v, 1)[None, :])
            else:
                raise err.InvalidArgument(f"metric {metric!r}")
            kk = min(k, scores.shape[1])
            s, i = jax.lax.top_k(scores, kk)
            i = i + row_base
            row_base += vectors.shape[0]
            if best_scores is None:
                best_scores, best_ids = s, i
            else:
                cat_s = jnp.concatenate([best_scores, s], axis=1)
                cat_i = jnp.concatenate([best_ids, i], axis=1)
                kk = min(k, cat_s.shape[1])
                best_scores, sel = jax.lax.top_k(cat_s, kk)
                best_ids = jnp.take_along_axis(cat_i, sel, axis=1)
        if best_scores is None:
            raise err.FileNotFound(f"table {self.path} is empty")
        return np.asarray(best_ids), np.asarray(best_scores)

    async def take(self, row_ids: np.ndarray) -> tuple[np.ndarray, dict]:
        """Materialize rows by global row id."""
        row_ids = np.asarray(row_ids).reshape(-1)
        out_vecs = np.zeros((row_ids.size, self.dim), dtype=np.float32)
        out_cols = {name: np.zeros(row_ids.size, dtype=_DTYPES[dt])
                    for name, dt in self.columns.items()}
        base = 0
        async for vectors, cols in self.scan():
            n = vectors.shape[0]
            mask = (row_ids >= base) & (row_ids < base + n)
            if mask.any():
                local = row_ids[mask] - base
                out_vecs[mask] = vectors[local]
                for name in self.columns:
                    out_cols[name][mask] = cols[name][local]
            base += n
        return out_vecs, out_cols
