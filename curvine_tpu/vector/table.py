"""Vector tables on the distributed cache.

Parity: curvine-lancedb/ (Lance columnar tables cached by Curvine, scanned
for embedding lookup). TPU-native rework: row groups are fixed-schema
columnar blobs cached as ordinary files (so they ride the short-circuit
mmap path), and KNN search runs as one bf16 matmul on the TPU — the MXU
does the scan, not a CPU ANN index.

Layout under `<path>/`:
  schema.json                  {"dim": D, "columns": {...}, "row_groups": N}
  rg-00000.vec ...             row groups: [n, D] float32 + packed columns
"""

from __future__ import annotations

import json
import logging

import numpy as np

from curvine_tpu.client import CurvineClient
from curvine_tpu.common import errors as err

log = logging.getLogger(__name__)

_DTYPES = {"f32": np.float32, "i32": np.int32, "i64": np.int64}

_SCAN_FNS: dict = {}


def _scan_fn(metric: str, k: int):
    """Jitted [Q,D]×[D,N] scan+top_k, cached per (metric, k) — a jit
    defined per call would recompile every time. The table array may be
    bf16 (half the HBM traffic of f32 — the scan is bandwidth-bound);
    the MXU accumulates in f32 either way
    (preferred_element_type)."""
    fn = _SCAN_FNS.get((metric, k))
    if fn is None:
        import jax
        import jax.numpy as jnp

        def scan_knn(q, v, ids):
            # v/ids carry a zero-vector sentinel row (id -1) at the end —
            # ONE padded device copy serves both this exact scan and the
            # IVF search's padded takes; the mask keeps the sentinel out
            dots = jax.lax.dot_general(
                q.astype(v.dtype), v,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)    # [Q, N]
            if metric == "cosine":
                qn = jnp.linalg.norm(q, axis=1, keepdims=True).clip(1e-12)
                scores = dots / qn
            else:
                vv = jnp.sum(
                    v.astype(jnp.float32) * v.astype(jnp.float32), 1)
                scores = -(jnp.sum(q * q, 1)[:, None]
                           - 2 * dots + vv[None, :])
            scores = jnp.where(ids[None, :] < 0, -jnp.inf, scores)
            s, dense = jax.lax.top_k(scores, min(k, scores.shape[1]))
            return s, jnp.take(ids, dense)   # dense idx → global row id

        fn = _SCAN_FNS[(metric, k)] = jax.jit(scan_knn)
    return fn


class VectorTable:
    def __init__(self, client: CurvineClient, path: str, dim: int,
                 columns: dict[str, str], row_groups: int,
                 version: int = 0, rows: int | None = None):
        self.client = client
        self.path = path.rstrip("/")
        self.dim = dim
        self.columns = columns
        self.row_groups = row_groups
        self.version = version
        self.rows = rows          # physical rows (None: legacy manifest)
        # deleted global row ids (Lance-style delete vector; rows stay in
        # their row groups until compaction rewrites them out)
        self._deletes: set[int] | None = None
        # device-resident scan cache: the table's LIVE vectors pinned in
        # HBM (normalized per metric) + dense→global id map, so repeated
        # scans run at MXU speed instead of re-streaming host->device
        self._dev_cache: dict = {}
        # lazily-loaded IVF index (vector/index.py); None = not probed
        self._index = None
        self._index_missing = False
        # knn calls that wanted the index but fell back to the exact
        # brute-force scan because it was stale — a silent ~100x serving
        # slowdown otherwise; logged once, counted always
        self.stale_fallbacks = 0
        self._stale_warned = False

    # ---------------- lifecycle ----------------

    @staticmethod
    async def create(client: CurvineClient, path: str, dim: int,
                     columns: dict[str, str] | None = None) -> "VectorTable":
        columns = columns or {}
        for name, dt in columns.items():
            if dt not in _DTYPES:
                raise err.InvalidArgument(f"column {name}: bad dtype {dt}")
        t = VectorTable(client, path, dim, columns, 0, rows=0)
        await client.meta.mkdir(path)
        await t._write_schema()
        return t

    @staticmethod
    async def open(client: CurvineClient, path: str) -> "VectorTable":
        raw = await (await client.open(f"{path.rstrip('/')}/schema.json")
                     ).read_all()
        s = json.loads(raw)
        return VectorTable(client, path, s["dim"], s["columns"],
                           s["row_groups"], version=s.get("version", 0),
                           rows=s.get("rows"))

    async def _write_schema(self) -> None:
        await self.client.write_all(
            f"{self.path}/schema.json",
            json.dumps({"dim": self.dim, "columns": self.columns,
                        "row_groups": self.row_groups,
                        "version": self.version,
                        "rows": self.rows}).encode())

    # ---------------- delete vector ----------------

    async def _load_deletes(self) -> set[int]:
        if self._deletes is None:
            try:
                raw = await (await self.client.open(
                    f"{self.path}/deletes.bin")).read_all()
                self._deletes = set(
                    np.frombuffer(raw, dtype=np.int64).tolist())
            except err.FileNotFound:
                self._deletes = set()
            # any OTHER failure (timeout, connect) propagates WITHOUT
            # memoizing: caching an empty set would silently resurrect
            # tombstoned rows for the life of this instance
        return self._deletes

    async def _save_deletes(self) -> None:
        arr = np.array(sorted(self._deletes or ()), dtype=np.int64)
        await self.client.write_all(f"{self.path}/deletes.bin",
                                    arr.tobytes())

    # ---------------- append / scan ----------------

    def _validate_batch(self, vectors: np.ndarray,
                        columns: dict[str, np.ndarray] | None
                        ) -> tuple[np.ndarray, dict[str, np.ndarray]]:
        columns = columns or {}
        vectors = np.ascontiguousarray(vectors, dtype=np.float32)
        if vectors.ndim != 2 or vectors.shape[1] != self.dim:
            raise err.InvalidArgument(
                f"vectors must be [n, {self.dim}], got {vectors.shape}")
        n = vectors.shape[0]
        out = {}
        for name, dt in self.columns.items():
            if name not in columns:
                raise err.InvalidArgument(f"missing column {name!r}")
            col = np.ascontiguousarray(columns[name], dtype=_DTYPES[dt])
            if col.shape[0] != n:
                raise err.InvalidArgument(f"column {name} length mismatch")
            out[name] = col
        return vectors, out

    async def append(self, vectors: np.ndarray,
                     columns: dict[str, np.ndarray] | None = None) -> int:
        """Append one row group; returns its index."""
        vectors, columns = self._validate_batch(vectors, columns)
        n = vectors.shape[0]
        parts = [np.int64(n).tobytes(), vectors.tobytes()]
        for name in self.columns:
            parts.append(columns[name].tobytes())
        rg = self.row_groups
        await self.client.write_all(f"{self.path}/rg-{rg:05d}.vec",
                                    b"".join(parts))
        self.row_groups += 1
        if self.rows is not None:          # legacy manifests stay lazy
            self.rows += n
        self._dev_cache.clear()
        await self._write_schema()
        return rg

    async def read_group(self, rg: int) -> tuple[np.ndarray, dict]:
        reader = await self.client.open(f"{self.path}/rg-{rg:05d}.vec")
        view = await reader.mmap_view(0, reader.len)
        if view is None:
            view = np.frombuffer(await reader.read_all(), dtype=np.uint8)
        n = int(view[:8].view(np.int64)[0])
        off = 8
        vec_bytes = n * self.dim * 4
        vectors = view[off:off + vec_bytes].view(np.float32).reshape(
            n, self.dim)
        off += vec_bytes
        cols = {}
        for name, dt in self.columns.items():
            dtype = np.dtype(_DTYPES[dt])
            cols[name] = view[off:off + n * dtype.itemsize].view(dtype)
            off += n * dtype.itemsize
        return vectors, cols

    async def scan(self):
        """Async iterator over (vectors, columns) per row group."""
        for rg in range(self.row_groups):
            yield await self.read_group(rg)

    async def _physical_rows(self) -> int:
        if self.rows is not None:
            return self.rows
        total = 0                  # legacy manifest without a row count
        async for vectors, _ in self.scan():
            total += vectors.shape[0]
        self.rows = total
        return total

    async def count(self) -> int:
        """Live rows (deletes excluded)."""
        return await self._physical_rows() - len(await self._load_deletes())

    # ---------------- delete / update / compaction ----------------

    async def delete(self, row_ids) -> int:
        """Mark global row ids deleted (Lance-style delete vector: the
        bytes stay in their row groups until compact()). Returns how many
        NEW rows were deleted."""
        total = await self._physical_rows()
        ids = [int(r) for r in np.asarray(row_ids).reshape(-1)]
        bad = [r for r in ids if not 0 <= r < total]
        if bad:
            raise err.InvalidArgument(
                f"row ids out of range [0, {total}): {bad[:5]}")
        dels = await self._load_deletes()
        before = len(dels)
        dels.update(ids)
        await self._save_deletes()
        self._dev_cache.clear()
        return len(dels) - before

    async def update(self, row_ids, vectors: np.ndarray,
                     columns: dict[str, np.ndarray] | None = None) -> int:
        """delete + insert (the Lance update model): old versions are
        tombstoned, new versions appended as a fresh row group. Returns
        the row-group index holding the new versions."""
        vectors, columns = self._validate_batch(
            np.atleast_2d(np.asarray(vectors, dtype=np.float32)), columns)
        row_ids = np.asarray(row_ids).reshape(-1)
        if vectors.shape[0] != row_ids.size:
            raise err.InvalidArgument("update rows/vectors length mismatch")
        # validation above runs BEFORE the tombstones persist: an invalid
        # replacement must not delete the old versions
        await self.delete(row_ids)
        return await self.append(vectors, columns)

    async def compact(self) -> int:
        """Rewrite row groups dropping deleted rows; global row ids are
        renumbered densely (as with Lance compaction, ids are not stable
        across compactions). Returns live rows kept."""
        dels = await self._load_deletes()
        del_arr = np.fromiter(dels, dtype=np.int64) if dels else \
            np.empty(0, dtype=np.int64)
        old_groups = self.row_groups
        self.row_groups = 0
        self.rows = 0
        self.version += 1
        self._deletes = set()
        # clear the delete vector on disk BEFORE rewriting row groups: a
        # crash mid-compaction then resurrects tombstoned rows
        # (recoverable by re-deleting) instead of tombstoning arbitrary
        # renumbered rows
        await self._save_deletes()
        # stream group by group (no whole-table materialization): each old
        # group's live rows become one new group, in order, so renumbering
        # is dense and peak memory is one row group
        kept = 0
        base = 0
        for rg in range(old_groups):
            vectors, cols = await self.read_group(rg)
            n = vectors.shape[0]
            keep = np.nonzero(~np.isin(np.arange(n) + base, del_arr))[0]
            base += n
            if not keep.size:
                continue
            await self.append(vectors[keep],
                              {name: np.asarray(cols[name])[keep]
                               for name in self.columns})
            kept += int(keep.size)
        if kept == 0:
            await self._write_schema()
        # drop superseded row-group files past the rewritten prefix
        for rg in range(self.row_groups, old_groups):
            try:
                await self.client.meta.delete(f"{self.path}/rg-{rg:05d}.vec")
            except err.CurvineError:
                pass
        self._dev_cache.clear()
        return kept

    # ---------------- TPU knn ----------------

    async def _host_live(self) -> tuple[np.ndarray, np.ndarray]:
        """All LIVE rows as one host [N, D] array + dense→global row-id
        map, in ascending global-id order (index build and the pinned
        device array must agree on this dense ordering)."""
        import asyncio

        dels = await self._load_deletes()
        if self.row_groups == 0:
            raise err.FileNotFound(f"table {self.path} is empty")
        groups = await asyncio.gather(
            *(self.read_group(rg) for rg in range(self.row_groups)))
        host = (np.concatenate([v for v, _ in groups], axis=0)
                if len(groups) > 1 else groups[0][0])
        if dels:
            mask = ~np.isin(np.arange(host.shape[0]),
                            np.fromiter(dels, dtype=np.int64))
            live = np.nonzero(mask)[0].astype(np.int32)
            host = host[live]
        else:
            live = np.arange(host.shape[0], dtype=np.int32)
        if host.shape[0] == 0:
            raise err.FileNotFound(f"table {self.path} has no live rows")
        return host, live

    async def _device_vectors(self, metric: str, device,
                              dtype: str = "f32"):
        """LIVE rows of all row groups as ONE device-resident [N, D]
        array (normalized for cosine) plus a dense→global row-id map,
        pinned across calls — the table lives in HBM like an HBM-tier
        block, and the scan is a single MXU matmul. Row groups are
        fetched concurrently (prefetch) on a cache miss. dtype=\"bf16\"
        pins the table in bfloat16: half the HBM footprint AND half the
        bandwidth of the bandwidth-bound scan (scores still accumulate
        in f32 on the MXU); top-k order can differ for near-ties."""
        import jax
        import jax.numpy as jnp

        dels = await self._load_deletes()
        key = (metric, dtype, getattr(device, "id", device),
               self.row_groups, len(dels))
        hit = self._dev_cache.get(key)
        if hit is not None:
            return hit
        host, live = await self._host_live()
        # sentinel-padded: one extra zero row (id -1) so the IVF search's
        # padded takes stay in-bounds on the SAME resident array as the
        # exact scan (no second device copy of the table)
        host = np.concatenate(
            [host, np.zeros((1, host.shape[1]), dtype=host.dtype)], axis=0)
        live = np.concatenate([live, np.full(1, -1, dtype=live.dtype)])
        v = jax.device_put(host, device)
        if metric == "cosine":
            v = v / jnp.linalg.norm(v, axis=1, keepdims=True).clip(1e-12)
        if dtype == "bf16":
            v = v.astype(jnp.bfloat16)
        v = jax.block_until_ready(v)
        ids = jax.block_until_ready(jax.device_put(live, device))
        self._dev_cache = {key: (v, ids)}   # one resident copy per table
        return v, ids

    # ---------------- IVF index ----------------

    async def create_index(self, nlist: int | None = None,
                           metric: str = "cosine", iters: int = 10,
                           device=None, cap_pct: float = 95.0,
                           pq_m: int | None = None, pq_ksub: int = 256,
                           pq_iters: int = 8,
                           pq_sample: int = 65536) -> "IvfIndex":
        """Build (or rebuild) the IVF ANN index on device and persist it
        as a cached file. Follows the Lance model: the index is a
        snapshot — table mutations leave it stale, and knn falls back to
        the exact scan until the next create_index. `cap_pct` clips the
        inverted-list padding at that percentile of list lengths (spill
        lists absorb the overflow); `pq_m` additionally trains product-
        quantization codebooks with pq_m subspaces × pq_ksub codewords
        and packs uint8 codes, enabling the two-stage ADC + exact-rerank
        search (the Lance IVF_PQ analog). See vector/index.py for the
        TPU-first design."""
        import jax
        from curvine_tpu.vector.index import IvfIndex, table_snapshot

        if metric not in ("cosine", "l2"):
            raise err.InvalidArgument(f"metric {metric!r}")
        if pq_m and self.dim % pq_m:
            raise err.InvalidArgument(
                f"pq_m {pq_m} must divide dim {self.dim}")
        host, live = await self._host_live()
        if metric == "cosine":
            host = host / np.linalg.norm(
                host, axis=1, keepdims=True).clip(1e-12)
        n = host.shape[0]
        if nlist is None:
            nlist = max(1, int(np.sqrt(n)))     # the usual IVF default
        snap = table_snapshot(self)
        snap["metric"] = metric
        dev = device if device is not None else jax.devices()[0]
        idx = IvfIndex.build(host, live, nlist, snap, iters=iters,
                             device=dev, cap_pct=cap_pct, pq_m=pq_m,
                             pq_ksub=pq_ksub, pq_iters=pq_iters,
                             pq_sample=pq_sample)
        await self.client.write_all(f"{self.path}/index.ivf",
                                    idx.to_bytes())
        self._index = idx
        self._index_missing = False
        return idx

    async def _load_index(self):
        from curvine_tpu.vector.index import IvfIndex

        if self._index is not None or self._index_missing:
            return self._index
        try:
            raw = await (await self.client.open(
                f"{self.path}/index.ivf")).read_all()
        except err.FileNotFound:
            self._index_missing = True
            return None
        self._index = IvfIndex.from_bytes(raw)
        return self._index

    async def _fresh_index(self, metric: str):
        """The persisted index, or None when absent/stale/other-metric
        (knn then uses the exact scan)."""
        from curvine_tpu.vector.index import table_snapshot

        idx = await self._load_index()
        if idx is None:
            return None
        await self._load_deletes()
        snap = table_snapshot(self)
        snap["metric"] = metric
        return idx if idx.built_at == snap else None

    async def knn(self, query: np.ndarray, k: int = 10,
                  metric: str = "cosine", device=None,
                  materialize: bool = True, use_index: bool = True,
                  nprobe: int = 8, dtype: str = "f32",
                  use_pq: bool | str = "auto", rerank: int | None = None,
                  pallas: bool | str = "auto"):
        """Top-k nearest rows to `query` [D] or [Q, D].

        With a FRESH IVF index (create_index since the last mutation) and
        use_index=True, the scan is chained device stages — queries ×
        centroids, then a gather+dot over only the probed lists; with PQ
        codes (create_index(pq_m=...)) and use_pq, the probed lists are
        scored by the 8-bit ADC scan first and only the top-`rerank`
        survivors are gathered for the exact re-rank (see
        vector/index.py); results are approximate with recall set by
        `nprobe` (and `rerank` on the PQ path). Otherwise it is ONE
        exact [Q, D]×[D, N] matmul + top_k over the pinned table — no
        per-group host loop, no re-streaming (the round-2 per-group
        await+device_put pattern benched at Python speed, not MXU
        speed). A STALE index (mutations since create_index) silently
        degrading to the brute-force scan is a ~100x serving regression,
        so it is warned once and counted in `stale_fallbacks`.

        materialize=False returns device arrays without forcing a
        device→host sync — callers issuing a stream of scans can pipeline
        dispatches and block once (remote-dispatch RTT amortizes)."""
        import jax

        if metric not in ("cosine", "l2"):
            raise err.InvalidArgument(f"metric {metric!r}")
        if dtype not in ("f32", "bf16"):
            raise err.InvalidArgument(f"dtype {dtype!r}")
        query = np.atleast_2d(np.asarray(query, dtype=np.float32))
        if query.shape[1] != self.dim:
            raise err.InvalidArgument(f"query dim {query.shape[1]} != {self.dim}")
        dev = device if device is not None else jax.devices()[0]
        v, ids = await self._device_vectors(metric, dev, dtype=dtype)
        idx = await self._fresh_index(metric) if use_index else None
        if use_index and idx is None and self._index is not None:
            self.stale_fallbacks += 1
            if not self._stale_warned:
                self._stale_warned = True
                log.warning(
                    "table %s: IVF index is stale (or built for another "
                    "metric) — knn falling back to the exact brute-force "
                    "scan until create_index() rebuilds it (warned once; "
                    "see the stale_fallbacks counter)", self.path)
        if idx is not None:
            s, i = idx.search(query, v, ids, k, metric, nprobe, dev,
                              use_pq=use_pq, rerank=rerank, pallas=pallas)
        else:
            q = jax.device_put(query, dev)
            s, i = _scan_fn(metric, k)(q, v, ids)
        if not materialize:
            return i, s
        return np.asarray(i), np.asarray(s)

    async def take(self, row_ids: np.ndarray) -> tuple[np.ndarray, dict]:
        """Materialize rows by global row id (deleted rows are invalid)."""
        row_ids = np.asarray(row_ids).reshape(-1)
        dels = await self._load_deletes()
        bad = [int(r) for r in row_ids if int(r) in dels]
        if bad:
            raise err.InvalidArgument(f"row ids deleted: {bad[:5]}")
        out_vecs = np.zeros((row_ids.size, self.dim), dtype=np.float32)
        out_cols = {name: np.zeros(row_ids.size, dtype=_DTYPES[dt])
                    for name, dt in self.columns.items()}
        base = 0
        async for vectors, cols in self.scan():
            n = vectors.shape[0]
            mask = (row_ids >= base) & (row_ids < base + n)
            if mask.any():
                local = row_ids[mask] - base
                out_vecs[mask] = vectors[local]
                for name in self.columns:
                    out_cols[name][mask] = cols[name][local]
            base += n
        return out_vecs, out_cols
