"""python -m curvine_tpu.csi — run the CSI driver."""
import argparse
import time

from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.csi.driver import CsiDriver

p = argparse.ArgumentParser()
p.add_argument("--endpoint", default="unix:///tmp/curvine-csi.sock")
p.add_argument("--conf", default=None)
p.add_argument("--node-id", default=None)
args = p.parse_args()

driver = CsiDriver(conf=ClusterConf.load(args.conf), endpoint=args.endpoint,
                   node_id=args.node_id)
driver.start()
try:
    while True:
        time.sleep(3600)
except KeyboardInterrupt:
    driver.stop()
