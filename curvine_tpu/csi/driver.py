"""CSI driver: Identity / Controller / Node gRPC services.

Parity: curvine-csi (Go). Volumes are directories in the Curvine
namespace (`/csi-volumes/<id>` by default) — CreateVolume is a mkdir
(millisecond provisioning, no cloud API), NodePublishVolume is a FUSE
mount of that subtree at the kubelet target path.

gRPC servicing uses generic method handlers (no grpc_tools codegen in
this image); message classes come from `protoc --python_out` of the
spec-field-compatible csi.proto next to this file."""

from __future__ import annotations

import asyncio
import logging
import socket
import threading
from concurrent import futures

import grpc

from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.csi import csi_pb2 as pb

log = logging.getLogger(__name__)

DRIVER_NAME = "tpu.curvine.csi"
VERSION = "0.1.0"
VOLUME_ROOT = "/csi-volumes"


class _Bridge:
    """Sync gRPC servicer thread → asyncio curvine client."""

    def __init__(self, conf: ClusterConf):
        self.conf = conf
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True, name="csi-bridge")
        self.thread.start()
        from curvine_tpu.client import CurvineClient

        async def make():
            return CurvineClient(conf)
        self.client = self.run(make())

    def run(self, coro, timeout: float = 60):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    def close(self):
        self.run(self.client.close())
        self.loop.call_soon_threadsafe(self.loop.stop)


class CsiDriver:
    def __init__(self, conf: ClusterConf | None = None,
                 endpoint: str = "unix:///tmp/curvine-csi.sock",
                 node_id: str | None = None):
        self.conf = conf or ClusterConf()
        self.endpoint = endpoint
        self.node_id = node_id or socket.gethostname()
        self.bridge = _Bridge(self.conf)
        self.server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._mounted: dict[str, object] = {}   # target_path → session
        for name, methods in self._services().items():
            handlers = {
                m: grpc.unary_unary_rpc_method_handler(
                    fn, request_deserializer=req.FromString,
                    response_serializer=lambda resp: resp.SerializeToString())
                for m, (fn, req) in methods.items()
            }
            self.server.add_generic_rpc_handlers(
                (grpc.method_handlers_generic_handler(name, handlers),))
        self.server.add_insecure_port(self.endpoint)

    def start(self) -> None:
        self.server.start()
        log.info("csi driver %s serving at %s", DRIVER_NAME, self.endpoint)

    def stop(self) -> None:
        self.server.stop(grace=1)
        self.bridge.close()

    # ---------------- service table ----------------

    def _services(self):
        return {
            "csi.v1.Identity": {
                "GetPluginInfo": (self.get_plugin_info,
                                  pb.GetPluginInfoRequest),
                "GetPluginCapabilities": (self.get_plugin_capabilities,
                                          pb.GetPluginCapabilitiesRequest),
                "Probe": (self.probe, pb.ProbeRequest),
            },
            "csi.v1.Controller": {
                "CreateVolume": (self.create_volume, pb.CreateVolumeRequest),
                "DeleteVolume": (self.delete_volume, pb.DeleteVolumeRequest),
                "ValidateVolumeCapabilities": (
                    self.validate_volume_capabilities,
                    pb.ValidateVolumeCapabilitiesRequest),
                "ControllerGetCapabilities": (
                    self.controller_get_capabilities,
                    pb.ControllerGetCapabilitiesRequest),
            },
            "csi.v1.Node": {
                "NodeStageVolume": (self.node_stage, pb.NodeStageVolumeRequest),
                "NodeUnstageVolume": (self.node_unstage,
                                      pb.NodeUnstageVolumeRequest),
                "NodePublishVolume": (self.node_publish,
                                      pb.NodePublishVolumeRequest),
                "NodeUnpublishVolume": (self.node_unpublish,
                                        pb.NodeUnpublishVolumeRequest),
                "NodeGetCapabilities": (self.node_get_capabilities,
                                        pb.NodeGetCapabilitiesRequest),
                "NodeGetInfo": (self.node_get_info, pb.NodeGetInfoRequest),
            },
        }

    # ---------------- Identity ----------------

    def get_plugin_info(self, req, ctx):
        return pb.GetPluginInfoResponse(name=DRIVER_NAME,
                                        vendor_version=VERSION)

    def get_plugin_capabilities(self, req, ctx):
        cap = pb.PluginCapability(
            service=pb.PluginCapability.Service(
                type=pb.PluginCapability.Service.CONTROLLER_SERVICE))
        return pb.GetPluginCapabilitiesResponse(capabilities=[cap])

    def probe(self, req, ctx):
        try:
            self.bridge.run(self.bridge.client.meta.master_info(), timeout=5)
            ready = True
        except Exception:  # noqa: BLE001 — probe reports, never raises
            ready = False
        resp = pb.ProbeResponse()
        resp.ready.value = ready
        return resp

    # ---------------- Controller ----------------

    def _vol_path(self, volume_id: str) -> str:
        return f"{VOLUME_ROOT}/{volume_id}"

    def create_volume(self, req, ctx):
        volume_id = req.name or "vol"
        path = self._vol_path(volume_id)
        self.bridge.run(self.bridge.client.meta.mkdir(path))
        cap = req.capacity_range.required_bytes or 0
        log.info("csi created volume %s at %s", volume_id, path)
        return pb.CreateVolumeResponse(volume=pb.Volume(
            capacity_bytes=cap, volume_id=volume_id,
            volume_context={"path": path}))

    def delete_volume(self, req, ctx):
        path = self._vol_path(req.volume_id)
        try:
            self.bridge.run(self.bridge.client.meta.delete(path,
                                                           recursive=True))
        except Exception as e:  # noqa: BLE001 — idempotent delete
            log.debug("delete volume %s: %s", req.volume_id, e)
        return pb.DeleteVolumeResponse()

    def validate_volume_capabilities(self, req, ctx):
        confirmed = pb.ValidateVolumeCapabilitiesResponse.Confirmed(
            volume_capabilities=list(req.volume_capabilities))
        return pb.ValidateVolumeCapabilitiesResponse(confirmed=confirmed)

    def controller_get_capabilities(self, req, ctx):
        cap = pb.ControllerServiceCapability(
            rpc=pb.ControllerServiceCapability.RPC(
                type=pb.ControllerServiceCapability.RPC.CREATE_DELETE_VOLUME))
        return pb.ControllerGetCapabilitiesResponse(capabilities=[cap])

    # ---------------- Node ----------------

    def node_stage(self, req, ctx):
        return pb.NodeStageVolumeResponse()

    def node_unstage(self, req, ctx):
        return pb.NodeUnstageVolumeResponse()

    def node_publish(self, req, ctx):
        """FUSE-mount the volume subtree at the kubelet target path."""
        from curvine_tpu.fuse.mount import fusermount_mount
        from curvine_tpu.fuse.ops import CurvineFuseFs
        from curvine_tpu.fuse.session import FuseSession
        import os

        target = req.target_path
        subtree = req.volume_context.get("path",
                                         self._vol_path(req.volume_id))

        async def mount():
            fd = fusermount_mount(target)
            fs = CurvineFuseFs(self.bridge.client, fs_root=subtree,
                               uid=os.getuid(), gid=os.getgid())
            session = FuseSession(fs, fd)
            asyncio.ensure_future(session.run())
            return session

        self._mounted[target] = self.bridge.run(mount())
        log.info("csi published %s at %s", subtree, target)
        return pb.NodePublishVolumeResponse()

    def node_unpublish(self, req, ctx):
        from curvine_tpu.fuse.mount import fusermount_umount
        session = self._mounted.pop(req.target_path, None)
        fusermount_umount(req.target_path)
        if session is not None:
            session.stop()
        return pb.NodeUnpublishVolumeResponse()

    def node_get_capabilities(self, req, ctx):
        return pb.NodeGetCapabilitiesResponse(capabilities=[])

    def node_get_info(self, req, ctx):
        return pb.NodeGetInfoResponse(node_id=self.node_id,
                                      max_volumes_per_node=0)
