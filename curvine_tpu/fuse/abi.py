"""FUSE kernel protocol ABI (v7.31 wire format).

Parity: curvine-fuse/src/raw/ (request/response structs mirrored from
<linux/fuse.h>). Little-endian, 8-byte aligned structs, spoken directly
over /dev/fuse — no libfuse."""

from __future__ import annotations

import struct
from dataclasses import dataclass

KERNEL_VERSION = 7
KERNEL_MINOR = 31


class Op:
    LOOKUP = 1
    FORGET = 2
    GETATTR = 3
    SETATTR = 4
    READLINK = 5
    SYMLINK = 6
    MKNOD = 8
    MKDIR = 9
    UNLINK = 10
    RMDIR = 11
    RENAME = 12
    LINK = 13
    OPEN = 14
    READ = 15
    WRITE = 16
    STATFS = 17
    RELEASE = 18
    FSYNC = 20
    SETXATTR = 21
    GETXATTR = 22
    LISTXATTR = 23
    REMOVEXATTR = 24
    FLUSH = 25
    INIT = 26
    OPENDIR = 27
    READDIR = 28
    RELEASEDIR = 29
    FSYNCDIR = 30
    GETLK = 31
    SETLK = 32
    SETLKW = 33
    ACCESS = 34
    CREATE = 35
    INTERRUPT = 36
    BMAP = 37
    DESTROY = 38
    IOCTL = 39
    POLL = 40
    NOTIFY_REPLY = 41
    BATCH_FORGET = 42
    FALLOCATE = 43
    READDIRPLUS = 44
    RENAME2 = 45
    LSEEK = 46
    COPY_FILE_RANGE = 47


# errno values we return (negated in the out header)
class Errno:
    EPERM = 1
    ENOENT = 2
    EIO = 5
    EAGAIN = 11
    EACCES = 13
    EEXIST = 17
    ENOTDIR = 20
    EISDIR = 21
    EINVAL = 22
    ENOSPC = 28
    EFBIG = 27
    EROFS = 30
    ENOSYS = 38
    ENOTEMPTY = 39
    ENODATA = 61
    EINTR = 4
    EDEADLK = 35
    ESTALE = 116
    EOPNOTSUPP = 95


IN_HEADER = struct.Struct("<IIQQIIII")      # len,opcode,unique,nodeid,uid,gid,pid,padding
OUT_HEADER = struct.Struct("<IiQ")          # len,error,unique

# fuse_attr: ino,size,blocks,atime,mtime,ctime,atimensec,mtimensec,
#            ctimensec,mode,nlink,uid,gid,rdev,blksize,padding
ATTR = struct.Struct("<QQQQQQIIIIIIIIII")
ATTR_SIZE = ATTR.size                        # 88

# fuse_entry_out: nodeid,generation,entry_valid,attr_valid,
#                 entry_valid_nsec,attr_valid_nsec + attr
ENTRY_OUT = struct.Struct("<QQQQII")
ENTRY_OUT_SIZE = ENTRY_OUT.size + ATTR_SIZE  # 128

ATTR_OUT = struct.Struct("<QII")             # attr_valid,valid_nsec,dummy
OPEN_OUT = struct.Struct("<QII")             # fh,open_flags,padding
INIT_IN = struct.Struct("<IIII")             # major,minor,max_readahead,flags
# fuse_init_out (7.23+): major,minor,max_readahead,flags,max_background,
#   congestion_threshold,max_write,time_gran,max_pages,padding,unused[8]
INIT_OUT = struct.Struct("<IIIIHHIIHH8I")
GETATTR_IN = struct.Struct("<IIQ")           # flags,dummy,fh
READ_IN = struct.Struct("<QQIIQII")          # fh,offset,size,read_flags,lock_owner,flags,padding
WRITE_IN = struct.Struct("<QQIIQII")         # fh,offset,size,write_flags,lock_owner,flags,padding
WRITE_OUT = struct.Struct("<II")             # size,padding
RELEASE_IN = struct.Struct("<QIIQ")          # fh,flags,release_flags,lock_owner
FLUSH_IN = struct.Struct("<QIIQ")            # fh,unused,padding,lock_owner
FSYNC_IN = struct.Struct("<QII")             # fh,fsync_flags,padding
MKDIR_IN = struct.Struct("<II")              # mode,umask
CREATE_IN = struct.Struct("<IIII")           # flags,mode,umask,open_flags
OPEN_IN = struct.Struct("<II")               # flags,open_flags
RENAME2_IN = struct.Struct("<QII")           # newdir,flags,padding
RENAME_IN = struct.Struct("<Q")              # newdir
LINK_IN = struct.Struct("<Q")                # oldnodeid
ACCESS_IN = struct.Struct("<II")             # mask,padding
INTERRUPT_IN = struct.Struct("<Q")           # unique
FORGET_IN = struct.Struct("<Q")              # nlookup
FALLOCATE_IN = struct.Struct("<QQQII")       # fh,offset,length,mode,padding
LSEEK_IN = struct.Struct("<QQII")            # fh,offset,whence,padding
LSEEK_OUT = struct.Struct("<Q")              # offset
# fuse_setattr_in: valid,padding,fh,size,lock_owner,atime,mtime,ctime,
#   atimensec,mtimensec,ctimensec,mode,unused4,uid,gid,unused5
SETATTR_IN = struct.Struct("<IIQQQQQQIIIIIIII")
STATFS_OUT = struct.Struct("<QQQQQIIII6I")   # kstatfs (blocks..frsize,padding,spare[6])
GETXATTR_IN = struct.Struct("<II")           # size,padding
GETXATTR_OUT = struct.Struct("<II")          # size,padding
SETXATTR_IN = struct.Struct("<II")           # size,flags

DIRENT_HDR = struct.Struct("<QQII")          # ino,off,namelen,type

# fuse_lk_in: fh,owner + fuse_file_lock{start,end,type,pid} + lk_flags,pad
LK_IN = struct.Struct("<QQQQIIII")
LK_OUT = struct.Struct("<QQII")              # fuse_file_lock
FUSE_LK_FLOCK = 1 << 0                       # lk_flags: flock, not fcntl
FOPEN_KEEP_CACHE = 1 << 1                    # open_flags: keep page cache


class SetattrValid:
    MODE = 1 << 0
    UID = 1 << 1
    GID = 1 << 2
    SIZE = 1 << 3
    ATIME = 1 << 4
    MTIME = 1 << 5
    FH = 1 << 6
    ATIME_NOW = 1 << 7
    MTIME_NOW = 1 << 8


class InitFlags:
    ASYNC_READ = 1 << 0
    POSIX_LOCKS = 1 << 1
    ATOMIC_O_TRUNC = 1 << 3
    BIG_WRITES = 1 << 5
    FLOCK_LOCKS = 1 << 10
    AUTO_INVAL_DATA = 1 << 12
    DO_READDIRPLUS = 1 << 13
    READDIRPLUS_AUTO = 1 << 14
    WRITEBACK_CACHE = 1 << 16
    PARALLEL_DIROPS = 1 << 18
    MAX_PAGES = 1 << 22
    CACHE_SYMLINKS = 1 << 23


S_IFDIR = 0o040000
S_IFREG = 0o100000
S_IFLNK = 0o120000
DT_DIR = 4
DT_REG = 8
DT_LNK = 10


@dataclass
class InHeader:
    length: int
    opcode: int
    unique: int
    nodeid: int
    uid: int
    gid: int
    pid: int

    @staticmethod
    def parse(buf: memoryview) -> "InHeader":
        length, opcode, unique, nodeid, uid, gid, pid, _ = \
            IN_HEADER.unpack_from(buf, 0)
        return InHeader(length, opcode, unique, nodeid, uid, gid, pid)


def pack_attr(ino: int, size: int, mode: int, nlink: int = 1,
              mtime_ms: int = 0, atime_ms: int = 0, uid: int = 0,
              gid: int = 0, blksize: int = 4096) -> bytes:
    mt, mtn = divmod(mtime_ms, 1000)
    at, atn = divmod(atime_ms, 1000)
    return ATTR.pack(ino, size, (size + 511) // 512, at, mt, mt,
                     atn * 1_000_000, mtn * 1_000_000, mtn * 1_000_000,
                     mode, nlink, uid, gid, 0, blksize, 0)


def pack_entry_out(nodeid: int, attr: bytes, entry_ttl_ms: int,
                   attr_ttl_ms: int, generation: int = 0) -> bytes:
    ev, evn = divmod(entry_ttl_ms, 1000)
    av, avn = divmod(attr_ttl_ms, 1000)
    return ENTRY_OUT.pack(nodeid, generation, ev, av,
                          evn * 1_000_000, avn * 1_000_000) + attr


def pack_reply(unique: int, payload: bytes = b"", error: int = 0) -> bytes:
    return OUT_HEADER.pack(OUT_HEADER.size + len(payload), -error,
                           unique) + payload


def pack_reply_header(unique: int, payload_len: int, error: int = 0) -> bytes:
    """Header alone — pair with writev to emit large payloads uncopied."""
    return OUT_HEADER.pack(OUT_HEADER.size + payload_len, -error, unique)


def pack_dirent(ino: int, off: int, name: bytes, dtype: int) -> bytes:
    ent = DIRENT_HDR.pack(ino, off, len(name), dtype) + name
    pad = (-len(ent)) % 8
    return ent + b"\x00" * pad


def pack_direntplus(entry_out: bytes, ino: int, off: int, name: bytes,
                    dtype: int) -> bytes:
    ent = entry_out + DIRENT_HDR.pack(ino, off, len(name), dtype) + name
    pad = (-len(ent)) % 8
    return ent + b"\x00" * pad
