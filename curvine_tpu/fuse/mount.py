"""Mount/umount via fusermount (unprivileged) and the serve entrypoint.

Parity: curvine-fuse/src/bin (cv-fuse) + session mount handling. The
/dev/fuse fd is obtained through fusermount's _FUSE_COMMFD SCM_RIGHTS
handshake, so no root is needed."""

from __future__ import annotations

import array
import asyncio
import logging
import os
import socket
import subprocess

# An in-process FUSE daemon makes subprocess's vfork fast path a
# deadlock machine: vfork suspends the calling thread WITH THE GIL HELD
# until the child execs, but the child's fd-closing can send a FUSE
# FLUSH that only a (GIL-needing) Python daemon thread can answer —
# child never execs, GIL never releases. Plain fork returns immediately
# and waitpid drops the GIL, so the daemon can serve the child. Any
# process importing this module may mount FUSE in-process, so the knob
# is flipped here, once, for the whole process.
if hasattr(subprocess, "_USE_VFORK"):
    subprocess._USE_VFORK = False

from curvine_tpu.common.conf import ClusterConf

log = logging.getLogger(__name__)


def fusermount_mount(mountpoint: str, fsname: str = "curvine",
                     options: str = "") -> int:
    """Returns the /dev/fuse fd for the new mount."""
    os.makedirs(mountpoint, exist_ok=True)
    recv_sock, send_sock = socket.socketpair(socket.AF_UNIX,
                                             socket.SOCK_STREAM)
    opts = f"rootmode=40000,user_id={os.getuid()},group_id={os.getgid()}," \
           f"fsname={fsname},subtype=curvine,max_read={1024 * 1024}"
    if options:
        opts += "," + options
    env = dict(os.environ, _FUSE_COMMFD=str(send_sock.fileno()))
    proc = subprocess.run(
        ["fusermount", "-o", opts, "--", mountpoint],
        env=env, pass_fds=(send_sock.fileno(),),
        capture_output=True, text=True)
    send_sock.close()
    if proc.returncode != 0:
        recv_sock.close()
        raise OSError(f"fusermount failed: {proc.stderr.strip()}")
    fds = array.array("i")
    msg, ancdata, _, _ = recv_sock.recvmsg(4, socket.CMSG_LEN(4))
    for level, ctype, data in ancdata:
        if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
            fds.frombytes(data[:4])
    recv_sock.close()
    if not fds:
        raise OSError("fusermount did not pass a /dev/fuse fd")
    return fds[0]


def fusermount_umount(mountpoint: str, lazy: bool = True) -> None:
    cmd = ["fusermount", "-u"]
    if lazy:
        cmd.append("-z")
    subprocess.run(cmd + ["--", mountpoint], capture_output=True)


def tune_readahead(mountpoint: str, read_ahead_kb: int) -> bool:
    """Raise the mount's bdi readahead window so sequential reads reach
    the daemon as max_write-sized requests instead of the kernel-default
    128 KiB — per-op cost (request copy + dispatch + reply writev +
    waker) dominates the FUSE read path, and 8x fewer ops is the single
    biggest seq-read lever (measured: 256 -> 32 READ ops per 32 MiB).

    The device number comes from /proc/self/mountinfo, NOT os.stat(mnt):
    a stat would issue a FUSE GETATTR back into the daemon — deadlock
    when called from the serving loop. Best-effort: needs a writable
    /sys (root/privileged container); False means kernel default stays."""
    try:
        with open("/proc/self/mountinfo") as f:
            for line in f:
                parts = line.split()
                if len(parts) > 4 and parts[4] == mountpoint:
                    path = f"/sys/class/bdi/{parts[2]}/read_ahead_kb"
                    with open(path, "w") as bdi:
                        bdi.write(str(read_ahead_kb))
                    log.info("fuse readahead %s -> %d KiB", mountpoint,
                             read_ahead_kb)
                    return True
    except OSError as e:
        log.debug("fuse readahead tuning unavailable: %s", e)
    return False


async def tune_readahead_retry(mountpoint: str, read_ahead_kb: int,
                               attempts: int = 10,
                               delay_s: float = 0.3) -> bool:
    """tune_readahead with retries: the bdi sysfs node can appear a
    beat AFTER fusermount returns. One shared loop for the daemon and
    bench — what ships is what gets measured."""
    for _ in range(attempts):
        if await asyncio.to_thread(tune_readahead, mountpoint,
                                   read_ahead_kb):
            return True
        await asyncio.sleep(delay_s)
    return False


async def mount_and_serve(conf: ClusterConf) -> None:
    """cv fuse: mount the namespace and serve until unmounted."""
    from curvine_tpu.client import CurvineClient
    from curvine_tpu.fuse.ops import CurvineFuseFs
    from curvine_tpu.fuse.session import FuseSession

    client = CurvineClient(conf)
    fd = fusermount_mount(conf.fuse.mount_point)
    fs = CurvineFuseFs(client, fs_root=conf.fuse.fs_path,
                       attr_ttl_ms=conf.fuse.attr_ttl_ms,
                       entry_ttl_ms=conf.fuse.entry_ttl_ms,
                       max_write=conf.fuse.max_write,
                       uid=os.getuid(), gid=os.getgid(),
                       inplace_max_mb=conf.fuse.inplace_max_mb)
    session = FuseSession(fs, fd, max_write=conf.fuse.max_write)
    log.info("fuse mounted at %s", conf.fuse.mount_point)
    tune_task = None
    if conf.fuse.read_ahead_kb > 0:
        # runs in the background while the session starts serving
        tune_task = asyncio.ensure_future(tune_readahead_retry(
            conf.fuse.mount_point, conf.fuse.read_ahead_kb))
    runner = None
    if conf.fuse.metrics_port > 0:
        runner = await serve_metrics(fs, conf.fuse.metrics_port,
                                     conf.fuse.metrics_host)
    try:
        await session.run()
    finally:
        session.stop()
        if tune_task is not None:
            tune_task.cancel()
        if runner is not None:
            await runner.cleanup()
        fusermount_umount(conf.fuse.mount_point)
        await client.close()


async def serve_metrics(fs, port: int, host: str = "127.0.0.1"):
    """Per-mount metrics plane: /metrics (prometheus text) and /ops
    (JSON per-op counters + latency quantiles). Parity:
    curvine-fuse/src/web_server.rs + fuse_metrics.rs. Binds loopback by
    default — op names leak path activity; expose deliberately via
    conf.fuse.metrics_host."""
    import json

    from aiohttp import web

    async def metrics(_req):
        return web.Response(text=fs.metrics.prometheus_text(),
                            content_type="text/plain")

    async def ops(_req):
        snap = fs.metrics.snapshot()
        out = {"counters": snap.get("counters", {}), "ops": {}}
        for name, h in (snap.get("histograms") or {}).items():
            out["ops"][name] = h
        return web.Response(text=json.dumps(out, indent=1),
                            content_type="application/json")

    app = web.Application()
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/ops", ops)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    log.info("fuse metrics at :%d/metrics", port)
    return runner
