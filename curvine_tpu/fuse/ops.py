"""FUSE operation handlers backed by the Curvine client.

Parity: curvine-fuse/src/fs/ (CurvineFileSystem: lookup/getattr/mkdir/
rmdir/unlink/rename/open/create/read/write/flush/release/readdir(plus)/
statfs/xattr/symlink/link) and fs/dcache.rs (nodeid↔path table)."""

from __future__ import annotations

import logging
import os
import time
from contextlib import nullcontext

from curvine_tpu.common import errors as cerr
from curvine_tpu.common.types import FileStatus, SetAttrOpts
from curvine_tpu.fuse import abi
from curvine_tpu.fuse.abi import Errno, Op

log = logging.getLogger(__name__)

ROOT_ID = 1


_UID_CACHE: dict = {}


def _uid_names(uid: int, gid: int) -> tuple[str, list[str]]:
    """Map kernel uid/gid to (user, group names) via the host user db;
    unknown ids fall back to their decimal string."""
    key = (uid, gid)
    hit = _UID_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        import pwd
        user = pwd.getpwuid(uid).pw_name
    except (KeyError, OSError):
        user = str(uid)
    groups = []
    try:
        import grp
        groups.append(grp.getgrgid(gid).gr_name)
        groups.extend(g.gr_name for g in grp.getgrall()
                      if user in g.gr_mem)
    except (KeyError, OSError):
        groups.append(str(gid))
    _UID_CACHE[key] = (user, groups)
    return user, groups


class FuseError(Exception):
    def __init__(self, errno: int):
        self.errno = errno


_ERRNO_MAP = {
    cerr.ErrorCode.FILE_NOT_FOUND: Errno.ENOENT,
    cerr.ErrorCode.FILE_ALREADY_EXISTS: Errno.EEXIST,
    cerr.ErrorCode.DIR_NOT_EMPTY: Errno.ENOTEMPTY,
    cerr.ErrorCode.NOT_A_DIRECTORY: Errno.ENOTDIR,
    cerr.ErrorCode.IS_A_DIRECTORY: Errno.EISDIR,
    cerr.ErrorCode.INVALID_PATH: Errno.EINVAL,
    cerr.ErrorCode.INVALID_ARGUMENT: Errno.EINVAL,
    cerr.ErrorCode.CAPACITY_EXCEEDED: Errno.ENOSPC,
    cerr.ErrorCode.PERMISSION_DENIED: Errno.EACCES,
    cerr.ErrorCode.UNSUPPORTED: Errno.EOPNOTSUPP,
    cerr.ErrorCode.LEASE_CONFLICT: Errno.EAGAIN,
}


def _fuse_errno(e: cerr.CurvineError) -> int:
    return _ERRNO_MAP.get(e.code, Errno.EIO)


class _StagedFile:
    """RAM-staged file content for in-place / random-offset writes.

    Cache files are immutable once complete (sequential-write object
    semantics, matching curvine-fuse/src/fs/fuse_writer.rs); an in-place
    open therefore stages the WHOLE file in memory, applies writes at
    arbitrary offsets, and rewrites the object at release (or fsync).
    This is what makes editors, fio rand-write, and O_RDWR
    read-after-write patterns work over the mount for files up to
    fuse.inplace_max_mb; larger files keep the honest EOPNOTSUPP.
    Last-close-wins across concurrent handles (no shared page cache)."""

    __slots__ = ("client", "path", "buf", "dirty", "cap")

    def __init__(self, client, path: str, data: bytes, cap: int,
                 dirty: bool = False):
        self.client = client
        self.path = path
        self.buf = bytearray(data)
        self.dirty = dirty
        self.cap = cap

    @property
    def pos(self) -> int:           # _open_writers live-size contract
        return len(self.buf)

    exact_size = True               # getattr: len(buf) IS the size

    def _check_cap(self, size: int) -> None:
        # growth through the handle honors the same bound as the open
        # (a 1TB ftruncate must not OOM the mount process)
        if size > self.cap:
            raise FuseError(Errno.EFBIG)

    def pwrite(self, offset: int, data) -> None:
        end = offset + len(data)
        self._check_cap(end)
        if offset > len(self.buf):
            self.buf.extend(b"\x00" * (offset - len(self.buf)))
        if end > len(self.buf):
            self.buf.extend(b"\x00" * (end - len(self.buf)))
        self.buf[offset:end] = data
        self.dirty = True

    def pread(self, offset: int, size: int) -> bytes:
        return bytes(self.buf[offset:offset + size])

    def truncate(self, size: int) -> None:
        self._check_cap(size)
        if size < len(self.buf):
            del self.buf[size:]
        else:
            self.buf.extend(b"\x00" * (size - len(self.buf)))
        self.dirty = True

    async def persist(self) -> None:
        if self.dirty:
            await self.client.write_all(self.path, bytes(self.buf))
            self.dirty = False


class _Handle:
    __slots__ = ("reader", "writer", "staged", "entries", "path", "lock",
                 "pending")

    def __init__(self, reader=None, writer=None, staged=None, entries=None,
                 path=""):
        self.reader = reader
        self.writer = writer
        self.staged = staged
        self.entries = entries
        self.path = path
        import asyncio
        self.lock = asyncio.Lock()
        # out-of-order WRITEs parked until the stream catches up
        self.pending: dict[int, bytes] = {}


class CurvineFuseFs:
    def __init__(self, client, fs_root: str = "/", attr_ttl_ms: int = 1000,
                 entry_ttl_ms: int = 1000, max_write: int = 1024 * 1024,
                 uid: int = 0, gid: int = 0,
                 inplace_max_mb: int = 256):
        self.client = client
        self.fs_root = fs_root.rstrip("/") or ""
        self.attr_ttl = attr_ttl_ms
        self.entry_ttl = entry_ttl_ms
        self.max_write = max_write
        self.inplace_max = inplace_max_mb * 1024 * 1024
        self.uid, self.gid = uid, gid
        self.nodes: dict[int, str] = {ROOT_ID: self.fs_root or "/"}
        self.ids: dict[str, int] = {self.fs_root or "/": ROOT_ID}
        self._next_node = 2
        self.handles: dict[int, _Handle] = {}
        self._next_fh = 1
        self.destroyed = False
        # path → FsWriter for in-flight writes (getattr sees live size)
        self._open_writers: dict[int, object] = {}
        # access(2) result cache: (nodeid, uid, gid, mask) -> (ok, expiry)
        self._access_cache: dict = {}
        from curvine_tpu.common.metrics import MetricsRegistry
        self.metrics = MetricsRegistry("fuse")
        from curvine_tpu.fuse.plock import PlockTable
        self.plocks = PlockTable()
        # unique -> task, for INTERRUPT of blocked requests (SETLKW)
        self._interruptible: dict[int, object] = {}

    # ---------------- node table (dcache) ----------------

    def node_path(self, nodeid: int) -> str:
        path = self.nodes.get(nodeid)
        if path is None:
            raise FuseError(Errno.ESTALE)
        return path

    def intern(self, path: str) -> int:
        nid = self.ids.get(path)
        if nid is None:
            nid = self._next_node
            self._next_node += 1
            self.ids[path] = nid
            self.nodes[nid] = path
        return nid

    def _drop_path(self, path: str) -> None:
        nid = self.ids.pop(path, None)
        if nid is not None:
            self.nodes.pop(nid, None)

    def _child(self, nodeid: int, name: bytes) -> str:
        base = self.node_path(nodeid)
        n = name.decode()
        return f"{base.rstrip('/')}/{n}" if base != "/" else f"/{n}"

    # ---------------- attr helpers ----------------

    def _mode_of(self, st: FileStatus) -> int:
        if st.is_dir:
            return abi.S_IFDIR | (st.mode or 0o755)
        if st.target is not None:
            return abi.S_IFLNK | 0o777
        return abi.S_IFREG | (st.mode or 0o644)

    def _attr(self, nid: int, st: FileStatus) -> bytes:
        return abi.pack_attr(nid, st.len, self._mode_of(st), st.nlink,
                             st.mtime, st.atime, self.uid, self.gid)

    def _entry(self, path: str, st: FileStatus) -> bytes:
        nid = self.intern(path)
        return abi.pack_entry_out(nid, self._attr(nid, st), self.entry_ttl,
                                  self.attr_ttl)

    def _new_fh(self, handle: _Handle) -> int:
        fh = self._next_fh
        self._next_fh += 1
        self.handles[fh] = handle
        return fh

    def _fh(self, fh: int) -> _Handle:
        h = self.handles.get(fh)
        if h is None:
            raise FuseError(Errno.ESTALE)
        return h

    # ---------------- dispatch ----------------

    async def handle(self, hdr: abi.InHeader, payload: memoryview) -> bytes | None:
        """Parity note: per-op counters/latency mirror the reference's
        curvine-fuse-metrics-design.md."""
        fn = _DISPATCH.get(hdr.opcode)
        if fn is None:
            raise FuseError(Errno.ENOSYS)
        name = fn.__name__[3:]
        self.metrics.inc(f"ops.{name}")
        # each kernel op is a (head-sampled) trace root on the client's
        # tracer: a slow/errored FUSE read shows its full path down to
        # the serving worker in one trace
        tracer = getattr(self.client, "tracer", None)
        span = tracer.span(f"fuse.{name}") if tracer is not None \
            else nullcontext()
        try:
            with span:
                with self.metrics.timer(f"lat.{name}"):
                    result = await fn(self, hdr, payload)
            if hdr.opcode == abi.Op.READ and result is not None:
                self.metrics.inc("bytes.read", len(result))
            elif hdr.opcode == abi.Op.WRITE:
                self.metrics.inc("bytes.written",
                                 max(0, hdr.length - 40 - abi.WRITE_IN.size))
            return result
        except FuseError:
            self.metrics.inc(f"errors.{name}")
            raise
        except cerr.CurvineError as e:
            self.metrics.inc(f"errors.{name}")
            raise FuseError(_fuse_errno(e)) from e
        except Exception:
            log.exception("fuse op %d failed", hdr.opcode)
            self.metrics.inc(f"errors.{name}")
            raise FuseError(Errno.EIO)

    # ---------------- ops ----------------

    async def op_init(self, hdr, payload) -> bytes:
        major, minor, max_readahead, flags = abi.INIT_IN.unpack_from(payload, 0)
        log.info("fuse init: kernel %d.%d flags=%#x", major, minor, flags)
        # ATOMIC_O_TRUNC: kernel passes O_TRUNC through to OPEN instead of
        # a SETATTR(size=0)+OPEN pair, so truncating opens are one op
        # POSIX_LOCKS/FLOCK_LOCKS: fcntl/flock dispatch to our plock
        # table (kernel stops emulating locally). AUTO_INVAL_DATA pairs
        # with FOPEN_KEEP_CACHE on opens: clean pages survive across
        # opens (warm re-reads never reach us) and drop automatically
        # when size/mtime changes — the measured 4x read win.
        # WRITEBACK_CACHE is deliberately NOT negotiated: it flushes
        # whole dirty pages, so an fsync-mid-page-then-write log pattern
        # or a large append re-sends page-aligned prefixes the
        # sequential stream writer cannot absorb — and it bought no
        # measurable write throughput here (the writer stream, not
        # per-op overhead, is the write ceiling).
        want = (abi.InitFlags.ASYNC_READ | abi.InitFlags.ATOMIC_O_TRUNC |
                abi.InitFlags.BIG_WRITES |
                abi.InitFlags.POSIX_LOCKS | abi.InitFlags.FLOCK_LOCKS |
                abi.InitFlags.AUTO_INVAL_DATA |
                abi.InitFlags.DO_READDIRPLUS | abi.InitFlags.READDIRPLUS_AUTO |
                abi.InitFlags.PARALLEL_DIROPS | abi.InitFlags.MAX_PAGES)
        out_flags = flags & want
        max_pages = max(1, self.max_write // 4096)
        return abi.INIT_OUT.pack(abi.KERNEL_VERSION,
                                 min(minor, abi.KERNEL_MINOR),
                                 max_readahead, out_flags, 16, 12,
                                 self.max_write, 1, max_pages, 0,
                                 *([0] * 8))

    async def op_destroy(self, hdr, payload) -> bytes:
        self.destroyed = True
        return b""

    async def op_lookup(self, hdr, payload) -> bytes:
        path = self._child(hdr.nodeid, bytes(payload).rstrip(b"\x00"))
        st = await self.client.meta.file_status(path)
        return self._entry(path, st)

    async def op_forget(self, hdr, payload) -> None:
        return None                      # keep dcache entries; no reply

    async def op_batch_forget(self, hdr, payload) -> None:
        return None

    async def op_getattr(self, hdr, payload) -> bytes:
        path = self.node_path(hdr.nodeid)
        st = await self.client.meta.file_status(path)
        w = self._open_writers.get(path)
        if w is not None:
            if getattr(w, "exact_size", False):
                st.len = w.pos              # staged handle: buffer IS size
            else:
                st.len = max(st.len, w.pos)  # in-flight write: live size
        av, avn = divmod(self.attr_ttl, 1000)
        return abi.ATTR_OUT.pack(av, avn * 1_000_000, 0) + \
            self._attr(hdr.nodeid, st)

    async def op_setattr(self, hdr, payload) -> bytes:
        (valid, _pad, fh, size, _lock, atime, mtime, _ctime, atimen, mtimen,
         _ctimen, mode, _u4, uid, gid, _u5) = abi.SETATTR_IN.unpack_from(
             payload, 0)
        path = self.node_path(hdr.nodeid)
        opts = SetAttrOpts()
        if valid & abi.SetattrValid.MODE:
            opts.mode = mode & 0o7777
        if valid & abi.SetattrValid.ATIME:
            opts.atime = atime * 1000 + atimen // 1_000_000
        if valid & abi.SetattrValid.MTIME:
            opts.mtime = mtime * 1000 + mtimen // 1_000_000
        now = int(time.time() * 1000)
        if valid & abi.SetattrValid.ATIME_NOW:
            opts.atime = now
        if valid & abi.SetattrValid.MTIME_NOW:
            opts.mtime = now
        if any(v is not None for v in
               (opts.mode, opts.atime, opts.mtime)):
            await self.client.meta.set_attr(path, opts)
        if valid & abi.SetattrValid.SIZE:
            w = self._open_writers.get(path)
            if getattr(w, "exact_size", False):
                # ftruncate on an open in-place handle: buffer-only; the
                # object rewrites at release
                w.truncate(size)
            else:
                st = await self.client.meta.file_status(path)
                if size == 0 and st.len != 0:
                    await self.client.write_all(path, b"")
                elif size < st.len:
                    await self.client.meta.resize_file(path, size)
                elif size > st.len:
                    # truncate(2) EXTEND: zero-pad and rewrite (bounded
                    # like the in-place open path)
                    if size > self.inplace_max:
                        raise FuseError(Errno.EOPNOTSUPP)
                    data = await self.client.read_all(path) if st.len \
                        else b""
                    await self.client.write_all(
                        path, data + b"\x00" * (size - len(data)))
        st = await self.client.meta.file_status(path)
        w = self._open_writers.get(path)
        if getattr(w, "exact_size", False):
            st.len = w.pos                  # staged: buffer IS the size
        av, avn = divmod(self.attr_ttl, 1000)
        return abi.ATTR_OUT.pack(av, avn * 1_000_000, 0) + \
            self._attr(hdr.nodeid, st)

    async def op_mkdir(self, hdr, payload) -> bytes:
        mode, _umask = abi.MKDIR_IN.unpack_from(payload, 0)
        name = bytes(payload[abi.MKDIR_IN.size:]).rstrip(b"\x00")
        path = self._child(hdr.nodeid, name)
        st = await self.client.meta.mkdir(path, create_parent=False,
                                          mode=mode & 0o7777)
        return self._entry(path, st)

    async def op_unlink(self, hdr, payload) -> bytes:
        path = self._child(hdr.nodeid, bytes(payload).rstrip(b"\x00"))
        await self.client.meta.delete(path, recursive=False)
        self._drop_path(path)
        return b""

    op_rmdir = op_unlink

    async def _rename(self, hdr, newdir: int, rest: bytes) -> bytes:
        old_name, new_name = rest.rstrip(b"\x00").split(b"\x00", 1)
        src = self._child(hdr.nodeid, old_name)
        dst = self._child(newdir, new_name)
        await self.client.meta.rename(src, dst)
        self._drop_path(src)
        self._drop_path(dst)
        return b""

    async def op_rename(self, hdr, payload) -> bytes:
        (newdir,) = abi.RENAME_IN.unpack_from(payload, 0)
        return await self._rename(hdr, newdir,
                                  bytes(payload[abi.RENAME_IN.size:]))

    async def op_rename2(self, hdr, payload) -> bytes:
        newdir, _flags, _pad = abi.RENAME2_IN.unpack_from(payload, 0)
        return await self._rename(hdr, newdir,
                                  bytes(payload[abi.RENAME2_IN.size:]))

    async def op_symlink(self, hdr, payload) -> bytes:
        name, target = bytes(payload).rstrip(b"\x00").split(b"\x00", 1)
        path = self._child(hdr.nodeid, name)
        st = await self.client.meta.symlink(target.decode(), path)
        return self._entry(path, st)

    async def op_readlink(self, hdr, payload) -> bytes:
        st = await self.client.meta.file_status(self.node_path(hdr.nodeid))
        if st.target is None:
            raise FuseError(Errno.EINVAL)
        return st.target.encode()

    async def op_link(self, hdr, payload) -> bytes:
        (oldnode,) = abi.LINK_IN.unpack_from(payload, 0)
        name = bytes(payload[abi.LINK_IN.size:]).rstrip(b"\x00")
        src = self.node_path(oldnode)
        dst = self._child(hdr.nodeid, name)
        st = await self.client.meta.link(src, dst)
        return self._entry(dst, st)

    async def _await_local_release(self, path: str) -> None:
        """close(2) returns at FLUSH but the file completes at the async
        RELEASE — an immediate re-open for write would race it and see
        LEASE_CONFLICT. Wait (bounded) for our own writer to finish."""
        import asyncio
        for _ in range(500):
            if path not in self._open_writers:
                return
            await asyncio.sleep(0.01)

    async def op_open(self, hdr, payload) -> bytes:
        flags, _ = abi.OPEN_IN.unpack_from(payload, 0)
        path = self.node_path(hdr.nodeid)
        acc = flags & os.O_ACCMODE
        # ALL opens wait: a read-open racing the async RELEASE of our own
        # just-closed writer would see the incomplete file (close-to-open
        # consistency)
        await self._await_local_release(path)
        if acc == os.O_RDONLY:
            # unified: cached files use block readers, uncached mounted
            # files stream from the UFS. KEEP_CACHE: clean pages from a
            # previous open stay valid (AUTO_INVAL_DATA drops them when
            # size/mtime changes), so warm re-reads are pure page-cache
            reader = await self.client.unified_open(path)
            fh = self._new_fh(_Handle(reader=reader, path=path))
            return abi.OPEN_OUT.pack(fh, abi.FOPEN_KEEP_CACHE, 0)
        else:
            if flags & os.O_APPEND:
                writer = await self.client.append(path)
            elif flags & os.O_TRUNC:
                if acc == os.O_RDWR and self.inplace_max > 0:
                    # reads come through this fd too: stage (empty after
                    # trunc; dirty when the trunc itself must persist)
                    st = await self.client.meta.file_status(path)
                    return self._open_staged(path, b"", dirty=st.len != 0)
                writer = await self.client.create(path, overwrite=True)
            else:
                # kernels without ATOMIC_O_TRUNC truncate via SETATTR then
                # open without O_TRUNC — a zero-length target streams; a
                # non-empty target is an IN-PLACE open: stage the content
                # in RAM and rewrite the object at release (bounded by
                # fuse.inplace_max_mb; 0 disables staging entirely and
                # restores the honest EOPNOTSUPP)
                st = await self.client.meta.file_status(path)
                if st.len == 0 and (acc != os.O_RDWR
                                    or self.inplace_max == 0):
                    writer = await self.client.create(path, overwrite=True)
                elif st.len <= self.inplace_max and self.inplace_max > 0:
                    data = await self.client.read_all(path) if st.len else b""
                    return self._open_staged(path, data)
                else:
                    raise FuseError(Errno.EOPNOTSUPP)
            fh = self._new_fh(_Handle(writer=writer, path=path))
            self._open_writers[path] = writer
        return abi.OPEN_OUT.pack(fh, 0, 0)

    def _open_staged(self, path: str, data: bytes,
                     dirty: bool = False) -> bytes:
        staged = _StagedFile(self.client, path, data, self.inplace_max,
                             dirty=dirty)
        fh = self._new_fh(_Handle(staged=staged, path=path))
        self._open_writers[path] = staged
        return abi.OPEN_OUT.pack(fh, 0, 0)

    async def op_create(self, hdr, payload) -> bytes:
        flags, mode, _umask, _of = abi.CREATE_IN.unpack_from(payload, 0)
        name = bytes(payload[abi.CREATE_IN.size:]).rstrip(b"\x00")
        path = self._child(hdr.nodeid, name)
        await self._await_local_release(path)
        exists = await self.client.meta.exists(path)
        acc = flags & os.O_ACCMODE
        staged = None
        if exists and not flags & os.O_EXCL and not flags & os.O_TRUNC:
            # stale negative dentry turned open(O_CREAT) of an existing
            # file into CREATE: empty targets stream; non-empty targets
            # take the staged in-place path (op_open parity)
            st0 = await self.client.meta.file_status(path)
            if st0.len != 0:
                if st0.len > self.inplace_max:
                    raise FuseError(Errno.EOPNOTSUPP)
                staged = _StagedFile(self.client, path,
                                     await self.client.read_all(path),
                                     self.inplace_max)
        elif exists and flags & os.O_EXCL:
            raise FuseError(Errno.EEXIST)
        if staged is None:
            if acc == os.O_RDWR and self.inplace_max > 0:
                # reads ride this fd: persist an empty object now, stage
                # content in RAM (read-after-write within the handle)
                await self.client.write_all(path, b"")
                staged = _StagedFile(self.client, path, b"",
                                     self.inplace_max)
            else:
                writer = await self.client.create(path, overwrite=exists)
        await self.client.meta.set_attr(path, SetAttrOpts(mode=mode & 0o7777))
        st = await self.client.meta.file_status(path)
        if staged is not None:
            fh = self._new_fh(_Handle(staged=staged, path=path))
            self._open_writers[path] = staged
        else:
            fh = self._new_fh(_Handle(writer=writer, path=path))
            self._open_writers[path] = writer
        return self._entry(path, st) + abi.OPEN_OUT.pack(fh, 0, 0)

    async def op_read(self, hdr, payload):
        fh, offset, size, *_ = abi.READ_IN.unpack_from(payload, 0)
        h = self._fh(fh)
        if h.staged is not None:
            async with h.lock:
                return h.staged.pread(offset, size)
        if h.reader is None:
            if h.writer is not None:
                # writeback cache: the kernel may RMW-read the tail page
                # of a write-only fd (appends). Serve the COMMITTED
                # bytes through a lazy reader — the writer's own dirty
                # pages never reach us (they're in the page cache)
                async with h.lock:
                    if h.reader is None:
                        try:
                            h.reader = await self.client.unified_open(
                                h.path)
                        except cerr.CurvineError as e:
                            raise FuseError(_fuse_errno(e)) from e
            else:
                raise FuseError(Errno.EINVAL)
        # numpy buffer (preadv fast path); the session writes it with
        # writev so it never gets copied into a bytes object
        return await h.reader.pread_view(offset, size)

    async def op_write(self, hdr, payload) -> bytes:
        fh, offset, size, *_ = abi.WRITE_IN.unpack_from(payload, 0)
        data = payload[abi.WRITE_IN.size:abi.WRITE_IN.size + size]
        h = self._fh(fh)
        if h.staged is not None:
            # in-place handle: any offset, no ordering constraints
            async with h.lock:
                h.staged.pwrite(offset, data)
            return abi.WRITE_OUT.pack(size, 0)
        if h.writer is None:
            raise FuseError(Errno.EINVAL)
        # the kernel issues writes concurrently: serialize per handle and
        # park out-of-order chunks until the stream catches up
        async with h.lock:
            if offset > h.writer.pos:
                if len(h.pending) > 256:
                    raise FuseError(Errno.EIO)
                h.pending[offset] = bytes(data)
                return abi.WRITE_OUT.pack(size, 0)
            if offset < h.writer.pos:
                # cache-mode files are sequential-write (reference semantics)
                raise FuseError(Errno.EOPNOTSUPP)
            await h.writer.write(data)
            while h.writer.pos in h.pending:
                await h.writer.write(h.pending.pop(h.writer.pos))
        return abi.WRITE_OUT.pack(size, 0)

    async def op_flush(self, hdr, payload) -> bytes:
        """FLUSH fires on EVERY close(2) of any fd referring to the handle
        — including the dup2()+close() inside shell redirection, which
        arrives BEFORE the first write. So FLUSH must not end the write
        stream: it is a durability point (buffered chunks pushed, sealed
        blocks journaled), and the file is completed at RELEASE.
        Parity: curvine-fuse/src/fs/fuse_writer.rs WriteTask::Flush vs
        ::Complete ('write_after_flush_keeps_the_durable_cleanup_boundary')."""
        fh, _unused, _pad, lock_owner = abi.FLUSH_IN.unpack_from(payload, 0)
        # the kernel asks close(2)-time POSIX-lock cleanup through
        # FLUSH's lock_owner (not RELEASE): drop everything that owner
        # holds on this node
        if lock_owner:
            self.plocks.release_owner(hdr.nodeid, lock_owner)
        h = self.handles.get(fh)
        if h and h.writer is not None:
            async with h.lock:
                if h.pending:
                    # out-of-order gap at a close boundary: surface it on
                    # this close() but keep the stream — writes from a
                    # still-open dup may yet fill the gap before RELEASE
                    raise FuseError(Errno.EIO)
                await h.writer.hflush()
        # staged handles persist at FLUSH too: close(2) is the only
        # syscall that can surface a failed rewrite to the caller
        # (RELEASE errors vanish in the kernel). persist() no-ops when
        # clean, so dup-close storms rewrite at most once per dirty span
        if h and h.staged is not None:
            async with h.lock:
                await h.staged.persist()
        return b""

    async def op_fsync(self, hdr, payload) -> bytes:
        fh, *_ = abi.FSYNC_IN.unpack_from(payload, 0)
        h = self.handles.get(fh)
        if h and h.writer is not None:
            await h.writer.flush()
        if h and h.staged is not None:      # fsync(2) demands durability
            async with h.lock:
                await h.staged.persist()
        return b""

    # ---------------- POSIX locks (fcntl + flock) ----------------
    # Parity: curvine-fuse/src/fs/curvine_file_system.rs:1752 +
    # plock_wait_registry.rs. Negotiating POSIX_LOCKS/FLOCK_LOCKS in
    # INIT makes the kernel dispatch these instead of emulating locally.

    def _parse_lk(self, payload):
        fh, owner, start, end, typ, pid, lk_flags, _pad = \
            abi.LK_IN.unpack_from(payload, 0)
        if lk_flags & abi.FUSE_LK_FLOCK:
            # flock(2): whole-file, owner-scoped; LOCK_SH/LOCK_EX arrive
            # already mapped to F_RDLCK/F_WRLCK by the kernel
            from curvine_tpu.fuse.plock import OFFSET_MAX
            start, end = 0, OFFSET_MAX
        return fh, owner, start, end, typ, pid

    async def op_getlk(self, hdr, payload) -> bytes:
        from curvine_tpu.fuse.plock import F_UNLCK
        _fh, owner, start, end, typ, _pid = self._parse_lk(payload)
        blocker = self.plocks.conflicting(hdr.nodeid, start, end, typ,
                                          owner)
        if blocker is None:
            return abi.LK_OUT.pack(0, 0, F_UNLCK, 0)
        return abi.LK_OUT.pack(blocker.start, blocker.end, blocker.type,
                               blocker.pid)

    async def op_setlk(self, hdr, payload) -> bytes:
        from curvine_tpu.fuse.plock import F_UNLCK
        _fh, owner, start, end, typ, pid = self._parse_lk(payload)
        if typ != F_UNLCK and self.plocks.conflicting(
                hdr.nodeid, start, end, typ, owner) is not None:
            raise FuseError(Errno.EAGAIN)
        self.plocks.apply(hdr.nodeid, start, end, typ, owner,
                          pid or hdr.pid)
        return b""

    async def op_setlkw(self, hdr, payload) -> bytes:
        import asyncio as _aio

        from curvine_tpu.fuse.plock import DeadlockError, F_UNLCK
        _fh, owner, start, end, typ, pid = self._parse_lk(payload)
        if typ == F_UNLCK:
            self.plocks.apply(hdr.nodeid, start, end, typ, owner,
                              pid or hdr.pid)
            return b""
        self._interruptible[hdr.unique] = _aio.current_task()
        try:
            await self.plocks.wait_and_apply(hdr.nodeid, start, end, typ,
                                             owner, pid or hdr.pid)
        except DeadlockError as e:
            log.warning("flock deadlock on node %d: %s", hdr.nodeid, e)
            raise FuseError(Errno.EDEADLK) from None
        except _aio.CancelledError:
            # kernel INTERRUPT (signal) or dead-owner cleanup: the
            # original request must still be answered
            raise FuseError(Errno.EINTR) from None
        finally:
            self._interruptible.pop(hdr.unique, None)
        return b""

    async def op_release(self, hdr, payload) -> bytes:
        fh, _flags, _rflags, lock_owner = \
            abi.RELEASE_IN.unpack_from(payload, 0)
        # closing the fd drops every lock its owner held (POSIX)
        self.plocks.release_owner(hdr.nodeid, lock_owner)
        h = self.handles.pop(fh, None)
        if h is not None:
            if h.writer is not None:        # last close: complete the file
                async with h.lock:
                    if h.pending:
                        await h.writer.abort()
                    else:
                        await h.writer.close()
                    self._open_writers.pop(h.path, None)
            if h.staged is not None:        # rewrite the object if dirty
                async with h.lock:
                    try:
                        await h.staged.persist()
                    finally:
                        self._open_writers.pop(h.path, None)
            if h.reader is not None:
                await h.reader.close()
        return b""

    async def op_opendir(self, hdr, payload) -> bytes:
        path = self.node_path(hdr.nodeid)
        entries = await self.client.meta.list_status(path)
        fh = self._new_fh(_Handle(entries=entries, path=path))
        return abi.OPEN_OUT.pack(fh, 0, 0)

    async def op_releasedir(self, hdr, payload) -> bytes:
        fh, *_ = abi.RELEASE_IN.unpack_from(payload, 0)
        self.handles.pop(fh, None)
        return b""

    def _dtype(self, st: FileStatus) -> int:
        if st.is_dir:
            return abi.DT_DIR
        if st.target is not None:
            return abi.DT_LNK
        return abi.DT_REG

    async def op_readdir(self, hdr, payload) -> bytes:
        fh, offset, size, *_ = abi.READ_IN.unpack_from(payload, 0)
        h = self._fh(fh)
        out = bytearray()
        entries = h.entries or []
        for i in range(offset, len(entries)):
            st = entries[i]
            nid = self.intern(st.path)
            ent = abi.pack_dirent(nid, i + 1, st.name.encode(),
                                  self._dtype(st))
            if len(out) + len(ent) > size:
                break
            out += ent
        return bytes(out)

    async def op_readdirplus(self, hdr, payload) -> bytes:
        fh, offset, size, *_ = abi.READ_IN.unpack_from(payload, 0)
        h = self._fh(fh)
        out = bytearray()
        entries = h.entries or []
        for i in range(offset, len(entries)):
            st = entries[i]
            entry_out = self._entry(st.path, st)
            ent = abi.pack_direntplus(entry_out, self.ids[st.path], i + 1,
                                      st.name.encode(), self._dtype(st))
            if len(out) + len(ent) > size:
                break
            out += ent
        return bytes(out)

    async def op_statfs(self, hdr, payload) -> bytes:
        info = await self.client.meta.master_info()
        bsize = 4096
        blocks = max(1, info.capacity // bsize)
        bfree = info.available // bsize
        return abi.STATFS_OUT.pack(blocks, bfree, bfree, info.inode_num + 1024,
                                   1024, bsize, 255, bsize, 0,
                                   0, 0, 0, 0, 0, 0)

    async def op_access(self, hdr, payload) -> bytes:
        """Honest access(2): POSIX mode check of the caller's uid/gid
        (mapped to names via the host user db) against the file's
        owner/group/mode. Parity: acl_feature.rs via the FUSE surface;
        root (uid 0) bypasses, like the master's superuser."""
        (mask, _pad) = abi.ACCESS_IN.unpack_from(payload, 0)
        if hdr.uid == 0 or mask == 0:        # F_OK / superuser
            return b""
        # short-TTL result cache: access(2) fires on hot paths (shell
        # completion, ls -l) and each miss is a master round trip
        import time
        key = (hdr.nodeid, hdr.uid, hdr.gid, mask)
        hit = self._access_cache.get(key)
        now = time.monotonic()
        if hit is not None and hit[1] > now:
            if not hit[0]:
                raise FuseError(Errno.EACCES)
            return b""
        from curvine_tpu.master.acl import posix_bits
        st = await self.client.meta.file_status(self.node_path(hdr.nodeid))
        user, groups = _uid_names(hdr.uid, hdr.gid)
        bits = posix_bits(st.owner, st.group, st.mode, user, groups)
        ok = (bits & mask) == mask
        self._access_cache[key] = (ok, now + self.attr_ttl / 1000)
        if len(self._access_cache) > 4096:
            self._access_cache.clear()
        if not ok:
            raise FuseError(Errno.EACCES)
        return b""

    async def op_getxattr(self, hdr, payload) -> bytes:
        size, _ = abi.GETXATTR_IN.unpack_from(payload, 0)
        name = bytes(payload[abi.GETXATTR_IN.size:]).rstrip(b"\x00").decode()
        st = await self.client.meta.file_status(self.node_path(hdr.nodeid))
        val = st.x_attr.get(name)
        if val is None:
            raise FuseError(Errno.ENODATA)
        val = val if isinstance(val, bytes) else str(val).encode()
        if size == 0:
            return abi.GETXATTR_OUT.pack(len(val), 0)
        if len(val) > size:
            raise FuseError(Errno.EINVAL)
        return val

    async def op_setxattr(self, hdr, payload) -> bytes:
        size, _flags = abi.SETXATTR_IN.unpack_from(payload, 0)
        rest = bytes(payload[abi.SETXATTR_IN.size:])
        name, rest = rest.split(b"\x00", 1)
        value = rest[:size]
        await self.client.meta.set_attr(
            self.node_path(hdr.nodeid),
            SetAttrOpts(add_x_attr={name.decode(): value}))
        return b""

    async def op_listxattr(self, hdr, payload) -> bytes:
        size, _ = abi.GETXATTR_IN.unpack_from(payload, 0)
        st = await self.client.meta.file_status(self.node_path(hdr.nodeid))
        blob = b"".join(k.encode() + b"\x00" for k in st.x_attr)
        if size == 0:
            return abi.GETXATTR_OUT.pack(len(blob), 0)
        return blob

    async def op_removexattr(self, hdr, payload) -> bytes:
        name = bytes(payload).rstrip(b"\x00").decode()
        await self.client.meta.set_attr(
            self.node_path(hdr.nodeid), SetAttrOpts(remove_x_attr=[name]))
        return b""

    async def op_lseek(self, hdr, payload) -> bytes:
        fh, offset, whence, _ = abi.LSEEK_IN.unpack_from(payload, 0)
        h = self._fh(fh)
        length = h.reader.len if h.reader else 0
        SEEK_DATA, SEEK_HOLE = 3, 4
        if whence == SEEK_DATA:
            if offset >= length:
                raise FuseError(Errno.EINVAL)
            return abi.LSEEK_OUT.pack(offset)
        if whence == SEEK_HOLE:
            return abi.LSEEK_OUT.pack(length)
        raise FuseError(Errno.EINVAL)

    async def op_interrupt(self, hdr, payload) -> None:
        """Cancel a blocked request (a signalled SETLKW waiter). The
        cancelled handler replies EINTR to its own unique."""
        (unique,) = abi.INTERRUPT_IN.unpack_from(payload, 0)
        task = self._interruptible.get(unique)
        if task is not None:
            task.cancel()
        return None

    async def op_fallocate(self, hdr, payload) -> bytes:
        raise FuseError(Errno.EOPNOTSUPP)


_DISPATCH = {
    Op.INIT: CurvineFuseFs.op_init,
    Op.DESTROY: CurvineFuseFs.op_destroy,
    Op.LOOKUP: CurvineFuseFs.op_lookup,
    Op.FORGET: CurvineFuseFs.op_forget,
    Op.BATCH_FORGET: CurvineFuseFs.op_batch_forget,
    Op.GETATTR: CurvineFuseFs.op_getattr,
    Op.SETATTR: CurvineFuseFs.op_setattr,
    Op.MKDIR: CurvineFuseFs.op_mkdir,
    Op.UNLINK: CurvineFuseFs.op_unlink,
    Op.RMDIR: CurvineFuseFs.op_rmdir,
    Op.RENAME: CurvineFuseFs.op_rename,
    Op.RENAME2: CurvineFuseFs.op_rename2,
    Op.SYMLINK: CurvineFuseFs.op_symlink,
    Op.READLINK: CurvineFuseFs.op_readlink,
    Op.LINK: CurvineFuseFs.op_link,
    Op.OPEN: CurvineFuseFs.op_open,
    Op.CREATE: CurvineFuseFs.op_create,
    Op.READ: CurvineFuseFs.op_read,
    Op.WRITE: CurvineFuseFs.op_write,
    Op.FLUSH: CurvineFuseFs.op_flush,
    Op.FSYNC: CurvineFuseFs.op_fsync,
    Op.RELEASE: CurvineFuseFs.op_release,
    Op.OPENDIR: CurvineFuseFs.op_opendir,
    Op.RELEASEDIR: CurvineFuseFs.op_releasedir,
    Op.READDIR: CurvineFuseFs.op_readdir,
    Op.READDIRPLUS: CurvineFuseFs.op_readdirplus,
    Op.STATFS: CurvineFuseFs.op_statfs,
    Op.ACCESS: CurvineFuseFs.op_access,
    Op.GETXATTR: CurvineFuseFs.op_getxattr,
    Op.SETXATTR: CurvineFuseFs.op_setxattr,
    Op.LISTXATTR: CurvineFuseFs.op_listxattr,
    Op.REMOVEXATTR: CurvineFuseFs.op_removexattr,
    Op.LSEEK: CurvineFuseFs.op_lseek,
    Op.INTERRUPT: CurvineFuseFs.op_interrupt,
    Op.FALLOCATE: CurvineFuseFs.op_fallocate,
    Op.GETLK: CurvineFuseFs.op_getlk,
    Op.SETLK: CurvineFuseFs.op_setlk,
    Op.SETLKW: CurvineFuseFs.op_setlkw,
}
