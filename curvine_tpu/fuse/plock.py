"""POSIX byte-range file locks for the FUSE mount.

Parity: curvine-fuse/src/fs/plock_wait_registry.rs (blocking-wait
registry with deadlock detection) + curvine_file_system.rs:1752
(GETLK/SETLK/SETLKW handling). Like the reference's, the table is local
to the FUSE daemon: one mount's fcntl/flock users (SQLite, pip, data
loaders) get full POSIX semantics; cross-mount coherence is the master
path-lock API's job (GET_LOCK/SET_LOCK RPCs).

Semantics implemented:
- byte ranges with inclusive ends (FUSE wire convention; OFFSET_MAX =
  "to EOF"), read locks share, write locks exclude, same-owner
  overlapping set REPLACES the overlapped portion (POSIX split/merge)
- SETLK: conflicting -> EAGAIN; SETLKW: waits on an asyncio.Event the
  next unlock wakes, with wait-graph cycle detection -> EDEADLK
- flock(2) (FUSE_LK_FLOCK) rides the same table as whole-file ranges
  keyed by the kernel's lock owner
- release(lock_owner) drops everything that owner held on the node
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, replace

F_RDLCK, F_WRLCK, F_UNLCK = 0, 1, 2
OFFSET_MAX = 0x7FFF_FFFF_FFFF_FFFF


@dataclass(frozen=True)
class Plock:
    start: int
    end: int            # inclusive
    type: int           # F_RDLCK | F_WRLCK
    owner: int          # kernel lock_owner cookie
    pid: int


def _overlaps(a_start: int, a_end: int, b_start: int, b_end: int) -> bool:
    return a_start <= b_end and b_start <= a_end


class DeadlockError(Exception):
    pass


class PlockTable:
    def __init__(self) -> None:
        self._locks: dict[int, list[Plock]] = {}       # node -> locks
        self._waiters: dict[int, list[asyncio.Event]] = {}
        # owner -> owner it currently waits on (one edge per blocked
        # SETLKW; cycles in this graph are deadlocks)
        self._waiting_on: dict[int, int] = {}
        # owner -> blocked SETLKW tasks: a dead process's close
        # (release_owner) cancels them so the lock is never granted to
        # a corpse
        self._wait_tasks: dict[int, set[asyncio.Task]] = {}

    # ---------------- queries ----------------

    def conflicting(self, node: int, start: int, end: int, typ: int,
                    owner: int) -> Plock | None:
        """First lock that prevents `owner` taking [start, end] as
        `typ`. Read locks share; anything conflicts with a write lock."""
        for lk in self._locks.get(node, ()):
            if lk.owner == owner:
                continue
            if not _overlaps(lk.start, lk.end, start, end):
                continue
            if typ == F_WRLCK or lk.type == F_WRLCK:
                return lk
        return None

    def holders(self, node: int) -> list[Plock]:
        return list(self._locks.get(node, ()))

    # ---------------- mutation ----------------

    def apply(self, node: int, start: int, end: int, typ: int,
              owner: int, pid: int) -> None:
        """Install (or, for F_UNLCK, remove) the range for `owner`,
        splitting the owner's overlapped locks POSIX-style. Caller has
        already checked conflicts."""
        out: list[Plock] = []
        for lk in self._locks.get(node, ()):
            if lk.owner != owner or not _overlaps(lk.start, lk.end,
                                                  start, end):
                out.append(lk)
                continue
            if lk.start < start:
                out.append(replace(lk, end=start - 1))
            if lk.end > end:
                out.append(replace(lk, start=end + 1))
        if typ != F_UNLCK:
            out.append(Plock(start, end, typ, owner, pid))
        if out:
            self._locks[node] = out
        else:
            self._locks.pop(node, None)
        self._wake(node)

    def release_owner(self, node: int, owner: int) -> None:
        """Drop every lock `owner` holds on `node` (fd close), and
        cancel its blocked waits — the process is gone; granting later
        would orphan the lock forever."""
        for t in self._wait_tasks.pop(owner, ()):
            t.cancel()
        self._waiting_on.pop(owner, None)
        locks = self._locks.get(node)
        if not locks:
            return
        kept = [lk for lk in locks if lk.owner != owner]
        if kept:
            self._locks[node] = kept
        elif node in self._locks:
            del self._locks[node]
        if len(kept) != len(locks):
            self._wake(node)

    # ---------------- blocking waits ----------------

    async def wait_and_apply(self, node: int, start: int, end: int,
                             typ: int, owner: int, pid: int) -> None:
        """SETLKW: block until the range is grantable, then take it.
        Raises DeadlockError when the wait graph would cycle.
        Cancellation (kernel INTERRUPT, or release of a dead owner)
        cleans its wait-graph edge — no stale edges, no grant to a
        corpse."""
        task = asyncio.current_task()
        if task is not None:
            self._wait_tasks.setdefault(owner, set()).add(task)
        try:
            while True:
                blocker = self.conflicting(node, start, end, typ, owner)
                if blocker is None:
                    self.apply(node, start, end, typ, owner, pid)
                    return
                if self._would_deadlock(owner, blocker.owner):
                    raise DeadlockError(
                        f"owner {owner:#x} <-> {blocker.owner:#x}")
                self._waiting_on[owner] = blocker.owner
                ev = asyncio.Event()
                self._waiters.setdefault(node, []).append(ev)
                try:
                    await ev.wait()
                finally:
                    ws = self._waiters.get(node)
                    if ws and ev in ws:
                        ws.remove(ev)
        finally:
            self._waiting_on.pop(owner, None)
            if task is not None:
                ts = self._wait_tasks.get(owner)
                if ts is not None:
                    ts.discard(task)
                    if not ts:
                        self._wait_tasks.pop(owner, None)

    def _would_deadlock(self, waiter: int, blocked_by: int) -> bool:
        """Walking the wait graph from `blocked_by` reaches `waiter` →
        granting would wait forever. Parity:
        plock_wait_registry.rs would_deadlock."""
        seen = set()
        cur = blocked_by
        while cur in self._waiting_on:
            if cur in seen:
                return False          # someone else's cycle
            seen.add(cur)
            cur = self._waiting_on[cur]
            if cur == waiter:
                return True
        return False

    def _wake(self, node: int) -> None:
        for ev in self._waiters.get(node, ()):
            ev.set()
