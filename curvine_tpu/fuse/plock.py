"""POSIX byte-range file locks for the FUSE mount.

Parity: curvine-fuse/src/fs/plock_wait_registry.rs (blocking-wait
registry with deadlock detection) + curvine_file_system.rs:1752
(GETLK/SETLK/SETLKW handling). Like the reference's, the table is local
to the FUSE daemon: one mount's fcntl/flock users (SQLite, pip, data
loaders) get full POSIX semantics; cross-mount coherence is the master
path-lock API's job (GET_LOCK/SET_LOCK RPCs).

Semantics implemented:
- byte ranges with inclusive ends (FUSE wire convention; OFFSET_MAX =
  "to EOF"), read locks share, write locks exclude, same-owner
  overlapping set REPLACES the overlapped portion (POSIX split/merge)
- SETLK: conflicting -> EAGAIN; SETLKW: waits on an asyncio.Event the
  next unlock wakes, with wait-graph cycle detection -> EDEADLK
- flock(2) (FUSE_LK_FLOCK) rides the same table as whole-file ranges
  keyed by the kernel's lock owner
- release(lock_owner) drops everything that owner held on the node
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, replace

F_RDLCK, F_WRLCK, F_UNLCK = 0, 1, 2
OFFSET_MAX = 0x7FFF_FFFF_FFFF_FFFF


@dataclass(frozen=True)
class Plock:
    start: int
    end: int            # inclusive
    type: int           # F_RDLCK | F_WRLCK
    owner: int          # kernel lock_owner cookie
    pid: int


def _overlaps(a_start: int, a_end: int, b_start: int, b_end: int) -> bool:
    return a_start <= b_end and b_start <= a_end


class DeadlockError(Exception):
    pass


class PlockTable:
    def __init__(self) -> None:
        self._locks: dict[int, list[Plock]] = {}       # node -> locks
        # node -> [(event, waiting task)]
        self._waiters: dict[int, list[tuple[asyncio.Event,
                                            asyncio.Task | None]]] = {}
        # one edge per blocked SETLKW, keyed by the waiting task so two
        # concurrent waits by the same owner never clobber each other:
        # task -> (waiter owner, blocker owner). Cycles in the induced
        # owner graph are deadlocks.
        self._waiting_on: dict[asyncio.Task, tuple[int, int]] = {}
        # (node, owner) -> blocked SETLKW tasks: a dead process's close
        # (release_owner) cancels only the waits on the node being
        # released — flush of an unrelated fd must not EINTR a
        # multithreaded process's blocked fcntl elsewhere
        self._wait_tasks: dict[tuple[int, int], set[asyncio.Task]] = {}

    # ---------------- queries ----------------

    def conflicting(self, node: int, start: int, end: int, typ: int,
                    owner: int) -> Plock | None:
        """First lock that prevents `owner` taking [start, end] as
        `typ`. Read locks share; anything conflicts with a write lock."""
        for lk in self._locks.get(node, ()):
            if lk.owner == owner:
                continue
            if not _overlaps(lk.start, lk.end, start, end):
                continue
            if typ == F_WRLCK or lk.type == F_WRLCK:
                return lk
        return None

    def holders(self, node: int) -> list[Plock]:
        return list(self._locks.get(node, ()))

    # ---------------- mutation ----------------

    def apply(self, node: int, start: int, end: int, typ: int,
              owner: int, pid: int) -> None:
        """Install (or, for F_UNLCK, remove) the range for `owner`,
        splitting the owner's overlapped locks POSIX-style. Caller has
        already checked conflicts."""
        out: list[Plock] = []
        for lk in self._locks.get(node, ()):
            if lk.owner != owner or not _overlaps(lk.start, lk.end,
                                                  start, end):
                out.append(lk)
                continue
            if lk.start < start:
                out.append(replace(lk, end=start - 1))
            if lk.end > end:
                out.append(replace(lk, start=end + 1))
        if typ != F_UNLCK:
            out.append(Plock(start, end, typ, owner, pid))
        if out:
            self._locks[node] = out
        else:
            self._locks.pop(node, None)
        self._wake(node)

    def release_owner(self, node: int, owner: int) -> None:
        """Drop every lock `owner` holds on `node` (fd close), and
        cancel its blocked waits on this node — the process is gone;
        granting later would orphan the lock forever. Waits the owner
        has on OTHER nodes are untouched (op_flush fires this on every
        close(2); a multithreaded process closing one file must not
        EINTR its blocked fcntl on another)."""
        for t in self._wait_tasks.pop((node, owner), ()):
            # drop the wait-graph edge NOW, not when the cancelled
            # task's finally runs on a later tick — an intervening
            # deadlock check must not walk an edge from an owner that
            # is no longer waiting (spurious EDEADLK)
            self._waiting_on.pop(t, None)
            t.cancel()
        locks = self._locks.get(node)
        if not locks:
            return
        kept = [lk for lk in locks if lk.owner != owner]
        if kept:
            self._locks[node] = kept
        elif node in self._locks:
            del self._locks[node]
        if len(kept) != len(locks):
            self._wake(node)

    # ---------------- blocking waits ----------------

    async def wait_and_apply(self, node: int, start: int, end: int,
                             typ: int, owner: int, pid: int) -> None:
        """SETLKW: block until the range is grantable, then take it.
        Raises DeadlockError when the wait graph would cycle.
        Cancellation (kernel INTERRUPT, or release of a dead owner)
        cleans its wait-graph edge — no stale edges, no grant to a
        corpse."""
        task = asyncio.current_task()
        key = (node, owner)
        if task is not None:
            self._wait_tasks.setdefault(key, set()).add(task)
        try:
            while True:
                blocker = self.conflicting(node, start, end, typ, owner)
                if blocker is None:
                    self.apply(node, start, end, typ, owner, pid)
                    return
                if self._would_deadlock(owner, blocker.owner):
                    raise DeadlockError(
                        f"owner {owner:#x} <-> {blocker.owner:#x}")
                if task is not None:
                    self._waiting_on[task] = (owner, blocker.owner)
                ev = asyncio.Event()
                entry = (ev, task)
                self._waiters.setdefault(node, []).append(entry)
                try:
                    await ev.wait()
                finally:
                    ws = self._waiters.get(node)
                    if ws and entry in ws:
                        ws.remove(entry)
        finally:
            if task is not None:
                self._waiting_on.pop(task, None)
                ts = self._wait_tasks.get(key)
                if ts is not None:
                    ts.discard(task)
                    if not ts:
                        self._wait_tasks.pop(key, None)

    def _would_deadlock(self, waiter: int, blocked_by: int) -> bool:
        """DFS over the owner wait graph from `blocked_by`; reaching
        `waiter` means granting would wait forever. An owner may have
        several outgoing edges (one per blocked SETLKW task). Parity:
        plock_wait_registry.rs would_deadlock."""
        adj: dict[int, set[int]] = {}
        for w, b in self._waiting_on.values():
            adj.setdefault(w, set()).add(b)
        seen: set[int] = set()
        stack = [blocked_by]
        while stack:
            cur = stack.pop()
            if cur == waiter:
                return True
            if cur in seen:
                continue
            seen.add(cur)
            stack.extend(adj.get(cur, ()))
        return False

    def _wake(self, node: int) -> None:
        for ev, task in self._waiters.get(node, ()):
            ev.set()
            # a woken waiter is no longer blocked: clear its edge NOW
            # (it re-records against the current blocker if it loses the
            # re-check) so a deadlock walk between the wake and the
            # task's resumption can't see a stale edge
            if task is not None:
                self._waiting_on.pop(task, None)
