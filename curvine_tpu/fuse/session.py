"""FUSE session: /dev/fuse channel loop + request dispatch.

Parity: curvine-fuse/src/session/ (channel readers feeding async handlers,
replies written back to the device). A dedicated thread blocks on
os.read(/dev/fuse) — one whole request per read — and hands requests to
the asyncio loop; handlers run concurrently; replies are single atomic
os.write calls."""

from __future__ import annotations

import asyncio
import logging
import os
import threading

from curvine_tpu.fuse import abi
from curvine_tpu.fuse.ops import CurvineFuseFs, FuseError

log = logging.getLogger(__name__)


class FuseSession:
    def __init__(self, fs: CurvineFuseFs, fd: int,
                 max_write: int = 1024 * 1024):
        self.fs = fs
        self.fd = fd
        self.bufsize = max_write + 64 * 1024
        self._stop = threading.Event()
        self._reader: threading.Thread | None = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self.ready = asyncio.Event()
        # request-buffer pool: os.read allocates a fresh bufsize (1 MB+)
        # bytes object per op — pure allocator churn for 40-byte GETATTRs.
        # readv into pooled bytearrays instead; a buffer is returned when
        # its request's dispatch completes.
        self._pool: list[bytearray] = []

    def _borrow(self) -> bytearray:
        return self._pool.pop() if self._pool else bytearray(self.bufsize)

    def _give_back(self, buf: bytearray) -> None:
        if len(self._pool) < 16:
            self._pool.append(buf)

    async def run(self) -> None:
        """Serve until unmount (ENODEV on the channel) or stop().

        The channel is read NON-BLOCKING on the event loop itself
        (loop.add_reader): /dev/fuse is pollable and hands out one whole
        request per read, so there is no reason to burn a thread and a
        cross-thread queue handoff per op — on the single-core TPU-VM
        profile that handoff used to dominate per-op latency."""
        self._loop = loop = asyncio.get_running_loop()
        done = asyncio.Event()
        pending: set[asyncio.Task] = set()
        os.set_blocking(self.fd, False)

        def on_readable():
            # drain everything ready: one wakeup can cover many ops
            while True:
                buf = self._borrow()
                try:
                    n = os.readv(self.fd, [buf])
                except BlockingIOError:
                    self._give_back(buf)
                    return
                except OSError as e:
                    self._give_back(buf)
                    if e.errno == 19:           # ENODEV: unmounted
                        log.info("fuse channel closed (unmount)")
                    elif not self._stop.is_set():
                        log.warning("fuse read error: %s", e)
                    try:
                        loop.remove_reader(self.fd)
                    except (OSError, ValueError):
                        pass
                    done.set()
                    return
                if n <= 0 or self.fs.destroyed:
                    self._give_back(buf)
                    done.set()
                    return
                t = asyncio.ensure_future(self._dispatch(buf, n))
                pending.add(t)
                t.add_done_callback(pending.discard)

        try:
            loop.add_reader(self.fd, on_readable)
        except NotImplementedError:
            # exotic loop without fd watching: fall back to a thread
            return await self._run_threaded()
        self.ready.set()
        try:
            await done.wait()
        finally:
            try:
                loop.remove_reader(self.fd)
            except (OSError, ValueError):
                pass
            for t in pending:
                t.cancel()

    async def _run_threaded(self) -> None:
        """Thread-based channel reader (fallback)."""
        queue: asyncio.Queue[bytes | None] = asyncio.Queue(maxsize=64)
        os.set_blocking(self.fd, True)

        def read_loop():
            while not self._stop.is_set():
                try:
                    buf = os.read(self.fd, self.bufsize)
                except OSError as e:
                    if e.errno == 19:          # ENODEV: unmounted
                        log.info("fuse channel closed (unmount)")
                    elif not self._stop.is_set():
                        log.warning("fuse read error: %s", e)
                    break
                if not buf:
                    break
                fut = asyncio.run_coroutine_threadsafe(queue.put(buf),
                                                       self._loop)
                try:
                    fut.result(timeout=30)
                except Exception:
                    break
            asyncio.run_coroutine_threadsafe(queue.put(None), self._loop)

        self._reader = threading.Thread(target=read_loop, daemon=True,
                                        name="fuse-chan")
        self._reader.start()
        self.ready.set()
        pending: set[asyncio.Task] = set()
        try:
            while True:
                buf = await queue.get()
                if buf is None or self.fs.destroyed:
                    break
                t = asyncio.ensure_future(self._dispatch(buf))
                pending.add(t)
                t.add_done_callback(pending.discard)
        finally:
            for t in pending:
                t.cancel()

    async def _dispatch(self, buf: bytes | bytearray,
                        n: int | None = None) -> None:
        pooled = n is not None
        view = memoryview(buf)[:n] if pooled else memoryview(buf)
        try:
            hdr = abi.InHeader.parse(view)
            payload = view[abi.IN_HEADER.size:hdr.length]
            bufs: list | None = None
            try:
                result = await self.fs.handle(hdr, payload)
                if result is None:    # FORGET-class: no reply at all
                    return
                if isinstance(result, (bytes, bytearray)):
                    bufs = [abi.pack_reply_header(hdr.unique, len(result)),
                            result]
                else:                 # buffer view (numpy): avoid the copy
                    rview = memoryview(result)
                    bufs = [abi.pack_reply_header(hdr.unique, rview.nbytes),
                            rview]
            except FuseError as e:
                bufs = [abi.pack_reply(hdr.unique, error=e.errno)]
            except asyncio.CancelledError:
                return
            try:
                os.writev(self.fd, bufs)
            except OSError as e:
                if e.errno not in (2, 19):    # ENOENT: interrupted request
                    log.warning("fuse reply write failed: %s", e)
        finally:
            # pooled bytearrays are REUSED: every handler either copies
            # what it keeps (audited: pending writes, staged pwrite,
            # name parses) or finishes consuming before returning
            if pooled:
                self._give_back(buf)  # type: ignore[arg-type]

    def stop(self) -> None:
        self._stop.set()
        try:
            os.close(self.fd)
        except OSError:
            pass
