"""Azure Blob protocol gateway: serve the cache namespace over the Blob
service REST API.

The Azure-wire sibling of gateway/s3.py — any Azure Blob client (and the
in-tree azblob:// adapter, which this gateway round-trip tests) can
read/write cached data. Containers map to top-level dirs, blobs to
files. Implemented surface: Put Blob (BlockBlob), Get Blob (ranged),
Get Blob Properties, Delete Blob, List Blobs (prefix + delimiter),
Create Container.

Auth: SharedKey verification against the configured account/key
(the exact canonicalization the adapter signs with — forged or unsigned
requests get 403); account=None is the anonymous opt-in.
"""

from __future__ import annotations

import logging
import posixpath
import urllib.parse
import xml.sax.saxutils as sax

from aiohttp import web

from curvine_tpu.common import errors as cerr
from curvine_tpu.ufs.azblob import sharedkey_auth

log = logging.getLogger(__name__)


class AzBlobGateway:
    def __init__(self, client, port: int = 0, host: str = "127.0.0.1",
                 account: str | None = None, key: str = ""):
        self.client = client
        self.host = host
        self.port = port
        self.account = account
        self.key = key
        middlewares = [self._auth_middleware] if account else []
        self.app = web.Application(client_max_size=1024 ** 3,
                                   middlewares=middlewares)
        self.app.router.add_route("*", "/{container}", self._container)
        self.app.router.add_route("*", "/{container}/{key:.*}", self._blob)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        log.info("azblob gateway on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    @web.middleware
    async def _auth_middleware(self, req: web.Request, handler):
        import hmac as _hmac
        from curvine_tpu.gateway.authutil import date_fresh, md5_binds_body
        auth = req.headers.get("Authorization", "")
        expect_prefix = f"SharedKey {self.account}:"
        ok = False
        if auth.startswith(expect_prefix):
            headers = {k.lower(): v for k, v in req.headers.items()}
            headers["content-length"] = str(req.content_length or 0)
            # replay window on the signed x-ms-date + payload binding
            # via the signed Content-MD5 (shared rules: authutil)
            fresh = date_fresh(headers.get("x-ms-date", ""))
            body_ok = not req.body_exists or md5_binds_body(
                await req.read(), headers.get("content-md5", ""))
            url = f"http://host{req.rel_url.raw_path}"
            if req.rel_url.raw_query_string:
                url += "?" + req.rel_url.raw_query_string
            want = sharedkey_auth(req.method, url, self.account, self.key,
                                  headers)
            ok = fresh and body_ok and _hmac.compare_digest(want, auth)
        if not ok:
            log.info("azblob auth rejected %s %s", req.method,
                     req.rel_url.raw_path)
            return web.Response(
                status=403, content_type="application/xml",
                text=('<?xml version="1.0"?><Error>'
                      "<Code>AuthenticationFailed</Code></Error>"))
        return await handler(req)

    # ---------------- container ops ----------------

    async def _container(self, req: web.Request) -> web.Response:
        name = req.match_info["container"]
        if req.method == "PUT" and req.query.get("restype") == "container":
            await self.client.meta.mkdir(f"/{name}")
            return web.Response(status=201)
        if req.method == "GET" and req.query.get("comp") == "list":
            return await self._list(req, name)
        return web.Response(status=400)

    async def _list(self, req: web.Request, container: str) -> web.Response:
        prefix = req.query.get("prefix", "")
        delimiter = req.query.get("delimiter", "")
        base = f"/{container}"
        if not await self.client.meta.exists(base):
            return web.Response(status=404)
        blobs: list[tuple[str, int]] = []
        prefixes: set[str] = set()

        async def walk(path: str) -> None:
            for st in await self.client.meta.list_status(path):
                key = st.path[len(base) + 1:]
                if not key.startswith(prefix) and not prefix.startswith(key):
                    continue
                if st.is_dir:
                    if delimiter == "/" and key.startswith(prefix) \
                            and "/" not in key[len(prefix):]:
                        prefixes.add(key + "/")
                        continue
                    await walk(st.path)
                elif key.startswith(prefix):
                    blobs.append((key, st.len))

        await walk(base)
        blobs.sort()
        items = "".join(
            f"<Blob><Name>{sax.escape(k)}</Name><Properties>"
            f"<Content-Length>{n}</Content-Length></Properties></Blob>"
            for k, n in blobs)
        commons = "".join(
            f"<BlobPrefix><Name>{sax.escape(p)}</Name></BlobPrefix>"
            for p in sorted(prefixes))
        return web.Response(content_type="application/xml", text=(
            f'<?xml version="1.0"?><EnumerationResults>'
            f"<Prefix>{sax.escape(prefix)}</Prefix>"
            f"<Blobs>{items}{commons}</Blobs></EnumerationResults>"))

    # ---------------- blob ops ----------------

    async def _blob(self, req: web.Request) -> web.StreamResponse:
        container = req.match_info["container"]
        key = urllib.parse.unquote(req.match_info["key"])
        path = f"/{container}/{key}"
        if not posixpath.normpath(path).startswith(f"/{container}/"):
            return web.Response(status=400)
        try:
            if req.method == "PUT":
                if req.headers.get("x-ms-blob-type", "BlockBlob") \
                        != "BlockBlob":
                    return web.Response(status=400)
                data = await req.read()
                await self.client.write_all(path, data)
                return web.Response(status=201)
            if req.method == "HEAD":
                st = await self.client.meta.file_status(path)
                if st.is_dir:
                    # blob semantics: a "directory" is only a name
                    # prefix (adapters' stat() relies on 404 → list)
                    return web.Response(status=404)
                return web.Response(status=200, headers={
                    "Content-Length": str(st.len),
                    "x-ms-blob-type": "BlockBlob"})
            if req.method == "GET":
                return await self._get(req, path)
            if req.method == "DELETE":
                try:
                    await self.client.meta.delete(path, recursive=False)
                except cerr.FileNotFound:
                    return web.Response(status=404)
                return web.Response(status=202)
        except cerr.FileNotFound:
            return web.Response(status=404)
        except cerr.CurvineError as e:
            return web.Response(status=500, text=str(e))
        return web.Response(status=405)

    async def _get(self, req: web.Request, path: str) -> web.StreamResponse:
        reader = await self.client.unified_open(path)
        length = reader.len
        status, offset, n = 200, 0, length
        rng = req.headers.get("Range") or req.headers.get("x-ms-range")
        if rng and rng.startswith("bytes="):
            lo, _, hi = rng[6:].partition("-")
            offset = int(lo or 0)
            end = int(hi) if hi else length - 1
            n = min(end, length - 1) - offset + 1
            status = 206
        resp = web.StreamResponse(status=status, headers={
            "Content-Length": str(max(0, n)),
            "x-ms-blob-type": "BlockBlob"})
        await resp.prepare(req)
        sent = 0
        while sent < n:
            chunk = await reader.pread(offset + sent,
                                       min(4 * 1024 * 1024, n - sent))
            if not chunk:
                break
            await resp.write(chunk)
            sent += len(chunk)
        await resp.write_eof()
        await reader.close()
        return resp
