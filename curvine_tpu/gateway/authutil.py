"""Shared request-auth checks for the protocol gateways.

One implementation of the replay-window and payload-binding rules so
the S3 (OSS-dialect) and Azure middlewares cannot drift apart."""

from __future__ import annotations

import base64
import datetime
import hashlib

MAX_SKEW_S = 15 * 60
HTTP_DATE = "%a, %d %b %Y %H:%M:%S GMT"


def date_fresh(value: str, fmt: str = HTTP_DATE,
               max_skew_s: int = MAX_SKEW_S) -> bool:
    """True when the signed date header is within the replay window."""
    try:
        sent = datetime.datetime.strptime(value, fmt).replace(
            tzinfo=datetime.timezone.utc)
    except (ValueError, TypeError):
        return False
    now = datetime.datetime.now(datetime.timezone.utc)
    return abs((now - sent).total_seconds()) <= max_skew_s


def md5_binds_body(body: bytes, content_md5: str) -> bool:
    """True when the signed Content-MD5 matches the received bytes; an
    empty body needs no binding, a non-empty one without (or with a
    wrong) Content-MD5 is refused — nothing else ties the signature to
    the payload in the date-based auth schemes."""
    if not body:
        return True
    return base64.b64encode(
        hashlib.md5(body).digest()).decode() == content_md5
