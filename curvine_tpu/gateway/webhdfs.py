"""WebHDFS gateway: HDFS REST compatibility over the cache namespace.

Parity: the reference's "HDFS protocol compatibility" surface. Speaks the
WebHDFS v1 API (``/webhdfs/v1/<path>?op=...``) so HDFS tooling
(`hdfs dfs -fs webhdfs://...`, Spark, distcp) can use the cache without
code changes. Single-node flavor: data is served directly (no DN
redirect hop)."""

from __future__ import annotations

import logging

from aiohttp import web

from curvine_tpu.common import errors as cerr

log = logging.getLogger(__name__)


def _fs_json(st) -> dict:
    return {
        "accessTime": st.atime, "modificationTime": st.mtime,
        "blockSize": st.block_size, "length": st.len,
        "owner": st.owner, "group": st.group,
        "permission": f"{st.mode & 0o777:o}",
        "replication": st.replicas,
        "type": "DIRECTORY" if st.is_dir else "FILE",
        "pathSuffix": st.name,
        "childrenNum": st.children_num,
    }


class WebHdfsGateway:
    def __init__(self, client, port: int = 0, host: str = "127.0.0.1"):
        self.client = client
        self.host = host
        self.port = port
        self.app = web.Application(client_max_size=1024 ** 3)
        self.app.router.add_route("*", "/webhdfs/v1{path:.*}", self._handle)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        log.info("webhdfs gateway on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _handle(self, req: web.Request) -> web.StreamResponse:
        path = req.match_info["path"] or "/"
        op = req.query.get("op", "").upper()
        try:
            return await self._dispatch(req, path, op)
        except cerr.FileNotFound:
            return self._remote_exc(404, "FileNotFoundException",
                                    f"{path} not found")
        except cerr.FileAlreadyExists:
            return self._remote_exc(403, "FileAlreadyExistsException", path)
        except cerr.CurvineError as e:
            return self._remote_exc(500, "IOException", str(e))

    async def _dispatch(self, req, path, op) -> web.StreamResponse:
        c = self.client
        if op == "GETFILESTATUS":
            st = await c.meta.file_status(path)
            return web.json_response({"FileStatus": _fs_json(st)})
        if op == "LISTSTATUS":
            sts = await c.meta.list_status(path)
            return web.json_response(
                {"FileStatuses": {"FileStatus": [_fs_json(s) for s in sts]}})
        if op == "GETCONTENTSUMMARY":
            cs = await c.content_summary(path)
            return web.json_response({"ContentSummary": {
                "length": cs["length"], "fileCount": cs["file_count"],
                "directoryCount": cs["directory_count"],
                "quota": -1, "spaceConsumed": cs["length"],
                "spaceQuota": -1}})
        if op == "OPEN":
            reader = await c.unified_open(path)
            offset = int(req.query.get("offset", "0"))
            length = int(req.query.get("length", str(reader.len - offset)))
            resp = web.StreamResponse(headers={
                "Content-Type": "application/octet-stream",
                "Content-Length": str(max(0, length))})
            await resp.prepare(req)
            sent = 0
            while sent < length:
                chunk = await reader.pread(offset + sent,
                                           min(4 * 1024 * 1024,
                                               length - sent))
                if not chunk:
                    break
                await resp.write(chunk)
                sent += len(chunk)
            await resp.write_eof()
            await reader.close()
            return resp
        if op == "MKDIRS":
            await c.meta.mkdir(path, create_parent=True)
            return web.json_response({"boolean": True})
        if op == "CREATE":
            data = await req.read()
            if not data and req.query.get("data") != "true":
                # protocol-correct two-step: real hdfs clients PUT without
                # a body first and expect a 307 redirect to the datanode
                # — redirect back to ourselves with data=true
                import urllib.parse
                qs = req.query_string
                qs += ("&" if qs else "") + "data=true"
                loc = (f"http://{req.host}/webhdfs/v1"
                       f"{urllib.parse.quote(path)}?{qs}")
                if req.query.get("noredirect") == "true":
                    return web.json_response({"Location": loc})
                return web.Response(status=307, headers={"Location": loc})
            await c.write_all(path, data,
                              **({"replicas": int(req.query["replication"])}
                                 if "replication" in req.query else {}))
            return web.Response(status=201)
        if op == "APPEND":
            data = await req.read()
            w = await c.append(path)
            await w.write(data)
            await w.close()
            return web.Response(status=200)
        if op == "RENAME":
            dst = req.query.get("destination", "")
            ok = await c.meta.rename(path, dst)
            return web.json_response({"boolean": ok})
        if op == "DELETE":
            recursive = req.query.get("recursive", "false") == "true"
            await c.meta.delete(path, recursive=recursive)
            return web.json_response({"boolean": True})
        if op == "SETPERMISSION":
            from curvine_tpu.common.types import SetAttrOpts
            await c.meta.set_attr(path, SetAttrOpts(
                mode=int(req.query.get("permission", "755"), 8)))
            return web.Response(status=200)
        return self._remote_exc(400, "UnsupportedOperationException",
                                f"op {op!r}")

    def _remote_exc(self, status: int, cls: str, msg: str) -> web.Response:
        return web.json_response(
            {"RemoteException": {"exception": cls, "message": msg}},
            status=status)
