"""AWS SigV4 *verification* for the S3 gateway.

The gateway's signing counterpart lives in ``curvine_tpu.ufs.s3``
(client side); this module re-derives the signature server-side from the
request the client actually sent and compares, so forged or unsigned
requests are rejected with S3-style 403s. Static credentials come from
cluster conf (``[gateway] s3_access_key/s3_secret_key``); anonymous mode
is an explicit opt-in, never a fallback.

Parity note: the reference ships no in-tree S3 gateway at all (its S3
story is s3-as-UFS + the S3a proxy class), so this exceeds in-tree
parity; the verification rules follow the public SigV4 spec.
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import re
import urllib.parse

_UNSIGNED = "UNSIGNED-PAYLOAD"
_AUTH_RE = re.compile(
    r"AWS4-HMAC-SHA256\s+"
    r"Credential=(?P<access>[^/]+)/(?P<date>\d{8})/(?P<region>[^/]+)"
    r"/(?P<service>[^/]+)/aws4_request,\s*"
    r"SignedHeaders=(?P<signed>[^,]+),\s*"
    r"Signature=(?P<sig>[0-9a-f]{64})")

# x-amz-date within this window of server time is accepted (AWS uses 15m)
MAX_SKEW_S = 15 * 60


class SigV4Error(Exception):
    """Verification failure; ``code`` is the S3 error code to return."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _derive_key(secret: str, datestamp: str, region: str,
                service: str) -> bytes:
    k = hmac.new(("AWS4" + secret).encode(), datestamp.encode(),
                 hashlib.sha256).digest()
    for part in (region, service, "aws4_request"):
        k = hmac.new(k, part.encode(), hashlib.sha256).digest()
    return k


def canonical_query(raw_query: str) -> str:
    q = urllib.parse.parse_qsl(raw_query, keep_blank_values=True)
    return "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q))


def verify_sigv4(method: str, raw_path: str, raw_query: str,
                 headers, body_sha256: str | None,
                 credentials: dict[str, str],
                 now: datetime.datetime | None = None) -> str:
    """Verify one request's Authorization header. Returns the access key
    on success; raises SigV4Error otherwise.

    ``headers`` is any case-insensitive mapping (aiohttp's CIMultiDict or
    a plain dict with lowercase keys). ``body_sha256`` is the hex digest
    of the received body, or None when the caller could not hash it (then
    only UNSIGNED-PAYLOAD / the client-declared hash is checked against
    the signature, not the bytes)."""
    auth = headers.get("Authorization") or headers.get("authorization") or ""
    m = _AUTH_RE.match(auth.strip())
    if not m:
        raise SigV4Error("AccessDenied",
                         "missing or malformed Authorization header")
    access = m["access"]
    secret = credentials.get(access)
    if secret is None:
        raise SigV4Error("InvalidAccessKeyId", f"unknown access key {access}")

    amz_date = headers.get("x-amz-date") or headers.get("X-Amz-Date") or ""
    if not re.fullmatch(r"\d{8}T\d{6}Z", amz_date):
        raise SigV4Error("AccessDenied", "missing x-amz-date")
    if not amz_date.startswith(m["date"]):
        raise SigV4Error("AccessDenied",
                         "credential scope date != x-amz-date")
    now = now or datetime.datetime.now(datetime.timezone.utc)
    req_t = datetime.datetime.strptime(amz_date, "%Y%m%dT%H%M%SZ").replace(
        tzinfo=datetime.timezone.utc)
    if abs((now - req_t).total_seconds()) > MAX_SKEW_S:
        raise SigV4Error("RequestTimeTooSkewed", "x-amz-date outside window")

    declared = (headers.get("x-amz-content-sha256")
                or headers.get("X-Amz-Content-Sha256") or "")
    if declared != _UNSIGNED and body_sha256 is not None \
            and declared != body_sha256:
        raise SigV4Error("XAmzContentSHA256Mismatch",
                         "payload hash != declared x-amz-content-sha256")
    payload_hash = declared or (body_sha256 or _UNSIGNED)

    signed_names = [h.strip().lower() for h in m["signed"].split(";") if h]
    if "host" not in signed_names:
        raise SigV4Error("AccessDenied", "host header must be signed")
    parts = []
    for name in signed_names:
        val = headers.get(name)
        if val is None:
            # CIMultiDict is case-insensitive already; plain dicts need
            # the title-cased fallback
            val = headers.get(name.title(), "")
        parts.append(f"{name}:{str(val).strip()}\n")

    # S3 SigV4 rule: canonical URI = the path exactly as sent on the
    # wire (each segment encoded once, no re-encode/normalize) — matches
    # ufs/s3.py sigv4_headers and real AWS SDK clients.
    canonical_uri = raw_path or "/"
    creq = "\n".join([method.upper(), canonical_uri,
                      canonical_query(raw_query), "".join(parts),
                      ";".join(signed_names), payload_hash])
    scope = f"{m['date']}/{m['region']}/{m['service']}/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])
    key = _derive_key(secret, m["date"], m["region"], m["service"])
    expect = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
    if not hmac.compare_digest(expect, m["sig"]):
        raise SigV4Error("SignatureDoesNotMatch",
                         "signature mismatch")
    return access
