"""S3 protocol gateway: serve the cache namespace over the S3 REST API.

Parity: the reference's "S3 protocol compatibility" surface — any S3
client (boto3, s5cmd, our own ufs.s3 adapter) can read/write cached data
without code changes. Path-style addressing: ``/<bucket>/<key>`` maps to
``/<bucket>/<key>`` in the namespace.

Implemented: GET/PUT/HEAD/DELETE object, ListObjectsV2 (delimiter +
prefix), ListBuckets, CreateBucket (mkdir), ranged GETs, multipart
uploads (initiate/UploadPart/complete/abort with validated uploadIds and
stale-upload GC). Authentication: SigV4 verification against static
credentials (``credentials={access: secret}``) — unsigned/forged
requests get S3-style 403s; ``credentials=None`` is the explicit
anonymous mode for cluster-internal deployments.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import math
import re
import time
import urllib.parse
import uuid
import xml.sax.saxutils as sax

from aiohttp import web

from curvine_tpu.common import errors as cerr
from curvine_tpu.common.metrics import MetricsRegistry
from curvine_tpu.common.qos import tenant_scope
from curvine_tpu.gateway.sigv4 import SigV4Error, verify_sigv4

log = logging.getLogger(__name__)

_NS = 'xmlns="http://s3.amazonaws.com/doc/2006-03-01/"'

# cheap access-key extraction for tenant identity: full SigV4/OSS
# verification still happens in the auth middleware — admission only
# needs WHO is asking, and must not burn HMAC cycles on a request that
# is about to be shed (overload control 101)
_CRED_RE = re.compile(r"Credential=([^/,\s]+)/")


class S3Gateway:
    def __init__(self, client, port: int = 0, host: str = "127.0.0.1",
                 credentials: dict[str, str] | None = None,
                 qos=None, metrics=None,
                 gc_interval_s: float = 3600.0):
        self.client = client
        self.host = host
        self.port = port
        self.credentials = credentials or None
        # multi-tenant admission (common/qos.py AdmissionController):
        # the QoS middleware runs FIRST — shed before auth crypto, shed
        # before the handler — and installs regardless of auth mode
        self.qos = qos
        self.metrics = metrics or MetricsRegistry("gateway")
        self.gc_interval_s = gc_interval_s
        middlewares = []
        if self.qos is not None:
            middlewares.append(self._qos_middleware)
        if self.credentials:
            middlewares.append(self._auth_middleware)
        self.app = web.Application(client_max_size=1024 ** 3,
                                   middlewares=middlewares)
        self.app.router.add_route("GET", "/", self._list_buckets)
        self.app.router.add_route("*", "/{bucket}", self._bucket)
        self.app.router.add_route("*", "/{bucket}/{key:.*}", self._object)
        self._runner: web.AppRunner | None = None
        self._gc_task: asyncio.Task | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]
        if self.gc_interval_s > 0:
            # an idle gateway must still reclaim abandoned multipart
            # uploads — the inline sweep only fires on initiate traffic
            self._gc_task = asyncio.ensure_future(self._gc_loop())
        log.info("s3 gateway on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._gc_task is not None:
            self._gc_task.cancel()
            try:
                await self._gc_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
            self._gc_task = None
        if self._runner:
            await self._runner.cleanup()

    async def _gc_loop(self) -> None:
        while True:
            await asyncio.sleep(self.gc_interval_s)
            try:
                await self._gc_stale_uploads()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — GC must never kill serving
                log.exception("s3 gateway stale-upload gc")

    # ---------------- tenant admission ----------------

    @staticmethod
    def tenant_of(req: web.Request) -> str:
        """Tenant id = the access key the request claims (SigV4
        Credential scope or OSS header); forged claims fail auth right
        after admission, so a throttled tenant cannot evade its quota
        by lying — it can only get itself 403s instead of 503s."""
        auth = req.headers.get("Authorization", "")
        if auth.startswith("OSS "):
            return auth[4:].partition(":")[0].strip() or "anonymous"
        m = _CRED_RE.search(auth)
        if m:
            return m.group(1)
        return "anonymous"

    @web.middleware
    async def _qos_middleware(self, req: web.Request, handler):
        """Admission check before auth and before the handler: HTTP
        503 + Retry-After with the S3 ``SlowDown`` code on rejection
        (what AWS itself returns under prefix overload). The tenant
        scope wraps the handler so downstream RPCs to master/worker
        carry the tenant id on the header rail."""
        tenant = self.tenant_of(req)
        op_class = "read" if req.method in ("GET", "HEAD") else "write"
        try:
            token = self.qos.admit(tenant, op_class)
        except cerr.Throttled as e:
            retry_s = max(1, math.ceil((e.retry_after_ms or 1000) / 1000))
            self.metrics.inc("gateway.throttled")
            return self._error(
                503, "SlowDown", req.rel_url.raw_path,
                headers={"Retry-After": str(retry_s)})
        except cerr.CurvineError as e:
            # DOA and other admission failures: plain 503, retryable
            return self._error(503, "SlowDown", str(e))
        t0 = time.perf_counter()
        try:
            with tenant_scope(tenant):
                return await handler(req)
        finally:
            self.qos.release(token, time.perf_counter() - t0)

    @web.middleware
    async def _auth_middleware(self, req: web.Request, handler):
        """SigV4-verify every request before it reaches a handler.

        The body is read (and cached by aiohttp, so handlers' later
        ``req.read()`` is free) to check the declared
        x-amz-content-sha256 against the actual bytes; UNSIGNED-PAYLOAD
        skips the hash but the signature itself is still required."""
        auth = req.headers.get("Authorization", "")
        if auth.startswith("OSS "):
            # OSS-dialect clients (ufs/oss.py native signing): verify
            # the HMAC-SHA1 header scheme against the same credentials
            if not await self._verify_oss(req, auth):
                log.info("s3 gateway rejected OSS auth %s %s", req.method,
                         req.rel_url.raw_path)
                return self._error(403, "SignatureDoesNotMatch",
                                   req.rel_url.raw_path)
            return await handler(req)
        declared = req.headers.get("x-amz-content-sha256", "")
        body_hash = None
        if req.body_exists and declared != "UNSIGNED-PAYLOAD":
            body_hash = hashlib.sha256(await req.read()).hexdigest()
        elif not req.body_exists:
            body_hash = hashlib.sha256(b"").hexdigest()
        try:
            verify_sigv4(req.method, req.rel_url.raw_path,
                         req.rel_url.raw_query_string, req.headers,
                         body_hash, self.credentials)
        except SigV4Error as e:
            log.info("s3 auth rejected %s %s: %s", req.method,
                     req.rel_url.raw_path, e)
            return self._error(403, e.code, req.rel_url.raw_path)
        return await handler(req)

    async def _verify_oss(self, req: web.Request, auth: str) -> bool:
        import hmac as _hmac
        from curvine_tpu.gateway.authutil import date_fresh, md5_binds_body
        from curvine_tpu.ufs.oss import oss_sign, oss_string_to_sign
        try:
            access, _, sig = auth[4:].partition(":")
            secret = self.credentials.get(access.strip())
            if secret is None:
                return False
            headers = {k.lower(): v for k, v in req.headers.items()}
            # replay window (real OSS enforces 15 min too)
            if not date_fresh(headers.get("date", "")):
                return False
            # payload binding via the signed Content-MD5
            if req.body_exists and not md5_binds_body(
                    await req.read(), headers.get("content-md5", "")):
                return False
            sts = oss_string_to_sign(
                req.method, urllib.parse.unquote(req.rel_url.raw_path),
                req.rel_url.raw_query_string, headers)
            return _hmac.compare_digest(oss_sign(secret, sts), sig.strip())
        except Exception:  # noqa: BLE001 — any parse failure = reject
            return False

    # ---------------- bucket ops ----------------

    async def _list_buckets(self, req: web.Request) -> web.Response:
        """ListBuckets: top-level dirs (dot-prefixed scratch dirs like
        /.s3mpu are internal and hidden)."""
        sts = await self.client.meta.list_status("/")
        def iso(ms: int) -> str:
            import datetime
            return datetime.datetime.fromtimestamp(
                ms / 1000, datetime.timezone.utc).strftime(
                "%Y-%m-%dT%H:%M:%S.000Z")
        items = "".join(
            f"<Bucket><Name>{sax.escape(st.name)}</Name>"
            f"<CreationDate>{iso(st.mtime)}</CreationDate></Bucket>"
            for st in sts if st.is_dir and not st.name.startswith("."))
        return web.Response(content_type="application/xml", text=(
            f'<?xml version="1.0"?><ListAllMyBucketsResult {_NS}>'
            f"<Owner><ID>curvine</ID></Owner>"
            f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"))

    async def _bucket(self, req: web.Request) -> web.StreamResponse:
        bucket = req.match_info["bucket"]
        if req.method == "PUT":                     # CreateBucket
            await self.client.meta.mkdir(f"/{bucket}")
            return web.Response(status=200)
        if req.method in ("GET", "HEAD"):
            if "list-type" in req.query or req.method == "GET":
                return await self._list_objects(req, bucket)
            exists = await self.client.meta.exists(f"/{bucket}")
            return web.Response(status=200 if exists else 404)
        if req.method == "DELETE":
            try:
                await self.client.meta.delete(f"/{bucket}", recursive=False)
            except cerr.FileNotFound:
                return self._error(404, "NoSuchBucket", bucket)
            except cerr.DirNotEmpty:
                return self._error(409, "BucketNotEmpty", bucket)
            return web.Response(status=204)
        return web.Response(status=405)

    async def _list_objects(self, req: web.Request,
                            bucket: str) -> web.Response:
        prefix = req.query.get("prefix", "")
        delimiter = req.query.get("delimiter", "")
        max_keys = int(req.query.get("max-keys", "1000"))
        base = f"/{bucket}"
        if not await self.client.meta.exists(base):
            return self._error(404, "NoSuchBucket", bucket)

        contents: list[tuple[str, int, int]] = []
        prefixes: set[str] = set()

        async def walk(path: str) -> None:
            for st in await self.client.meta.list_status(path):
                key = st.path[len(base) + 1:]
                if not key.startswith(prefix) and not prefix.startswith(key):
                    continue
                if st.is_dir:
                    if delimiter == "/" and key.startswith(prefix):
                        rest = key[len(prefix):]
                        if "/" not in rest:
                            prefixes.add(key + "/")
                            continue
                    await walk(st.path)
                elif key.startswith(prefix):
                    contents.append((key, st.len, st.mtime))

        await walk(base)
        contents.sort()
        items = "".join(
            f"<Contents><Key>{sax.escape(k)}</Key><Size>{n}</Size>"
            f"<LastModified>1970-01-01T00:00:00.000Z</LastModified>"
            f"<ETag>&quot;{m:x}&quot;</ETag>"
            f"<StorageClass>STANDARD</StorageClass></Contents>"
            for k, n, m in contents[:max_keys])
        commons = "".join(
            f"<CommonPrefixes><Prefix>{sax.escape(p)}</Prefix>"
            f"</CommonPrefixes>" for p in sorted(prefixes))
        body = (f'<?xml version="1.0"?><ListBucketResult {_NS}>'
                f"<Name>{bucket}</Name><Prefix>{sax.escape(prefix)}</Prefix>"
                f"<KeyCount>{len(contents[:max_keys])}</KeyCount>"
                f"<MaxKeys>{max_keys}</MaxKeys><IsTruncated>"
                f"{'true' if len(contents) > max_keys else 'false'}"
                f"</IsTruncated>{items}{commons}</ListBucketResult>")
        return web.Response(text=body, content_type="application/xml")

    # ---------------- object ops ----------------

    async def _object(self, req: web.Request) -> web.StreamResponse:
        bucket = req.match_info["bucket"]
        key = urllib.parse.unquote(req.match_info["key"])
        path = f"/{bucket}/{key}"
        # a key like '..%2Fother/file' must not cross the bucket boundary:
        # reject any key whose normalized path escapes /<bucket>/
        import posixpath
        normed = posixpath.normpath(path)
        if not normed.startswith(f"/{bucket}/"):
            return self._error(400, "InvalidObjectName", path)
        try:
            # ---- multipart upload (real S3 clients use it for anything
            # big: boto3 defaults to multipart above 8 MiB) ----
            if req.method == "POST" and "uploads" in req.query:
                upload_id = uuid.uuid4().hex[:20]
                await self.client.meta.mkdir(
                    f"/.s3mpu/{upload_id}", create_parent=True)
                await self._gc_stale_uploads()
                return web.Response(content_type="application/xml", text=(
                    f'<?xml version="1.0"?>'
                    f"<InitiateMultipartUploadResult {_NS}>"
                    f"<Bucket>{bucket}</Bucket>"
                    f"<Key>{sax.escape(key)}</Key>"
                    f"<UploadId>{upload_id}</UploadId>"
                    f"</InitiateMultipartUploadResult>"))
            if req.method == "PUT" and "uploadId" in req.query:
                upload_id = self._upload_id(req)
                if upload_id is None:
                    return self._error(400, "NoSuchUpload", key)
                try:
                    part = int(req.query.get("partNumber", "1"))
                except ValueError:
                    part = 0
                if not 1 <= part <= 10_000:
                    return self._error(400, "InvalidPartNumber", key)
                data = await req.read()
                await self.client.write_all(
                    f"/.s3mpu/{upload_id}/part-{part:05d}", data)
                return web.Response(status=200,
                                    headers={"ETag": f'"part-{part}"'})
            if req.method == "POST" and "uploadId" in req.query:
                upload_id = self._upload_id(req)
                if upload_id is None:
                    return self._error(400, "NoSuchUpload", key)
                manifest = (await req.read()).decode(errors="replace")
                uploaded = {st.name: st.path
                            for st in await self.client.meta.list_status(
                                f"/.s3mpu/{upload_id}")}
                wanted = [int(m) for m in
                          re.findall(r"<PartNumber>(\d+)</PartNumber>",
                                     manifest)]
                if wanted:
                    # honor the client's manifest: only the LISTED parts,
                    # in the listed order; a missing one is InvalidPart
                    parts = []
                    for n in wanted:
                        name = f"part-{n:05d}"
                        if name not in uploaded:
                            return self._error(400, "InvalidPart", key)
                        parts.append(uploaded[name])
                else:
                    parts = [uploaded[k] for k in sorted(uploaded)]
                if not parts:
                    return self._error(400, "InvalidPart", key)
                w = await self.client.create(path, overwrite=True)
                for p_path in parts:
                    reader = await self.client.open(p_path)
                    off = 0
                    while off < reader.len:
                        chunk = await reader.pread(off, 4 * 1024 * 1024)
                        if not chunk:
                            break
                        await w.write(chunk)
                        off += len(chunk)
                    await reader.close()
                await w.close()
                await self.client.meta.delete(f"/.s3mpu/{upload_id}",
                                              recursive=True)
                return web.Response(content_type="application/xml", text=(
                    f'<?xml version="1.0"?>'
                    f"<CompleteMultipartUploadResult {_NS}>"
                    f"<Bucket>{bucket}</Bucket><Key>{sax.escape(key)}</Key>"
                    f'<ETag>"ok"</ETag>'
                    f"</CompleteMultipartUploadResult>"))
            if req.method == "DELETE" and "uploadId" in req.query:
                upload_id = self._upload_id(req)
                if upload_id is not None:
                    try:
                        await self.client.meta.delete(
                            f"/.s3mpu/{upload_id}", recursive=True)
                    except cerr.FileNotFound:
                        pass
                return web.Response(status=204)
            if req.method == "PUT":
                data = await req.read()
                await self.client.write_all(path, data)
                return web.Response(status=200, headers={"ETag": '"ok"'})
            if req.method == "HEAD":
                st = await self.client.meta.file_status(path)
                if st.is_dir:
                    # S3 semantics: a directory is only a key PREFIX —
                    # clients detect it via the trailing-delimiter list
                    # probe, never via HEAD (adapters' stat() relies on
                    # the 404 → list fallback)
                    return self._error(404, "NoSuchKey", key)
                return web.Response(status=200, headers={
                    "Content-Length": str(st.len),
                    "ETag": '"ok"', "Accept-Ranges": "bytes"})
            if req.method == "GET":
                return await self._get_object(req, path)
            if req.method == "DELETE":
                try:
                    await self.client.meta.delete(path, recursive=False)
                except cerr.FileNotFound:
                    pass
                return web.Response(status=204)
        except cerr.FileNotFound:
            return self._error(404, "NoSuchKey", key)
        except cerr.CurvineError as e:
            return self._error(500, "InternalError", str(e))
        return web.Response(status=405)

    async def _get_object(self, req: web.Request,
                          path: str) -> web.StreamResponse:
        reader = await self.client.unified_open(path)
        length = reader.len
        status = 200
        offset, n = 0, length
        rng = req.headers.get("Range")
        if rng and rng.startswith("bytes="):
            lo, _, hi = rng[6:].partition("-")
            offset = int(lo or 0)
            end = int(hi) if hi else length - 1
            n = min(end, length - 1) - offset + 1
            status = 206
        resp = web.StreamResponse(status=status, headers={
            "Content-Length": str(max(0, n)),
            "Accept-Ranges": "bytes",
            "Content-Type": "application/octet-stream"})
        if status == 206:
            resp.headers["Content-Range"] = \
                f"bytes {offset}-{offset + n - 1}/{length}"
        await resp.prepare(req)
        sent = 0
        while sent < n:
            chunk = await reader.pread(offset + sent,
                                       min(4 * 1024 * 1024, n - sent))
            if not chunk:
                break
            await resp.write(chunk)
            sent += len(chunk)
        await resp.write_eof()
        await reader.close()
        return resp

    @staticmethod
    def _upload_id(req) -> str | None:
        """uploadIds are self-issued 20-hex tokens; anything else (e.g.
        '../somebucket') is a traversal attempt, never a path component."""
        uid = req.query.get("uploadId", "")
        return uid if re.fullmatch(r"[0-9a-f]{20}", uid) else None

    async def _gc_stale_uploads(self, max_age_ms: int = 24 * 3600 * 1000):
        """Abandoned multipart scratch dirs (no complete/abort) age out —
        real S3 needs lifecycle rules; the gateway sweeps on each
        initiate AND from the background interval task (idle gateways
        still reclaim)."""
        from curvine_tpu.common.types import now_ms
        self.metrics.inc("gateway.stale_uploads_gc")
        try:
            cutoff = now_ms() - max_age_ms
            for st in await self.client.meta.list_status("/.s3mpu"):
                if st.is_dir and st.mtime < cutoff:
                    try:
                        await self.client.meta.delete(st.path, recursive=True)
                        self.metrics.inc("gateway.stale_uploads_reclaimed")
                    except cerr.CurvineError:
                        pass
        except cerr.CurvineError:
            pass

    def _error(self, status: int, code: str, resource: str,
               headers: dict | None = None) -> web.Response:
        body = (f'<?xml version="1.0"?><Error><Code>{code}</Code>'
                f"<Resource>{sax.escape(resource)}</Resource></Error>")
        return web.Response(status=status, text=body,
                            content_type="application/xml",
                            headers=headers)
