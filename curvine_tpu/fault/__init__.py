from curvine_tpu.fault.disk import DiskFaultInjector, DiskFaultSpec
from curvine_tpu.fault.runtime import FaultInjector, FaultSpec

__all__ = ["DiskFaultInjector", "DiskFaultSpec", "FaultInjector",
           "FaultSpec"]
