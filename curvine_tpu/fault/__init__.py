from curvine_tpu.fault.runtime import FaultInjector, FaultSpec

__all__ = ["FaultInjector", "FaultSpec"]
