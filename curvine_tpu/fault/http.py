"""HTTP control plane for fault injection.

Parity: curvine-fault/src/http_control.rs + http_server.rs.
  GET    /faults           list armed faults
  POST   /faults           arm a fault (JSON FaultSpec fields)
  DELETE /faults/{id}      disarm
  DELETE /faults           disarm all
  GET    /faults/log       injection event log"""

from __future__ import annotations

import dataclasses
import json

from aiohttp import web

from curvine_tpu.fault.runtime import FaultInjector, FaultSpec


class FaultControlServer:
    def __init__(self, injector: FaultInjector, port: int = 0,
                 host: str = "127.0.0.1"):
        self.injector = injector
        self.host = host
        self.port = port
        self.app = web.Application()
        self.app.router.add_get("/faults", self._list)
        self.app.router.add_post("/faults", self._add)
        self.app.router.add_delete("/faults/{fault_id}", self._remove)
        self.app.router.add_delete("/faults", self._clear)
        self.app.router.add_get("/faults/log", self._log)
        self._runner: web.AppRunner | None = None

    async def start(self) -> None:
        self._runner = web.AppRunner(self.app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        for s in self._runner.sites:
            self.port = s._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    def _json(self, obj, status=200):
        return web.Response(text=json.dumps(obj), status=status,
                            content_type="application/json")

    async def _list(self, req):
        return self._json([dataclasses.asdict(f)
                           for f in self.injector.faults.values()])

    async def _add(self, req):
        body = await req.json()
        allowed = {f.name for f in dataclasses.fields(FaultSpec)} \
            - {"fault_id", "hits"}
        spec = FaultSpec(**{k: v for k, v in body.items() if k in allowed})
        fid = self.injector.add(spec)
        return self._json({"fault_id": fid}, status=201)

    async def _remove(self, req):
        self.injector.remove(int(req.match_info["fault_id"]))
        return self._json({})

    async def _clear(self, req):
        self.injector.clear()
        return self._json({})

    async def _log(self, req):
        return self._json(self.injector.log[-1000:])
