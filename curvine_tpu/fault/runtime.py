"""Fault injection runtime.

Parity: curvine-fault/src/ (catalog.rs fault kinds, runtime.rs injection,
controller.rs lifecycle). Faults are installed onto RpcServer.fault_hook
and act on matching requests: added latency, dropped requests (client
sees a timeout), or injected errors. Used by resilience tests and the
`/faults` HTTP control plane (curvine_tpu.fault.http)."""

from __future__ import annotations

import asyncio
import fnmatch
import itertools
import logging
import random
import time
from dataclasses import dataclass, field

from curvine_tpu.common.errors import CurvineError, ErrorCode

log = logging.getLogger(__name__)

KINDS = ("delay", "drop", "error")


@dataclass
class FaultSpec:
    kind: str                       # delay | drop | error
    target: str = "*"               # server name glob: master|worker|*
    codes: list[int] = field(default_factory=list)   # RpcCodes; [] = all
    probability: float = 1.0
    delay_ms: int = 0               # for kind=delay
    error_code: int = int(ErrorCode.IO)
    error_msg: str = "injected fault"
    max_hits: int = 0               # 0 = unlimited
    fault_id: int = 0
    hits: int = 0

    def matches(self, server_name: str, code: int) -> bool:
        if self.max_hits and self.hits >= self.max_hits:
            return False
        if not fnmatch.fnmatch(server_name, self.target):
            return False
        return not self.codes or code in self.codes


class FaultInjector:
    """Install on one or more RpcServers; manage active faults."""

    def __init__(self) -> None:
        self.faults: dict[int, FaultSpec] = {}
        self._ids = itertools.count(1)
        self.log: list[dict] = []

    def install(self, *servers) -> "FaultInjector":
        for s in servers:
            s.fault_hook = self.hook
        return self

    def uninstall(self, *servers) -> None:
        for s in servers:
            s.fault_hook = None

    def install_client(self, *pools) -> "FaultInjector":
        """Client-side twin of install(): hook a ConnectionPool so every
        OUTGOING request runs the same fault catalogue before it leaves
        the client. The `target` glob matches the destination address
        (e.g. "127.0.0.1:9996" or "*:9996"), so one worker can be faulted
        from the client side without server cooperation — a dropped send
        looks exactly like a request lost on the wire (the caller times
        out). Mirrors RpcServer.fault_hook."""
        for p in pools:
            p.set_fault_hook(self.hook)
        return self

    def uninstall_client(self, *pools) -> None:
        for p in pools:
            p.set_fault_hook(None)

    def add(self, spec: FaultSpec) -> int:
        if spec.kind not in KINDS:
            raise ValueError(f"unknown fault kind {spec.kind!r}")
        spec.fault_id = next(self._ids)
        self.faults[spec.fault_id] = spec
        log.info("fault %d armed: %s", spec.fault_id, spec)
        return spec.fault_id

    def remove(self, fault_id: int) -> None:
        self.faults.pop(fault_id, None)

    def clear(self) -> None:
        self.faults.clear()

    async def hook(self, server_name: str, msg) -> bool:
        """Returns False to drop the request."""
        for spec in list(self.faults.values()):
            if not spec.matches(server_name, msg.code):
                continue
            if random.random() > spec.probability:
                continue
            spec.hits += 1
            self.log.append({"ts": time.time(), "fault": spec.fault_id,
                             "kind": spec.kind, "server": server_name,
                             "code": msg.code})
            if spec.kind == "delay":
                await asyncio.sleep(spec.delay_ms / 1000)
            elif spec.kind == "drop":
                return False
            elif spec.kind == "error":
                raise CurvineError.from_wire(spec.error_code, spec.error_msg)
        return True
