"""Disk-level fault injection for the worker's storage plane.

The RPC-plane FaultInjector (fault/runtime.py) exercises the process
and network fault domains; this module exercises the MEDIA fault domain
— the one that actually degrades first on real hosts with local NVMe.
A DiskFaultInjector hangs off BlockStore.fault_hook (and the direct-IO
engine's fault_hook) and perturbs file IO per tier directory:

  eio_read    OSError(EIO) raised before a block read
  eio_write   OSError(EIO) raised before a block write
  enospc      OSError(ENOSPC) raised before a block write
  bitflip     one bit flipped in the bytes a read returns (media rot /
              controller bitrot as seen by the reader; the file on disk
              is untouched, so the fault clears with the spec)
  torn_write  a write is silently truncated (crash-consistency hole:
              the caller believes the full buffer landed)

Specs match on a per-directory glob against the block file path (or the
bdev backing file path), mirroring FaultSpec's target-glob idiom, with
the same probability / max_hits shaping. All methods are thread-safe:
storage IO runs on event-loop threads, asyncio.to_thread workers, and
the direct-IO engine's ring thread concurrently.
"""

from __future__ import annotations

import errno
import fnmatch
import itertools
import random
import threading
from dataclasses import dataclass, field

READ_KINDS = ("eio_read", "bitflip")
WRITE_KINDS = ("eio_write", "enospc", "torn_write")
KINDS = READ_KINDS + WRITE_KINDS

_ids = itertools.count(1)


@dataclass
class DiskFaultSpec:
    kind: str                     # one of KINDS
    path_glob: str = "*"          # fnmatch against the file path
    probability: float = 1.0
    max_hits: int = 0             # 0 = unlimited
    seed: int = 0                 # bitflip/torn determinism
    fault_id: int = field(default_factory=lambda: next(_ids))
    hits: int = 0

    def matches(self, path: str) -> bool:
        if self.max_hits and self.hits >= self.max_hits:
            return False
        return fnmatch.fnmatch(path, self.path_glob)


class DiskFaultInjector:
    """Mutable set of DiskFaultSpecs consulted by the storage plane."""

    def __init__(self, rng: random.Random | None = None):
        self._specs: dict[int, DiskFaultSpec] = {}
        self._lock = threading.Lock()
        self._rng = rng or random.Random()

    # ---- spec management (test/storm control plane) ----
    def add(self, spec: DiskFaultSpec) -> int:
        with self._lock:
            self._specs[spec.fault_id] = spec
        return spec.fault_id

    def remove(self, fault_id: int) -> None:
        with self._lock:
            self._specs.pop(fault_id, None)

    def clear(self) -> None:
        with self._lock:
            self._specs.clear()

    def specs(self) -> list[DiskFaultSpec]:
        with self._lock:
            return list(self._specs.values())

    def _pick(self, path: str, kinds: tuple[str, ...]) -> DiskFaultSpec | None:
        with self._lock:
            for spec in self._specs.values():
                if spec.kind in kinds and spec.matches(path) \
                        and self._rng.random() < spec.probability:
                    spec.hits += 1
                    return spec
        return None

    # ---- hooks consulted by the storage plane ----
    def check_read(self, path: str) -> None:
        """Raise OSError(EIO) when an eio_read spec fires for `path`."""
        spec = self._pick(path, ("eio_read",))
        if spec is not None:
            raise OSError(errno.EIO,
                          f"injected EIO on read (fault {spec.fault_id})",
                          path)

    def check_write(self, path: str) -> None:
        """Raise OSError(EIO/ENOSPC) when a write-error spec fires."""
        spec = self._pick(path, ("eio_write", "enospc"))
        if spec is not None:
            code = errno.ENOSPC if spec.kind == "enospc" else errno.EIO
            raise OSError(code,
                          f"injected {errno.errorcode[code]} on write "
                          f"(fault {spec.fault_id})", path)

    def mutate_read(self, path: str, data) -> bool:
        """Flip one bit of `data` (a writable buffer: bytearray or
        memoryview) in place when a bitflip spec fires. Returns True
        when a flip happened. Empty buffers are never mutated."""
        if not len(data):
            return False
        spec = self._pick(path, ("bitflip",))
        if spec is None:
            return False
        # deterministic per (seed, hit): storms replay identically
        r = random.Random((spec.seed << 20) ^ spec.hits)
        i = r.randrange(len(data))
        data[i] ^= 1 << r.randrange(8)
        return True

    def torn_write_len(self, path: str, n: int) -> int:
        """Length a write of `n` bytes should be truncated to when a
        torn_write spec fires; `n` unchanged otherwise."""
        if n <= 1:
            return n
        spec = self._pick(path, ("torn_write",))
        if spec is None:
            return n
        r = random.Random((spec.seed << 20) ^ spec.hits)
        return r.randrange(1, n)

    def wants_read_data(self, path: str) -> bool:
        """True when a bitflip spec could fire for `path` — read paths
        that cannot expose bytes to the hook (kernel sendfile) fall back
        to a buffered read so the fault can actually apply."""
        with self._lock:
            return any(s.kind == "bitflip" and s.matches(path)
                       for s in self._specs.values())
