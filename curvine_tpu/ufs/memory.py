"""mem:// in-process object store.

S3-semantics test double (flat key space, pseudo-dirs from key prefixes) —
the role the reference fills with opendal memory/s3 services in tests.
Buckets are process-global so master, workers, and tests share state."""

from __future__ import annotations

import time

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri

# bucket -> {key -> (bytes, mtime_ms)}
_BUCKETS: dict[str, dict[str, tuple[bytes, int]]] = {}


def reset() -> None:
    _BUCKETS.clear()


class MemoryUfs(Ufs):
    scheme = "mem"

    @staticmethod
    def _bucket(uri: str) -> tuple[dict, str]:
        _, bucket, key = split_uri(uri)
        return _BUCKETS.setdefault(bucket, {}), key.rstrip("/")

    async def stat(self, uri: str) -> UfsStatus | None:
        b, key = self._bucket(uri)
        if key in b:
            data, mtime = b[key]
            return UfsStatus(path=uri.rstrip("/"), len=len(data), mtime=mtime)
        if not key:  # bucket root is a dir
            return UfsStatus(path=uri.rstrip("/"), is_dir=True)
        prefix = key + "/"
        if any(k.startswith(prefix) for k in b):
            return UfsStatus(path=uri.rstrip("/"), is_dir=True)
        return None

    async def list(self, uri: str) -> list[UfsStatus]:
        b, key = self._bucket(uri)
        _, bucket, _ = split_uri(uri)
        prefix = key + "/" if key else ""
        names: dict[str, UfsStatus] = {}
        for k, (data, mtime) in sorted(b.items()):
            if not k.startswith(prefix):
                continue
            rest = k[len(prefix):]
            head = rest.split("/", 1)[0]
            full = f"mem://{bucket}/{prefix}{head}"
            if "/" in rest:
                names.setdefault(head, UfsStatus(path=full, is_dir=True))
            else:
                names[head] = UfsStatus(path=full, len=len(data), mtime=mtime)
        return list(names.values())

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 1024 * 1024):
        b, key = self._bucket(uri)
        if key not in b:
            raise err.FileNotFound(uri)
        data = b[key][0]
        end = len(data) if length < 0 else min(len(data), offset + length)
        for i in range(offset, end, chunk_size):
            yield data[i:min(i + chunk_size, end)]

    async def write(self, uri: str, chunks) -> int:
        b, key = self._bucket(uri)
        buf = bytearray()
        async for chunk in chunks:
            buf += chunk
        b[key] = (bytes(buf), int(time.time() * 1000))
        return len(buf)

    async def delete(self, uri: str) -> None:
        b, key = self._bucket(uri)
        if key in b:
            del b[key]
            return
        prefix = key + "/"
        for k in [k for k in b if k.startswith(prefix)]:
            del b[k]


register_scheme("mem", MemoryUfs)
