"""file:// UFS adapter over the local filesystem.

Parity: curvine-ufs opendal services-fs + curvine-common/src/fs/local/."""

from __future__ import annotations

import asyncio
import os
import shutil

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri


def _fs_path(uri: str) -> str:
    _, authority, key = split_uri(uri)
    # file:///a/b → authority="", key="a/b"
    return "/" + key if not authority else f"/{authority}/{key}"


class LocalUfs(Ufs):
    scheme = "file"

    async def stat(self, uri: str) -> UfsStatus | None:
        p = _fs_path(uri)
        try:
            st = await asyncio.to_thread(os.stat, p)
        except FileNotFoundError:
            return None
        import stat as stat_mod
        return UfsStatus(path=f"file://{p}", is_dir=stat_mod.S_ISDIR(st.st_mode),
                         len=st.st_size, mtime=int(st.st_mtime * 1000))

    async def list(self, uri: str) -> list[UfsStatus]:
        p = _fs_path(uri)
        out = []
        try:
            names = await asyncio.to_thread(os.listdir, p)
        except FileNotFoundError as e:
            raise err.FileNotFound(uri) from e
        except NotADirectoryError as e:
            raise err.NotADirectory(uri) from e
        for name in sorted(names):
            st = await self.stat(f"file://{p.rstrip('/')}/{name}")
            if st is not None:
                out.append(st)
        return out

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 1024 * 1024):
        p = _fs_path(uri)
        try:
            f = await asyncio.to_thread(open, p, "rb")
        except FileNotFoundError as e:
            raise err.FileNotFound(uri) from e
        try:
            if offset:
                f.seek(offset)
            remaining = length if length >= 0 else None
            while True:
                n = chunk_size if remaining is None else min(chunk_size, remaining)
                if n == 0:
                    break
                chunk = await asyncio.to_thread(f.read, n)
                if not chunk:
                    break
                if remaining is not None:
                    remaining -= len(chunk)
                yield chunk
        finally:
            f.close()

    async def write(self, uri: str, chunks) -> int:
        p = _fs_path(uri)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        total = 0
        tmp = p + ".curvine-tmp"
        f = await asyncio.to_thread(open, tmp, "wb")
        try:
            async for chunk in chunks:
                await asyncio.to_thread(f.write, chunk)
                total += len(chunk)
        finally:
            f.close()
        os.replace(tmp, p)
        return total

    async def delete(self, uri: str) -> None:
        p = _fs_path(uri)
        try:
            if os.path.isdir(p):
                await asyncio.to_thread(shutil.rmtree, p)
            else:
                await asyncio.to_thread(os.unlink, p)
        except FileNotFoundError:
            pass

    async def mkdir(self, uri: str) -> None:
        await asyncio.to_thread(os.makedirs, _fs_path(uri), exist_ok=True)

    async def rename(self, src: str, dst: str) -> None:
        await asyncio.to_thread(os.replace, _fs_path(src), _fs_path(dst))


register_scheme("file", LocalUfs)
