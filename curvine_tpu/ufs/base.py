"""Under-filesystem (UFS) abstraction.

Parity: curvine-ufs/src/fs/ (opendal-backed object storage adapters). A
Ufs exposes object-store semantics: stat/list/walk/read/write/delete on
full URIs (``scheme://authority/key``). New backends register a scheme,
mirroring the reference's opendal service features (s3/oss/gcs/hdfs/...)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import AsyncIterator, Callable

from curvine_tpu.common import errors as err


@dataclass
class UfsStatus:
    path: str            # full uri
    is_dir: bool = False
    len: int = 0
    mtime: int = 0


class Ufs:
    scheme = ""

    def __init__(self, properties: dict | None = None):
        self.properties = properties or {}

    async def stat(self, uri: str) -> UfsStatus | None:
        raise NotImplementedError

    async def list(self, uri: str) -> list[UfsStatus]:
        raise NotImplementedError

    async def walk(self, uri: str, recursive: bool = True
                   ) -> AsyncIterator[UfsStatus]:
        for st in await self.list(uri):
            yield st
            if st.is_dir and recursive:
                async for sub in self.walk(st.path, recursive=True):
                    yield sub

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 1024 * 1024) -> AsyncIterator[bytes]:
        raise NotImplementedError
        yield b""  # pragma: no cover

    async def read_all(self, uri: str) -> bytes:
        out = bytearray()
        async for chunk in self.read(uri):
            out += chunk
        return bytes(out)

    async def write(self, uri: str, chunks) -> int:
        """Write full object from an async iterator of bytes; returns len."""
        raise NotImplementedError

    async def write_all(self, uri: str, data: bytes) -> int:
        async def one():
            yield data
        return await self.write(uri, one())

    async def delete(self, uri: str) -> None:
        raise NotImplementedError

    async def mkdir(self, uri: str) -> None:
        """Object stores have no real dirs; default is a no-op."""
        return None

    async def rename(self, src: str, dst: str) -> None:
        # default: copy + delete (object-store semantics, no atomic rename)
        data = await self.read_all(src)
        await self.write_all(dst, data)
        await self.delete(src)


_SCHEMES: dict[str, Callable[..., Ufs]] = {}


def register_scheme(scheme: str, factory: Callable[..., Ufs]) -> None:
    _SCHEMES[scheme] = factory


def split_uri(uri: str) -> tuple[str, str, str]:
    """uri → (scheme, authority, key-path)."""
    if "://" not in uri:
        return "file", "", uri
    scheme, rest = uri.split("://", 1)
    if "/" in rest:
        authority, key = rest.split("/", 1)
    else:
        authority, key = rest, ""
    return scheme, authority, key


def create_ufs(uri: str, properties: dict | None = None) -> Ufs:
    scheme, _, _ = split_uri(uri)
    factory = _SCHEMES.get(scheme)
    if factory is None:
        raise err.UfsError(f"no UFS backend for scheme {scheme!r}; "
                           f"registered: {sorted(_SCHEMES)}")
    return factory(properties=properties)
