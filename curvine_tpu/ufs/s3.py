"""s3:// UFS adapter — minimal S3 REST client with SigV4 signing.

Parity: curvine-ufs opendal services-s3. Implemented directly against the
S3 REST API (GET/PUT/DELETE object, ListObjectsV2, HEAD) over aiohttp so no
SDK is needed. Credentials/endpoint come from mount properties or the
standard AWS_* environment variables. Network-gated: in an egress-less
environment every call surfaces a UfsError; the signing logic itself is
unit-tested offline (tests/test_ufs.py)."""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def sigv4_headers(method: str, url: str, region: str, access_key: str,
                  secret_key: str, payload_hash: str = _EMPTY_SHA256,
                  now: datetime.datetime | None = None,
                  extra_headers: dict | None = None) -> dict:
    """Compute AWS SigV4 Authorization headers for one request."""
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")
    parsed = urllib.parse.urlparse(url)
    host = parsed.netloc
    # S3 SigV4 rule: the canonical URI is the path exactly as sent on the
    # wire, each segment URI-encoded ONCE (object_url already did that) —
    # re-quoting here would double-encode '%' and break keys with spaces
    # etc. against real verifiers.
    canonical_uri = parsed.path or "/"
    # canonical query: sorted, url-encoded
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q))
    headers = {"host": host, "x-amz-content-sha256": payload_hash,
               "x-amz-date": amz_date}
    headers.update({k.lower(): v for k, v in (extra_headers or {}).items()})
    signed = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k].strip()}\n"
                                for k in sorted(headers))
    creq = "\n".join([method, canonical_uri, canonical_query,
                      canonical_headers, signed, payload_hash])
    scope = f"{datestamp}/{region}/s3/aws4_request"
    sts = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                     hashlib.sha256(creq.encode()).hexdigest()])

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret_key).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, "s3")
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, sts.encode(), hashlib.sha256).hexdigest()
    auth = (f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
            f"SignedHeaders={signed}, Signature={signature}")
    out = dict(headers)
    out["authorization"] = auth
    del out["host"]  # aiohttp sets it
    return out


class S3Ufs(Ufs):
    scheme = "s3"

    def __init__(self, properties: dict | None = None):
        super().__init__(properties)
        p = self.properties
        self.endpoint = (p.get("s3.endpoint_url")
                         or os.environ.get("AWS_ENDPOINT_URL", "")).rstrip("/")
        self.region = p.get("s3.region_name",
                            os.environ.get("AWS_REGION", "us-east-1"))
        self.access_key = p.get("s3.credentials.access",
                                os.environ.get("AWS_ACCESS_KEY_ID", ""))
        self.secret_key = p.get("s3.credentials.secret",
                                os.environ.get("AWS_SECRET_ACCESS_KEY", ""))
        self.path_style = str(p.get("s3.path_style", "true")).lower() == "true"

    def object_url(self, uri: str) -> str:
        _, bucket, key = split_uri(uri)
        key = urllib.parse.quote(key)
        if self.endpoint:
            if self.path_style:
                return f"{self.endpoint}/{bucket}/{key}"
            scheme, host = self.endpoint.split("://", 1)
            return f"{scheme}://{bucket}.{host}/{key}"
        return f"https://{bucket}.s3.{self.region}.amazonaws.com/{key}"

    async def _request(self, method: str, url: str, data: bytes = b"",
                       extra_headers: dict | None = None):
        try:
            import aiohttp
        except ImportError as e:  # pragma: no cover
            raise err.UfsError("aiohttp unavailable for s3://") from e
        payload_hash = hashlib.sha256(data).hexdigest()
        headers = sigv4_headers(method, url, self.region, self.access_key,
                                self.secret_key, payload_hash,
                                extra_headers=extra_headers)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.request(method, url, data=data or None,
                                        headers=headers) as resp:
                    body = await resp.read()
                    return resp.status, dict(resp.headers), body
        except Exception as e:  # noqa: BLE001 — network-gated environment
            raise err.UfsError(f"s3 {method} {url}: {e}") from e

    async def stat(self, uri: str) -> UfsStatus | None:
        status, headers, _ = await self._request("HEAD", self.object_url(uri))
        if status == 200:
            return UfsStatus(path=uri, len=int(headers.get("Content-Length", 0)))
        if status == 404:
            # prefix probe: a "directory" exists if any key has the prefix
            subs = await self.list(uri)
            if subs:
                return UfsStatus(path=uri.rstrip("/"), is_dir=True)
            return None
        raise err.UfsError(f"s3 HEAD {uri}: http {status}")

    async def list(self, uri: str) -> list[UfsStatus]:
        _, bucket, key = split_uri(uri)
        prefix = key.rstrip("/") + "/" if key else ""
        base = (f"{self.endpoint}/{bucket}" if self.endpoint and self.path_style
                else self.object_url(f"s3://{bucket}/").rstrip("/"))
        url = (f"{base}?list-type=2&delimiter=%2F"
               f"&prefix={urllib.parse.quote(prefix)}")
        status, _, body = await self._request("GET", url)
        if status != 200:
            raise err.UfsError(f"s3 LIST {uri}: http {status}")
        ns = "{http://s3.amazonaws.com/doc/2006-03-01/}"
        root = ET.fromstring(body)
        out = []
        for c in root.findall(f"{ns}Contents"):
            k = c.findtext(f"{ns}Key", "")
            if k == prefix:
                continue
            out.append(UfsStatus(path=f"s3://{bucket}/{k}",
                                 len=int(c.findtext(f"{ns}Size", "0"))))
        for c in root.findall(f"{ns}CommonPrefixes"):
            k = c.findtext(f"{ns}Prefix", "").rstrip("/")
            out.append(UfsStatus(path=f"s3://{bucket}/{k}", is_dir=True))
        return out

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 4 * 1024 * 1024):
        rng = None
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            rng = {"range": f"bytes={offset}-{end}"}
        status, _, body = await self._request("GET", self.object_url(uri),
                                              extra_headers=rng)
        if status == 404:
            raise err.FileNotFound(uri)
        if status not in (200, 206):
            raise err.UfsError(f"s3 GET {uri}: http {status}")
        for i in range(0, len(body), chunk_size):
            yield body[i:i + chunk_size]

    async def write(self, uri: str, chunks) -> int:
        buf = bytearray()
        async for chunk in chunks:
            buf += chunk
        status, _, _ = await self._request("PUT", self.object_url(uri),
                                           data=bytes(buf))
        if status != 200:
            raise err.UfsError(f"s3 PUT {uri}: http {status}")
        return len(buf)

    async def delete(self, uri: str) -> None:
        status, _, _ = await self._request("DELETE", self.object_url(uri))
        if status not in (200, 204, 404):
            raise err.UfsError(f"s3 DELETE {uri}: http {status}")


register_scheme("s3", S3Ufs)
