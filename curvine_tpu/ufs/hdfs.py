"""hdfs:// UFS adapter speaking the WebHDFS v1 REST protocol.

Parity: curvine-ufs/src/fs/ HDFS support (the reference wires HDFS via
opendal/JNI; this adapter rides WebHDFS — the REST surface every HDFS
namenode serves — so no JVM is needed). It is the exact client of the
protocol `gateway/webhdfs.py` serves, and the two are tested against
each other (tests/test_ufs_backends.py): a curvine cluster can mount
ANOTHER curvine cluster (or a real HDFS) as its under-store.

URI: ``hdfs://host:port/path``. ``port`` is the WebHDFS HTTP port
(default 9870); override with ``hdfs.endpoint_url`` in mount properties
when the REST endpoint differs from the authority.
"""

from __future__ import annotations

import urllib.parse

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri

_CHUNK = 4 * 1024 * 1024


class HdfsUfs(Ufs):
    scheme = "hdfs"

    def __init__(self, properties: dict | None = None):
        super().__init__(properties)
        self._session = None

    def _endpoint(self, authority: str) -> str:
        ep = self.properties.get("hdfs.endpoint_url")
        if ep:
            return ep.rstrip("/")
        if ":" not in authority and authority:
            authority = f"{authority}:9870"
        return f"http://{authority}"

    def _url(self, uri: str, op: str, **params) -> str:
        _, authority, key = split_uri(uri)
        key = urllib.parse.quote(key)      # '#'/'?'/'%' must not leak
        qs = urllib.parse.urlencode({"op": op, **{
            k: v for k, v in params.items() if v is not None}})
        return f"{self._endpoint(authority)}/webhdfs/v1/{key}?{qs}"

    async def _http(self):
        if self._session is None:
            import aiohttp
            self._session = aiohttp.ClientSession()
        return self._session

    async def close(self) -> None:
        if self._session is not None:
            await self._session.close()
            self._session = None

    @staticmethod
    async def _raise_remote(resp, uri: str) -> None:
        try:
            body = await resp.json()
            exc = body.get("RemoteException", {})
            cls, msg = exc.get("exception", ""), exc.get("message", "")
        except Exception:
            cls, msg = "", await resp.text()
        if resp.status == 404 or "FileNotFound" in cls:
            raise err.FileNotFound(uri)
        if "FileAlreadyExists" in cls:
            raise err.FileAlreadyExists(uri)
        raise err.UfsError(f"webhdfs {resp.status} {cls}: {msg}")

    def _status(self, uri: str, fs: dict, name: str | None = None) -> UfsStatus:
        suffix = name if name is not None else fs.get("pathSuffix", "")
        path = uri.rstrip("/")
        if suffix:
            path = f"{path}/{suffix}"
        return UfsStatus(path=path, is_dir=fs.get("type") == "DIRECTORY",
                         len=fs.get("length", 0),
                         mtime=fs.get("modificationTime", 0))

    # ---------------- ops ----------------

    async def stat(self, uri: str) -> UfsStatus | None:
        s = await self._http()
        async with s.get(self._url(uri, "GETFILESTATUS")) as r:
            if r.status == 404:
                return None
            if r.status >= 400:
                await self._raise_remote(r, uri)
            fs = (await r.json())["FileStatus"]
            return self._status(uri, fs, name="")

    async def list(self, uri: str) -> list[UfsStatus]:
        s = await self._http()
        async with s.get(self._url(uri, "LISTSTATUS")) as r:
            if r.status >= 400:
                await self._raise_remote(r, uri)
            body = await r.json()
            return [self._status(uri, fs)
                    for fs in body["FileStatuses"]["FileStatus"]]

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = _CHUNK):
        s = await self._http()
        params = {"offset": offset}
        if length >= 0:
            params["length"] = length
        async with s.get(self._url(uri, "OPEN", **params)) as r:
            if r.status >= 400:
                await self._raise_remote(r, uri)
            async for chunk in r.content.iter_chunked(chunk_size):
                yield chunk

    async def write(self, uri: str, chunks) -> int:
        """WebHDFS two-step CREATE, streaming the chunk iterator into the
        data PUT (no whole-object buffering). A real namenode answers the
        bodyless step-1 PUT with a 307 redirect to a datanode; single-hop
        servers (like our own gateway) answer 2xx directly and get the
        body in a second direct PUT. Either way the one-shot generator is
        consumed exactly once."""
        total = 0

        async def body():
            nonlocal total
            async for chunk in chunks:
                total += len(chunk)
                yield bytes(chunk)

        s = await self._http()
        url = self._url(uri, "CREATE", overwrite="true")
        async with s.put(url, allow_redirects=False) as r1:
            if r1.status in (301, 302, 307):
                target = r1.headers.get("Location", url)
            elif r1.status < 400:
                target = url          # single-hop server: re-PUT with data
            else:
                await self._raise_remote(r1, uri)
        async with s.put(target, data=body()) as r2:
            if r2.status >= 400:
                await self._raise_remote(r2, uri)
        return total

    async def delete(self, uri: str) -> None:
        s = await self._http()
        async with s.delete(self._url(uri, "DELETE",
                                      recursive="true")) as r:
            if r.status >= 400:
                await self._raise_remote(r, uri)
            # WebHDFS signals "nothing deleted" as 200 {"boolean": false}
            try:
                ok = (await r.json()).get("boolean", True)
            except Exception:
                ok = True
            if not ok:
                raise err.FileNotFound(uri)

    async def mkdir(self, uri: str) -> None:
        s = await self._http()
        async with s.put(self._url(uri, "MKDIRS")) as r:
            if r.status >= 400:
                await self._raise_remote(r, uri)

    async def rename(self, src: str, dst: str) -> None:
        _, _, dkey = split_uri(dst)
        s = await self._http()
        async with s.put(self._url(src, "RENAME",
                                   destination=f"/{dkey}")) as r:
            if r.status >= 400:
                await self._raise_remote(r, src)


register_scheme("hdfs", HdfsUfs)
