from curvine_tpu.ufs.base import Ufs, UfsStatus, create_ufs, register_scheme

# register built-in schemes
import curvine_tpu.ufs.local   # noqa: F401  (file://)
import curvine_tpu.ufs.memory  # noqa: F401  (mem://)
import curvine_tpu.ufs.s3      # noqa: F401  (s3://, env-gated)
import curvine_tpu.ufs.hdfs    # noqa: F401  (hdfs:// via WebHDFS REST)
import curvine_tpu.ufs.gcs     # noqa: F401  (gs://gcs:// via XML interop)
import curvine_tpu.ufs.oss     # noqa: F401  (oss:// native OSS signing)
import curvine_tpu.ufs.azblob  # noqa: F401  (azblob:// SharedKey)
import curvine_tpu.ufs.stubs   # noqa: F401  (cos, env-gated)

__all__ = ["Ufs", "UfsStatus", "create_ufs", "register_scheme"]
