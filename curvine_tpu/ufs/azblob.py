"""azblob:// UFS adapter — Azure Blob Storage REST with SharedKey auth.

Parity: curvine-ufs opendal services-azblob (the reference mounts Azure
Blob containers as UFS). Implemented directly against the Blob service
REST API over aiohttp: Put Blob (BlockBlob), Get Blob (ranged), Get Blob
Properties, Delete Blob, List Blobs (flat listing with prefix +
delimiter). Auth is the SharedKey scheme — HMAC-SHA256 over the
canonicalized request, `Authorization: SharedKey <account>:<sig>`.

URI form: ``azblob://<container>/<key>``. Properties:
  azblob.account        storage account name
  azblob.key            base64 account key
  azblob.endpoint_url   override (emulator/gateway); default
                        https://<account>.blob.core.windows.net

Network-gated like s3://: in an egress-less environment the signing is
exercised against the in-tree Azure-wire gateway
(curvine_tpu/gateway/azblob.py, tests/test_ufs_backends.py)."""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri

API_VERSION = "2021-08-06"


def sharedkey_auth(method: str, url: str, account: str, key_b64: str,
                   headers: dict) -> str:
    """Compute the SharedKey Authorization value for one request.
    `headers` must already hold x-ms-date, x-ms-version and any x-ms-*
    op headers (lowercase names)."""
    parsed = urllib.parse.urlparse(url)
    canon_headers = "".join(
        f"{k}:{headers[k].strip()}\n"
        for k in sorted(h for h in headers if h.startswith("x-ms-")))
    resource = f"/{account}{urllib.parse.unquote(parsed.path) or '/'}"
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canon_resource = resource + "".join(
        f"\n{k.lower()}:{v}" for k, v in sorted(q))
    length = headers.get("content-length", "")
    if length == "0":
        length = ""           # 2015-02-21+ rule: zero length is empty
    sts = "\n".join([
        method.upper(),
        headers.get("content-encoding", ""),
        headers.get("content-language", ""),
        length,
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        "",                    # Date (x-ms-date is canonicalized instead)
        headers.get("if-modified-since", ""),
        headers.get("if-match", ""),
        headers.get("if-none-match", ""),
        headers.get("if-unmodified-since", ""),
        headers.get("range", ""),
        canon_headers + canon_resource])
    sig = base64.b64encode(hmac.new(
        base64.b64decode(key_b64), sts.encode(), hashlib.sha256).digest())
    return f"SharedKey {account}:{sig.decode()}"


class AzblobUfs(Ufs):
    scheme = "azblob"

    def __init__(self, properties: dict | None = None):
        super().__init__(properties)
        p = self.properties
        self.account = p.get("azblob.account",
                             os.environ.get("AZURE_STORAGE_ACCOUNT", ""))
        self.key = p.get("azblob.key",
                         os.environ.get("AZURE_STORAGE_KEY", ""))
        self.endpoint = (p.get("azblob.endpoint_url", "")).rstrip("/")
        if not self.endpoint:
            self.endpoint = f"https://{self.account}.blob.core.windows.net"

    def blob_url(self, uri: str) -> str:
        _, container, key = split_uri(uri)
        return f"{self.endpoint}/{container}/{urllib.parse.quote(key)}"

    async def _request(self, method: str, url: str, data: bytes = b"",
                       extra_headers: dict | None = None):
        try:
            import aiohttp
        except ImportError as e:  # pragma: no cover
            raise err.UfsError("aiohttp unavailable for azblob://") from e
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {
            "x-ms-date": now.strftime("%a, %d %b %Y %H:%M:%S GMT"),
            "x-ms-version": API_VERSION,
            "content-length": str(len(data)),
        }
        if data:
            # bind the signature to the payload (SharedKey signs
            # Content-MD5 when present; the in-tree gateway verifies it)
            headers["content-md5"] = base64.b64encode(
                hashlib.md5(data).digest()).decode()
        headers.update({k.lower(): v for k, v in (extra_headers or {}).items()})
        headers["authorization"] = sharedkey_auth(
            method, url, self.account, self.key, headers)
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.request(method, url, data=data or None,
                                        headers=headers,
                                        skip_auto_headers=("Content-Type",),
                                        ) as resp:
                    body = await resp.read()
                    return resp.status, dict(resp.headers), body
        except Exception as e:  # noqa: BLE001 — network-gated environment
            raise err.UfsError(f"azblob {method} {url}: {e}") from e

    # ---------------- ops ----------------

    async def stat(self, uri: str) -> UfsStatus | None:
        status, headers, _ = await self._request("HEAD", self.blob_url(uri))
        if status == 200:
            return UfsStatus(path=uri,
                             len=int(headers.get("Content-Length", 0)))
        if status == 404:
            subs = await self.list(uri)
            if subs:
                return UfsStatus(path=uri.rstrip("/"), is_dir=True)
            return None
        raise err.UfsError(f"azblob HEAD {uri}: http {status}")

    async def list(self, uri: str) -> list[UfsStatus]:
        _, container, key = split_uri(uri)
        prefix = key.rstrip("/") + "/" if key else ""
        url = (f"{self.endpoint}/{container}?restype=container&comp=list"
               f"&delimiter=%2F&prefix={urllib.parse.quote(prefix)}")
        status, _, body = await self._request("GET", url)
        if status != 200:
            raise err.UfsError(f"azblob LIST {uri}: http {status}")
        root = ET.fromstring(body)
        out = []
        for b in root.iter("Blob"):
            name = b.findtext("Name", "")
            if name == prefix:
                continue
            size = b.findtext("Properties/Content-Length", "0")
            out.append(UfsStatus(path=f"azblob://{container}/{name}",
                                 len=int(size)))
        for p in root.iter("BlobPrefix"):
            name = p.findtext("Name", "").rstrip("/")
            out.append(UfsStatus(path=f"azblob://{container}/{name}",
                                 is_dir=True))
        return out

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 4 * 1024 * 1024):
        rng = None
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            rng = {"range": f"bytes={offset}-{end}"}
        status, _, body = await self._request("GET", self.blob_url(uri),
                                              extra_headers=rng)
        if status == 404:
            raise err.FileNotFound(uri)
        if status not in (200, 206):
            raise err.UfsError(f"azblob GET {uri}: http {status}")
        for i in range(0, len(body), chunk_size):
            yield body[i:i + chunk_size]

    async def write(self, uri: str, chunks) -> int:
        buf = bytearray()
        async for chunk in chunks:
            buf += chunk
        status, _, _ = await self._request(
            "PUT", self.blob_url(uri), data=bytes(buf),
            extra_headers={"x-ms-blob-type": "BlockBlob"})
        if status not in (200, 201):
            raise err.UfsError(f"azblob PUT {uri}: http {status}")
        return len(buf)

    async def delete(self, uri: str) -> None:
        status, _, _ = await self._request("DELETE", self.blob_url(uri))
        if status not in (200, 202, 404):
            raise err.UfsError(f"azblob DELETE {uri}: http {status}")


register_scheme("azblob", AzblobUfs)
