"""gs:///gcs:// UFS adapter — GCS via the XML interoperability API.

Parity: curvine-ufs opendal services-gcs. Google Cloud Storage's XML API
is S3-wire-compatible when used with HMAC interoperability keys, so this
rides the same SigV4 client as s3:// with the GCS endpoint as the
default. Properties/env:

  gcs.endpoint_url       default https://storage.googleapis.com
                         (point at any S3-compatible endpoint for tests)
  gcs.credentials.access / gcs.credentials.secret
                         HMAC interop key pair (falls back to
                         GCS_ACCESS_KEY_ID/GCS_SECRET_ACCESS_KEY, then
                         the s3.* properties / AWS_* env)
"""

from __future__ import annotations

import os

from curvine_tpu.ufs.base import register_scheme
from curvine_tpu.ufs.s3 import S3Ufs


class GcsUfs(S3Ufs):
    scheme = "gcs"

    def __init__(self, properties: dict | None = None):
        p = dict(properties or {})
        p.setdefault("s3.endpoint_url",
                     p.get("gcs.endpoint_url")
                     or os.environ.get("GCS_ENDPOINT_URL",
                                       "https://storage.googleapis.com"))
        access = (p.get("gcs.credentials.access")
                  or os.environ.get("GCS_ACCESS_KEY_ID"))
        secret = (p.get("gcs.credentials.secret")
                  or os.environ.get("GCS_SECRET_ACCESS_KEY"))
        if access:
            p.setdefault("s3.credentials.access", access)
        if secret:
            p.setdefault("s3.credentials.secret", secret)
        super().__init__(p)


register_scheme("gcs", GcsUfs)
register_scheme("gs", GcsUfs)
