"""oss:// UFS adapter — Alibaba Cloud OSS REST with native OSS signing.

Parity: curvine-ufs opendal services-oss. OSS's wire protocol is
S3-V1-shaped (same ListBucketResult XML, same object verbs) but its
native auth is NOT SigV4: the header scheme is
``Authorization: OSS <AccessKeyId>:<base64 hmac-sha1(secret, sts)>``
over VERB/Content-MD5/Content-Type/Date/x-oss-* headers/canonicalized
resource. This adapter signs natively (an OSS endpoint that only takes
S3-compatible credentials can instead ride the s3:// adapter via
``s3.endpoint_url`` — both routes now work).

URI form: ``oss://<bucket>/<key>``. Properties:
  oss.credentials.access / oss.credentials.secret
  oss.endpoint_url   e.g. https://oss-cn-hangzhou.aliyuncs.com or the
                     in-tree S3 gateway (which verifies OSS signatures)
Network-gated like s3://; signing is exercised against the in-tree
gateway in tests/test_ufs_backends.py."""

from __future__ import annotations

import base64
import datetime
import hashlib
import hmac
import os
import urllib.parse
import xml.etree.ElementTree as ET

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, UfsStatus, register_scheme, split_uri

# query params that are part of the canonicalized resource (the OSS
# subresource list, trimmed to what this adapter can emit)
_SUBRESOURCES = {"acl", "uploads", "uploadId", "partNumber", "delete",
                 "append", "position", "symlink", "tagging", "restype",
                 "comp", "list-type"}


def oss_string_to_sign(method: str, path: str, query: str,
                       headers: dict) -> str:
    """Canonical string for OSS header signing. `path` is the
    canonicalized resource path (/bucket/key); `headers` lowercase."""
    canon_oss = "".join(
        f"{k}:{headers[k].strip()}\n"
        for k in sorted(h for h in headers if h.startswith("x-oss-")))
    q = [(k, v) for k, v in urllib.parse.parse_qsl(
        query, keep_blank_values=True) if k in _SUBRESOURCES]
    resource = path
    if q:
        resource += "?" + "&".join(
            f"{k}={v}" if v else k for k, v in sorted(q))
    return "\n".join([
        method.upper(),
        headers.get("content-md5", ""),
        headers.get("content-type", ""),
        headers.get("date", ""),
        canon_oss + resource])


def oss_sign(secret: str, sts: str) -> str:
    return base64.b64encode(hmac.new(
        secret.encode(), sts.encode(), hashlib.sha1).digest()).decode()


class OssUfs(Ufs):
    scheme = "oss"

    def __init__(self, properties: dict | None = None):
        super().__init__(properties)
        p = self.properties
        # an S3-compatible endpoint keeps working through the SigV4
        # adapter (the pre-round-5 route)
        self.endpoint = (p.get("oss.endpoint_url")
                         or p.get("s3.endpoint_url", "")).rstrip("/")
        self.access = p.get("oss.credentials.access",
                            os.environ.get("OSS_ACCESS_KEY_ID", ""))
        self.secret = p.get("oss.credentials.secret",
                            os.environ.get("OSS_ACCESS_KEY_SECRET", ""))
        if not self.endpoint:
            region = p.get("oss.region", "oss-cn-hangzhou")
            self.endpoint = f"https://{region}.aliyuncs.com"

    def object_url(self, uri: str) -> str:
        _, bucket, key = split_uri(uri)
        return f"{self.endpoint}/{bucket}/{urllib.parse.quote(key)}"

    async def _request(self, method: str, url: str, data: bytes = b"",
                       extra_headers: dict | None = None):
        try:
            import aiohttp
        except ImportError as e:  # pragma: no cover
            raise err.UfsError("aiohttp unavailable for oss://") from e
        parsed = urllib.parse.urlparse(url)
        now = datetime.datetime.now(datetime.timezone.utc)
        headers = {"date": now.strftime("%a, %d %b %Y %H:%M:%S GMT")}
        if data:
            # bind the signature to the payload: OSS signs Content-MD5
            # when present, and the in-tree gateway verifies it against
            # the received bytes (replay-with-substituted-body defense)
            headers["content-md5"] = base64.b64encode(
                hashlib.md5(data).digest()).decode()
        headers.update({k.lower(): v
                        for k, v in (extra_headers or {}).items()})
        sts = oss_string_to_sign(
            method, urllib.parse.unquote(parsed.path) or "/",
            parsed.query, headers)
        headers["authorization"] = \
            f"OSS {self.access}:{oss_sign(self.secret, sts)}"
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.request(method, url, data=data or None,
                                        headers=headers,
                                        skip_auto_headers=("Content-Type",),
                                        ) as resp:
                    body = await resp.read()
                    return resp.status, dict(resp.headers), body
        except Exception as e:  # noqa: BLE001 — network-gated environment
            raise err.UfsError(f"oss {method} {url}: {e}") from e

    # ---------------- ops (S3-wire shapes) ----------------

    async def stat(self, uri: str) -> UfsStatus | None:
        status, headers, _ = await self._request("HEAD", self.object_url(uri))
        if status == 200:
            return UfsStatus(path=uri,
                             len=int(headers.get("Content-Length", 0)))
        if status == 404:
            subs = await self.list(uri)
            if subs:
                return UfsStatus(path=uri.rstrip("/"), is_dir=True)
            return None
        raise err.UfsError(f"oss HEAD {uri}: http {status}")

    async def list(self, uri: str) -> list[UfsStatus]:
        _, bucket, key = split_uri(uri)
        prefix = key.rstrip("/") + "/" if key else ""
        url = (f"{self.endpoint}/{bucket}?delimiter=%2F"
               f"&prefix={urllib.parse.quote(prefix)}")
        status, _, body = await self._request("GET", url)
        if status != 200:
            raise err.UfsError(f"oss LIST {uri}: http {status}")
        root = ET.fromstring(body)
        ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
        out = []
        for c in root.findall(f"{ns}Contents"):
            k = c.findtext(f"{ns}Key", "")
            if k == prefix:
                continue
            out.append(UfsStatus(path=f"oss://{bucket}/{k}",
                                 len=int(c.findtext(f"{ns}Size", "0"))))
        for c in root.findall(f"{ns}CommonPrefixes"):
            k = c.findtext(f"{ns}Prefix", "").rstrip("/")
            out.append(UfsStatus(path=f"oss://{bucket}/{k}", is_dir=True))
        return out

    async def read(self, uri: str, offset: int = 0, length: int = -1,
                   chunk_size: int = 4 * 1024 * 1024):
        rng = None
        if offset or length >= 0:
            end = "" if length < 0 else str(offset + length - 1)
            rng = {"range": f"bytes={offset}-{end}"}
        status, _, body = await self._request("GET", self.object_url(uri),
                                              extra_headers=rng)
        if status == 404:
            raise err.FileNotFound(uri)
        if status not in (200, 206):
            raise err.UfsError(f"oss GET {uri}: http {status}")
        for i in range(0, len(body), chunk_size):
            yield body[i:i + chunk_size]

    async def write(self, uri: str, chunks) -> int:
        buf = bytearray()
        async for chunk in chunks:
            buf += chunk
        status, _, _ = await self._request("PUT", self.object_url(uri),
                                           data=bytes(buf))
        if status != 200:
            raise err.UfsError(f"oss PUT {uri}: http {status}")
        return len(buf)

    async def delete(self, uri: str) -> None:
        status, _, _ = await self._request("DELETE", self.object_url(uri))
        if status not in (200, 204, 404):
            raise err.UfsError(f"oss DELETE {uri}: http {status}")


register_scheme("oss", OssUfs)
