"""Scheme stubs for object stores that need environment-specific backends.

Parity: curvine-ufs optional opendal services (oss/gcs/azblob/hdfs/...).
Each scheme is registered so mounts/type-checking work everywhere; actual
IO raises a clear gating error until a backend (credentials + network)
is wired via mount properties. S3-compatible endpoints can usually be
served today by the s3:// adapter with `s3.endpoint_url`."""

from __future__ import annotations

from curvine_tpu.common import errors as err
from curvine_tpu.ufs.base import Ufs, register_scheme
from curvine_tpu.ufs.s3 import S3Ufs


def _gated(scheme: str, hint: str):
    class GatedUfs(Ufs):
        def __init__(self, properties=None):
            super().__init__(properties)
            # S3-compatible services ride the SigV4 adapter when an
            # endpoint is configured
            if properties and properties.get("s3.endpoint_url"):
                self.__class__ = S3Ufs          # type: ignore[assignment]
                S3Ufs.__init__(self, properties)
                return
            raise err.UfsError(
                f"{scheme}:// backend is environment-gated: {hint}")
    GatedUfs.scheme = scheme
    return GatedUfs


register_scheme("cos", _gated(
    "cos", "set s3.endpoint_url to the COS S3-compatible endpoint"))
# OSS-HDFS (Alibaba JindoFS service): the reference ships a 1,099-LoC
# native FFI filesystem for its proprietary wire protocol
# (curvine-ufs/src/oss_hdfs/oss_hdfs_filesystem.rs). Zero-egress here:
# the scheme registers so mounts type-check, and endpoints exposing the
# S3-compatible or WebHDFS-compatible surface route through oss:// /
# hdfs:// today; the native protocol stays env-gated.
register_scheme("oss-hdfs", _gated(
    "oss-hdfs", "route via oss:// (S3-compatible) or hdfs:// (WebHDFS) "
    "endpoints; the native JindoFS wire protocol needs the vendor SDK"))
# gcs://, hdfs://, oss:// and azblob:// have real backends now
# (ufs/gcs.py XML interop, ufs/hdfs.py WebHDFS REST, ufs/oss.py native
# OSS signing, ufs/azblob.py SharedKey) — no longer stubbed.
