"""Synchronous Python SDK.

Parity: curvine-libsdk/src/python/ (python_abi.rs, python_filesystem.rs) —
a blocking FileSystem facade over the async client, safe to call from any
thread (dedicated event-loop thread under the hood), with file-like
reader/writer objects (lib_fs_reader.rs / lib_fs_writer.rs)."""

from __future__ import annotations

import asyncio
import threading
from typing import Any

from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.common.types import FileStatus, SetAttrOpts


class _LoopThread:
    """One shared asyncio loop running on a daemon thread."""

    def __init__(self) -> None:
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self.loop.run_forever,
                                       daemon=True, name="curvine-sdk")
        self.thread.start()

    def run(self, coro, timeout: float | None = 120) -> Any:
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result(timeout)

    def close(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.thread.join(5)


class CurvineFile:
    """File-like object (binary). Mode 'rb' wraps FsReader (seekable);
    'wb'/'ab' wrap FsWriter (sequential)."""

    def __init__(self, lt: _LoopThread, inner, mode: str):
        self._lt = lt
        self._inner = inner
        self.mode = mode
        self.closed = False

    # -- reading --
    def read(self, n: int = -1) -> bytes:
        return self._lt.run(self._inner.read(n))

    def pread(self, offset: int, n: int) -> bytes:
        return self._lt.run(self._inner.pread(offset, n))

    def seek(self, pos: int, whence: int = 0) -> int:
        if whence == 1:
            pos += self._inner.pos
        elif whence == 2:
            pos += self._inner.len
        self._inner.seek(pos)
        return pos

    def tell(self) -> int:
        return self._inner.pos

    # -- writing --
    def write(self, data: bytes) -> int:
        return self._lt.run(self._inner.write(data))

    def flush(self) -> None:
        if self.mode != "rb":
            self._lt.run(self._inner.flush())

    def close(self) -> None:
        if not self.closed:
            self._lt.run(self._inner.close())
            self.closed = True

    def __enter__(self) -> "CurvineFile":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class CurvineFileSystem:
    """Blocking FS API: the SDK entry point.

    >>> fs = CurvineFileSystem(master="127.0.0.1:8995")
    >>> with fs.open("/data/x.bin", "wb") as f: f.write(b"...")
    """

    def __init__(self, conf: ClusterConf | None = None,
                 master: str | None = None, conf_path: str | None = None):
        self.conf = conf or ClusterConf.load(conf_path)
        if master:
            self.conf.client.master_addrs = [master]
        self._lt = _LoopThread()
        from curvine_tpu.client import CurvineClient

        async def make():
            return CurvineClient(self.conf)
        self._client = self._lt.run(make())

    @property
    def client(self):
        return self._client

    # ---------------- namespace ----------------

    def mkdir(self, path: str, create_parent: bool = True) -> FileStatus:
        return self._lt.run(self._client.meta.mkdir(path, create_parent))

    def exists(self, path: str) -> bool:
        return self._lt.run(self._client.meta.exists(path))

    def get_status(self, path: str) -> FileStatus:
        return self._lt.run(self._client.meta.file_status(path))

    def list_status(self, path: str) -> list[FileStatus]:
        return self._lt.run(self._client.meta.list_status(path))

    def delete(self, path: str, recursive: bool = False) -> None:
        self._lt.run(self._client.meta.delete(path, recursive))

    def rename(self, src: str, dst: str) -> bool:
        return self._lt.run(self._client.meta.rename(src, dst))

    def set_attr(self, path: str, **kw) -> None:
        self._lt.run(self._client.meta.set_attr(path, SetAttrOpts(**kw)))

    # ---------------- io ----------------

    def open(self, path: str, mode: str = "rb") -> CurvineFile:
        if mode in ("r", "rb"):
            return CurvineFile(self._lt, self._lt.run(self._client.open(path)),
                               "rb")
        if mode in ("w", "wb"):
            return CurvineFile(self._lt,
                               self._lt.run(self._client.create(
                                   path, overwrite=True)), "wb")
        if mode in ("a", "ab"):
            return CurvineFile(self._lt,
                               self._lt.run(self._client.append(path)), "ab")
        raise ValueError(f"unsupported mode {mode!r}")

    def read_all(self, path: str) -> bytes:
        async def go():
            r = await self._client.open(path)
            try:
                return await r.read_all()
            finally:
                await r.close()
        return self._lt.run(go())

    def write_all(self, path: str, data: bytes) -> None:
        self._lt.run(self._client.write_all(path, data))

    # ---------------- cluster ----------------

    def master_info(self):
        return self._lt.run(self._client.meta.master_info())

    def mount(self, cv_path: str, ufs_path: str, **kw):
        return self._lt.run(self._client.meta.mount(cv_path, ufs_path, **kw))

    def submit_load(self, path: str, recursive: bool = True) -> str:
        return self._lt.run(self._client.meta.submit_load(path, recursive))

    def job_status(self, job_id: str):
        return self._lt.run(self._client.meta.job_status(job_id))

    def close(self) -> None:
        try:
            self._lt.run(self._client.close())
        finally:
            self._lt.close()

    def __enter__(self) -> "CurvineFileSystem":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
