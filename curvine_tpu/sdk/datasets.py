"""Framework dataset adapters over the cache.

Parity: the reference's SDK integration points (libsdk consumed by
PyTorch/Ray loaders). Provides:
  * CurvineIterableDataset — torch.utils.data.IterableDataset streaming
    cached shards (worker-sharded for num_workers > 1);
  * jax_batches — synchronous numpy batch iterator for JAX input
    pipelines (pair with curvine_tpu.tpu.ingest.DevicePrefetcher).
"""

from __future__ import annotations

import numpy as np

from curvine_tpu.common.epoch import epoch_shard_order
from curvine_tpu.sdk.filesystem import CurvineFileSystem


def _list_shards(fs: CurvineFileSystem, path: str) -> list[str]:
    return sorted(s.path for s in fs.list_status(path) if not s.is_dir)


def next_epoch_order(fs: CurvineFileSystem, path: str,
                     shuffle_seed: int | None, epoch: int) -> list[str]:
    """Shard visit order for a given epoch.

    Public hook: callers (or the master's prefetch planner) can compute
    the *next* epoch's order ahead of time and warm the cache before the
    current epoch drains.  Same (seed, epoch) always yields the same
    permutation.
    """
    return epoch_shard_order(_list_shards(fs, path), shuffle_seed, epoch)


def jax_batches(fs: CurvineFileSystem, path: str, batch: int, seq_len: int,
                dtype=np.int32, shuffle_seed: int | None = None,
                epoch: int = 0):
    """Yield [batch, seq_len] numpy token batches from cached shards.

    The shard order is a deterministic per-epoch permutation seeded by
    (shuffle_seed, epoch): re-running the same epoch replays the same
    order, and the next epoch's order is computable in advance (see
    ``next_epoch_order``) so prefetch can run ahead of the cursor.
    """
    dtype = np.dtype(dtype)
    shards = epoch_shard_order(_list_shards(fs, path), shuffle_seed, epoch)
    per_batch = batch * seq_len
    carry = np.empty(0, dtype=dtype)
    for shard in shards:
        data = np.frombuffer(fs.read_all(shard), dtype=dtype)
        if carry.size:
            data = np.concatenate([carry, data])
        usable = (data.size // per_batch) * per_batch
        for off in range(0, usable, per_batch):
            yield data[off:off + per_batch].reshape(batch, seq_len)
        carry = data[usable:].copy()


try:
    import torch
    from torch.utils.data import IterableDataset, get_worker_info

    class CurvineIterableDataset(IterableDataset):
        """Streams samples from cached shard files; shards are split
        across DataLoader workers."""

        def __init__(self, master: str, path: str, sample_bytes: int,
                     dtype=np.uint8, transform=None):
            super().__init__()
            self.master = master
            self.path = path
            self.sample_bytes = sample_bytes
            self.dtype = np.dtype(dtype)
            self.transform = transform

        def __iter__(self):
            fs = CurvineFileSystem(master=self.master)
            try:
                shards = _list_shards(fs, self.path)
                info = get_worker_info()
                if info is not None:
                    shards = shards[info.id::info.num_workers]
                for shard in shards:
                    data = fs.read_all(shard)
                    n = len(data) // self.sample_bytes
                    for i in range(n):
                        raw = data[i * self.sample_bytes:
                                   (i + 1) * self.sample_bytes]
                        sample = torch.from_numpy(
                            np.frombuffer(raw, dtype=self.dtype).copy())
                        yield self.transform(sample) if self.transform \
                            else sample
            finally:
                fs.close()

except ImportError:  # pragma: no cover — torch is baked into this image
    CurvineIterableDataset = None  # type: ignore[assignment]
