"""ctypes binding for the native C-ABI SDK (csrc/sdk.cc).

Parity: curvine-libsdk — the reference ships a native SDK (JNI + PyO3)
built on its Rust client; `libcurvine_sdk.so` is the rebuild's native
client speaking the wire protocol directly (own msgpack codec, framed
TCP, block streaming), and this module is the Python face of its C ABI.
A Java JNI shim would bind the same ABI (no JVM in this image to compile
one — the C surface below is the contract it would wrap)."""

from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess

from curvine_tpu.common import errors as err

log = logging.getLogger(__name__)

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libcurvine_sdk.so")
_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO) and os.path.exists(
            os.path.join(_CSRC, "Makefile")):
        try:
            subprocess.run(["make", "-C", _CSRC], capture_output=True,
                           timeout=120, check=True)
        except Exception as e:  # noqa: BLE001 — stay gracefully absent
            log.debug("native sdk build failed: %s", e)
    if os.path.exists(_SO):
        lib = ctypes.CDLL(_SO)
        lib.cv_sdk_connect.restype = ctypes.c_void_p
        lib.cv_sdk_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_char_p]
        lib.cv_sdk_close.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_last_error.restype = ctypes.c_char_p
        lib.cv_sdk_last_error_code.restype = ctypes.c_int
        lib.cv_sdk_mkdir.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int64]
        lib.cv_sdk_get.restype = ctypes.c_int64
        lib.cv_sdk_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_void_p, ctypes.c_int64]
        lib.cv_sdk_len.restype = ctypes.c_int64
        lib.cv_sdk_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
        lib.cv_sdk_rename.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p]
        lib.cv_sdk_exists.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_list.restype = ctypes.c_void_p
        lib.cv_sdk_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_stat.restype = ctypes.c_void_p
        lib.cv_sdk_stat.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_free.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_open_reader.restype = ctypes.c_void_p
        lib.cv_sdk_open_reader.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_read.restype = ctypes.c_int64
        lib.cv_sdk_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                    ctypes.c_int64]
        lib.cv_sdk_seek.restype = ctypes.c_int64
        lib.cv_sdk_seek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.cv_sdk_reader_len.restype = ctypes.c_int64
        lib.cv_sdk_reader_len.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_reader_pos.restype = ctypes.c_int64
        lib.cv_sdk_reader_pos.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_close_reader.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_open_writer.restype = ctypes.c_void_p
        lib.cv_sdk_open_writer.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_int]
        lib.cv_sdk_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                     ctypes.c_int64]
        lib.cv_sdk_flush.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_writer_pos.restype = ctypes.c_int64
        lib.cv_sdk_writer_pos.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_close_writer.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativeCurvineClient:
    """Blocking native client: every byte of the protocol handled in C++
    (connect → mkdir/put/get/ls/stat/rename/delete)."""

    def __init__(self, host: str, port: int, user: str | None = None):
        lib = _load()
        if lib is None:
            raise err.Unsupported("libcurvine_sdk.so not built")
        self._lib = lib
        self._h = lib.cv_sdk_connect(host.encode(), port,
                                     (user or "").encode())
        if not self._h:
            raise err.ConnectError(self._err())

    def _err(self) -> str:
        return self._lib.cv_sdk_last_error().decode(errors="replace")

    def _raise(self):
        code = self._lib.cv_sdk_last_error_code()
        raise err.CurvineError.from_wire(code, self._err()) if code else \
            err.CurvineError(self._err())

    def _check(self, rc: int):
        if rc != 0:
            self._raise()

    def close(self) -> None:
        if self._h:
            self._lib.cv_sdk_close(self._h)
            self._h = None

    def mkdir(self, path: str) -> None:
        self._check(self._lib.cv_sdk_mkdir(self._h, path.encode()))

    def put(self, path: str, data: bytes) -> None:
        self._check(self._lib.cv_sdk_put(self._h, path.encode(), data,
                                         len(data)))

    def get(self, path: str) -> bytes:
        n = self.stat_len(path)
        if n < 0:
            # the typed remote error (FileNotFound vs a transport blip)
            # comes from the wire error_code — a network failure must NOT
            # masquerade as not-found
            self._raise()
        buf = ctypes.create_string_buffer(max(1, n))
        got = self._lib.cv_sdk_get(self._h, path.encode(), buf, n)
        if got < 0:
            self._raise()
        return buf.raw[:got]

    def stat_len(self, path: str) -> int:
        return self._lib.cv_sdk_len(self._h, path.encode())

    def exists(self, path: str) -> bool:
        rc = self._lib.cv_sdk_exists(self._h, path.encode())
        if rc < 0:
            self._raise()
        return rc == 1

    def delete(self, path: str, recursive: bool = False) -> None:
        self._check(self._lib.cv_sdk_delete(self._h, path.encode(),
                                            1 if recursive else 0))

    def rename(self, src: str, dst: str) -> None:
        self._check(self._lib.cv_sdk_rename(self._h, src.encode(),
                                            dst.encode()))

    def list(self, path: str) -> list[dict]:
        p = self._lib.cv_sdk_list(self._h, path.encode())
        if not p:
            raise err.CurvineError(self._err())
        try:
            return json.loads(ctypes.string_at(p).decode())
        finally:
            self._lib.cv_sdk_free(p)

    def stat(self, path: str) -> dict:
        p = self._lib.cv_sdk_stat(self._h, path.encode())
        if not p:
            self._raise()
        try:
            return json.loads(ctypes.string_at(p).decode())
        finally:
            self._lib.cv_sdk_free(p)

    def open_reader(self, path: str) -> "NativeReader":
        h = self._lib.cv_sdk_open_reader(self._h, path.encode())
        if not h:
            self._raise()
        return NativeReader(self, h)

    def open_writer(self, path: str,
                    overwrite: bool = True) -> "NativeWriter":
        h = self._lib.cv_sdk_open_writer(self._h, path.encode(),
                                         1 if overwrite else 0)
        if not h:
            self._raise()
        return NativeWriter(self, h)

    def __enter__(self) -> "NativeCurvineClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NativeReader:
    """Streaming file reader over a native handle (lib_fs_reader parity:
    read/seek/len on an open stream, block streams reopened at offset
    after a seek)."""

    def __init__(self, client: NativeCurvineClient, handle: int):
        self._c = client
        self._h = handle

    def _handle(self) -> int:
        if not self._h:
            raise ValueError("I/O operation on closed reader")
        return self._h

    def __len__(self) -> int:
        return self._c._lib.cv_sdk_reader_len(self._handle())

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = max(0, len(self) - self.tell())
        buf = ctypes.create_string_buffer(max(1, n))
        got = self._c._lib.cv_sdk_read(self._handle(), buf, n)
        if got < 0:
            self._c._raise()
        return buf.raw[:got]

    def tell(self) -> int:
        return self._c._lib.cv_sdk_reader_pos(self._handle())

    def seek(self, pos: int) -> int:
        rc = self._c._lib.cv_sdk_seek(self._handle(), pos)
        if rc < 0:
            self._c._raise()
        return rc

    def close(self) -> None:
        if self._h:
            self._c._lib.cv_sdk_close_reader(self._h)
            self._h = None

    def __enter__(self) -> "NativeReader":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class NativeWriter:
    """Streaming file writer over a native handle (lib_fs_writer parity);
    close() commits outstanding blocks and completes the file."""

    def __init__(self, client: NativeCurvineClient, handle: int):
        self._c = client
        self._h = handle

    def _handle(self) -> int:
        if not self._h:
            raise ValueError("I/O operation on closed writer")
        return self._h

    def write(self, data: bytes) -> int:
        if self._c._lib.cv_sdk_write(self._handle(), data, len(data)) != 0:
            self._c._raise()
        return len(data)

    def flush(self) -> None:
        if self._c._lib.cv_sdk_flush(self._handle()) != 0:
            self._c._raise()

    def tell(self) -> int:
        return self._c._lib.cv_sdk_writer_pos(self._handle())

    def close(self) -> None:
        if self._h:
            h, self._h = self._h, None
            if self._c._lib.cv_sdk_close_writer(h) != 0:
                self._c._raise()

    def __enter__(self) -> "NativeWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
