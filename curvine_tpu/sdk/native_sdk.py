"""ctypes binding for the native C-ABI SDK (csrc/sdk.cc).

Parity: curvine-libsdk — the reference ships a native SDK (JNI + PyO3)
built on its Rust client; `libcurvine_sdk.so` is the rebuild's native
client speaking the wire protocol directly (own msgpack codec, framed
TCP, block streaming), and this module is the Python face of its C ABI.
A Java JNI shim would bind the same ABI (no JVM in this image to compile
one — the C surface below is the contract it would wrap)."""

from __future__ import annotations

import ctypes
import json
import logging
import os
import subprocess

from curvine_tpu.common import errors as err

log = logging.getLogger(__name__)

_CSRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc")
_SO = os.path.join(_CSRC, "build", "libcurvine_sdk.so")
_lib = None
_tried = False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO) and os.path.exists(
            os.path.join(_CSRC, "Makefile")):
        try:
            subprocess.run(["make", "-C", _CSRC], capture_output=True,
                           timeout=120, check=True)
        except Exception as e:  # noqa: BLE001 — stay gracefully absent
            log.debug("native sdk build failed: %s", e)
    if os.path.exists(_SO):
        lib = ctypes.CDLL(_SO)
        lib.cv_sdk_connect.restype = ctypes.c_void_p
        lib.cv_sdk_connect.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                       ctypes.c_char_p]
        lib.cv_sdk_close.argtypes = [ctypes.c_void_p]
        lib.cv_sdk_last_error.restype = ctypes.c_char_p
        lib.cv_sdk_last_error_code.restype = ctypes.c_int
        lib.cv_sdk_mkdir.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_put.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_char_p, ctypes.c_int64]
        lib.cv_sdk_get.restype = ctypes.c_int64
        lib.cv_sdk_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_void_p, ctypes.c_int64]
        lib.cv_sdk_len.restype = ctypes.c_int64
        lib.cv_sdk_len.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int]
        lib.cv_sdk_rename.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_char_p]
        lib.cv_sdk_exists.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_list.restype = ctypes.c_void_p
        lib.cv_sdk_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.cv_sdk_free.argtypes = [ctypes.c_void_p]
        _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


class NativeCurvineClient:
    """Blocking native client: every byte of the protocol handled in C++
    (connect → mkdir/put/get/ls/stat/rename/delete)."""

    def __init__(self, host: str, port: int, user: str | None = None):
        lib = _load()
        if lib is None:
            raise err.Unsupported("libcurvine_sdk.so not built")
        self._lib = lib
        self._h = lib.cv_sdk_connect(host.encode(), port,
                                     (user or "").encode())
        if not self._h:
            raise err.ConnectError(self._err())

    def _err(self) -> str:
        return self._lib.cv_sdk_last_error().decode(errors="replace")

    def _raise(self):
        code = self._lib.cv_sdk_last_error_code()
        raise err.CurvineError.from_wire(code, self._err()) if code else \
            err.CurvineError(self._err())

    def _check(self, rc: int):
        if rc != 0:
            self._raise()

    def close(self) -> None:
        if self._h:
            self._lib.cv_sdk_close(self._h)
            self._h = None

    def mkdir(self, path: str) -> None:
        self._check(self._lib.cv_sdk_mkdir(self._h, path.encode()))

    def put(self, path: str, data: bytes) -> None:
        self._check(self._lib.cv_sdk_put(self._h, path.encode(), data,
                                         len(data)))

    def get(self, path: str) -> bytes:
        n = self.stat_len(path)
        if n < 0:
            # the typed remote error (FileNotFound vs a transport blip)
            # comes from the wire error_code — a network failure must NOT
            # masquerade as not-found
            self._raise()
        buf = ctypes.create_string_buffer(max(1, n))
        got = self._lib.cv_sdk_get(self._h, path.encode(), buf, n)
        if got < 0:
            self._raise()
        return buf.raw[:got]

    def stat_len(self, path: str) -> int:
        return self._lib.cv_sdk_len(self._h, path.encode())

    def exists(self, path: str) -> bool:
        rc = self._lib.cv_sdk_exists(self._h, path.encode())
        if rc < 0:
            self._raise()
        return rc == 1

    def delete(self, path: str, recursive: bool = False) -> None:
        self._check(self._lib.cv_sdk_delete(self._h, path.encode(),
                                            1 if recursive else 0))

    def rename(self, src: str, dst: str) -> None:
        self._check(self._lib.cv_sdk_rename(self._h, src.encode(),
                                            dst.encode()))

    def list(self, path: str) -> list[dict]:
        p = self._lib.cv_sdk_list(self._h, path.encode())
        if not p:
            raise err.CurvineError(self._err())
        try:
            return json.loads(ctypes.string_at(p).decode())
        finally:
            self._lib.cv_sdk_free(p)

    def __enter__(self) -> "NativeCurvineClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
