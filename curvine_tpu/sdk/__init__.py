from curvine_tpu.sdk.filesystem import CurvineFileSystem, CurvineFile

__all__ = ["CurvineFileSystem", "CurvineFile"]
