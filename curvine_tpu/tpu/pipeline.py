"""Pipeline parallelism (pp axis): GPipe-style stage pipeline.

Layers are stacked ([L, ...] leading dim) and sharded over the ``pp``
mesh axis so each chip owns L/S contiguous layers. Microbatches flow
through the ring: at step t, stage s computes microbatch t-s and
ppermutes its activations to stage s+1 — M + S - 1 steps total, the
classic bubble. Embedding/unembedding stay outside the pipelined region.

The scan/ppermute idiom follows the public TPU scaling recipe: shard_map
over the stage axis, static per-stage layer loop inside, collectives on
ICI only."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from curvine_tpu.tpu.model import ModelConfig, _block, _rmsnorm


def stack_layers(params: dict) -> dict:
    """[{k: w} per layer] → {k: [L, ...]} for pp sharding."""
    layers = params["layers"]
    stacked = {k: jnp.stack([layer[k] for layer in layers])
               for k in layers[0]}
    out = dict(params)
    out["layers"] = stacked
    return out


def stacked_specs(params_stacked: dict) -> dict:
    """PartitionSpecs: stacked layer weights sharded over 'pp' dim 0."""
    base = {"embed": P(None, None), "pos": P(None, None), "ln_f": P(None)}
    layer_specs = {k: P("pp", *([None] * (v.ndim - 1)))
                   for k, v in params_stacked["layers"].items()}
    return {**base, "layers": layer_specs}


def shard_stacked(params_stacked: dict, mesh: Mesh) -> dict:
    specs = stacked_specs(params_stacked)
    out = {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
           for k, v in params_stacked.items() if k != "layers"}
    out["layers"] = {
        k: jax.device_put(v, NamedSharding(mesh, specs["layers"][k]))
        for k, v in params_stacked["layers"].items()}
    return out


def pipeline_forward(params_stacked: dict, tokens, cfg: ModelConfig,
                     mesh: Mesh, microbatches: int = 2):
    """tokens [B, L] with B divisible by `microbatches` → logits [B, L, V].

    Stages = mesh.shape['pp']; cfg.n_layers must divide evenly."""
    S = mesh.shape["pp"]
    assert cfg.n_layers % S == 0, "n_layers must divide stages"
    per_stage = cfg.n_layers // S
    B, L = tokens.shape
    M = microbatches
    assert B % M == 0, "batch must divide microbatches"

    x = params_stacked["embed"][tokens] + params_stacked["pos"][:L]
    x = x.reshape(M, B // M, L, cfg.d_model)

    def stage_compute(layers_local, h):
        for i in range(per_stage):
            layer = {k: v[i] for k, v in layers_local.items()}
            h = _block(h, layer, cfg, None)
        return h

    def pipelined(layers_local, xs):
        stage = jax.lax.axis_index("pp")
        state = jnp.zeros_like(xs[0])
        out = jnp.zeros_like(xs)
        perm = [(i, (i + 1) % S) for i in range(S)]
        for t in range(M + S - 1):
            mb_in = jnp.clip(t, 0, M - 1)
            inp = jnp.where(stage == 0, xs[mb_in], state)
            h = stage_compute(layers_local, inp)
            done = t - (S - 1)
            if done >= 0:
                # only the last stage's value is real; mask others so the
                # replicating psum outside recovers it exactly
                mask = (stage == S - 1).astype(h.dtype)
                out = out.at[done].set(h * mask)
            state = jax.lax.ppermute(h, "pp", perm)
        return out

    layer_specs = {k: P("pp", *([None] * (v.ndim - 1)))
                   for k, v in params_stacked["layers"].items()}
    from curvine_tpu.tpu.mesh import shard_map_compat
    fn = shard_map_compat(
        pipelined, mesh=mesh,
        in_specs=(layer_specs, P()), out_specs=P("pp"))
    # out_specs P('pp') stacks each stage's masked buffer: [S*M, mb, L, D];
    # summing the stage axis recovers the last stage's outputs
    stacked_out = fn(params_stacked["layers"], x)
    stacked_out = stacked_out.reshape(S, M, B // M, L, cfg.d_model)
    x = jnp.sum(stacked_out, axis=0).reshape(B, L, cfg.d_model)

    x = _rmsnorm(x, params_stacked["ln_f"])
    return (x @ params_stacked["embed"].T).astype(jnp.float32)


def pipeline_loss(params_stacked, tokens, cfg: ModelConfig, mesh: Mesh,
                  microbatches: int = 2):
    logits = pipeline_forward(params_stacked, tokens, cfg, mesh,
                              microbatches)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)
