"""Demo training consumer: a pure-JAX decoder-only transformer LM.

This is the flagship compute consumer of the cache (fed by
curvine_tpu.tpu.loader): bf16 matmuls for the MXU, TP×DP×SP sharding via
NamedSharding + jit (XLA inserts the collectives), ring attention
(shard_map/ppermute) for the long-context path, optax AdamW training step.

Sharding recipe (Megatron-style TP over the ``model`` axis):
  embed [V, D]        → P(None, 'model')
  wq/wk/wv [D, D]     → P(None, 'model')   (heads sharded)
  wo [D, D]           → P('model', None)
  mlp w1 [D, F]       → P(None, 'model')
  mlp w2 [F, D]       → P('model', None)
  activations [B,L,D] → P('data', 'seq', None)
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from curvine_tpu.tpu.ring_attention import dense_attention, ring_attention_sharded


@dataclass(frozen=True)
class ModelConfig:
    vocab: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 2048
    max_seq: int = 2048
    dtype: str = "bfloat16"
    use_ring_attention: bool = False
    remat: bool = False        # jax.checkpoint each layer (HBM for FLOPs)
    moe_experts: int = 0       # >0: MoE FFN, experts sharded over 'ep'
    # Fused (Pallas) flash attention on TPU: no [B,H,L,L] score
    # materialization, O(L) memory. Requires head_dim % 128 == 0 and
    # seq % 128 == 0; anything else falls back to dense_attention.
    use_flash_attention: bool = False
    # Cross-entropy in chunks of this many tokens (0 = one-shot): the
    # [B·L, vocab] f32 logits never materialize — each chunk's logits
    # are rematerialized in the backward pass. At vocab 32K, seq 1K the
    # one-shot path peaks >1 GiB of HBM in pure loss bookkeeping.
    ce_chunk: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def jax_dtype(self):
        return jnp.dtype(self.dtype)

    @staticmethod
    def tiny() -> "ModelConfig":
        return ModelConfig(vocab=256, d_model=64, n_heads=4, n_layers=2,
                           d_ff=128, max_seq=128)


def init_params(rng, cfg: ModelConfig) -> dict:
    dt = cfg.jax_dtype()
    keys = jax.random.split(rng, 2 + cfg.n_layers)

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape) / np.sqrt(fan_in)).astype(dt)

    layers = []
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[2 + i], 7)
        layer = {
            "ln1": jnp.ones(cfg.d_model, dt),
            "wq": dense(k[0], cfg.d_model, (cfg.d_model, cfg.d_model)),
            "wk": dense(k[1], cfg.d_model, (cfg.d_model, cfg.d_model)),
            "wv": dense(k[2], cfg.d_model, (cfg.d_model, cfg.d_model)),
            "wo": dense(k[3], cfg.d_model, (cfg.d_model, cfg.d_model)),
            "ln2": jnp.ones(cfg.d_model, dt),
        }
        if cfg.moe_experts > 0:
            E = cfg.moe_experts
            layer["router"] = dense(k[6], cfg.d_model, (cfg.d_model, E))
            layer["ew1"] = dense(k[4], cfg.d_model,
                                 (E, cfg.d_model, cfg.d_ff))
            layer["ew2"] = dense(k[5], cfg.d_ff, (E, cfg.d_ff, cfg.d_model))
        else:
            layer["w1"] = dense(k[4], cfg.d_model, (cfg.d_model, cfg.d_ff))
            layer["w2"] = dense(k[5], cfg.d_ff, (cfg.d_ff, cfg.d_model))
        layers.append(layer)
    return {
        "embed": dense(keys[0], cfg.d_model, (cfg.vocab, cfg.d_model)),
        "pos": dense(keys[1], cfg.d_model, (cfg.max_seq, cfg.d_model)),
        "ln_f": jnp.ones(cfg.d_model, dt),
        "layers": layers,
    }


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _flash_eligible(cfg: ModelConfig, L: int) -> bool:
    return (cfg.use_flash_attention
            and jax.default_backend() == "tpu"
            and cfg.head_dim % 128 == 0
            and L % 128 == 0)


def _flash_attention(q, k, v):
    """Pallas TPU fused attention (public jax.experimental kernel):
    online-softmax tiles in VMEM, never materializing the [B,H,L,L]
    score matrix — the single biggest activation sink of the dense
    path at seq 1K+."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention,
    )
    return flash_attention(q, k, v, causal=True,
                           sm_scale=1.0 / float(np.sqrt(q.shape[-1])))


def _attention(x, layer, cfg: ModelConfig, mesh: Mesh | None):
    B, L, D = x.shape
    H, hd = cfg.n_heads, cfg.head_dim
    q = (x @ layer["wq"]).reshape(B, L, H, hd).transpose(0, 2, 1, 3)
    k = (x @ layer["wk"]).reshape(B, L, H, hd).transpose(0, 2, 1, 3)
    v = (x @ layer["wv"]).reshape(B, L, H, hd).transpose(0, 2, 1, 3)
    if cfg.use_ring_attention and mesh is not None and "seq" in mesh.axis_names:
        o = ring_attention_sharded(q, k, v, mesh, axis_name="seq", causal=True)
    elif _flash_eligible(cfg, L):
        o = _flash_attention(q, k, v)
    else:
        o = dense_attention(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(B, L, D)
    return o @ layer["wo"]


def _moe_ffn(x, layer, cfg: ModelConfig):
    """Expert-parallel FFN: experts sharded over the 'ep' mesh axis
    (weights P('ep', …)); XLA partitions the expert einsums across chips
    and inserts the combine all-reduce over 'ep'. Soft top-2 routing —
    dense compute, the sharding/collective pattern of EP without the
    dynamic-dispatch complexity (honest demo-scale MoE)."""
    gates = jax.nn.softmax(
        (x @ layer["router"]).astype(jnp.float32), axis=-1)
    # keep top-2 gates, renormalize (still differentiable & static-shape)
    top2 = jax.lax.top_k(gates, 2)[0][..., -1:]
    gates = jnp.where(gates >= top2, gates, 0.0)
    gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    h = jnp.einsum("bld,edf->belf", x, layer["ew1"])
    h = jax.nn.gelu(h)
    y = jnp.einsum("belf,efd->beld", h, layer["ew2"])
    return jnp.einsum("beld,ble->bld", y, gates.astype(x.dtype))


def _block(x, layer, cfg: ModelConfig, mesh: Mesh | None):
    x = x + _attention(_rmsnorm(x, layer["ln1"]), layer, cfg, mesh)
    h = _rmsnorm(x, layer["ln2"])
    if cfg.moe_experts > 0:
        h = _moe_ffn(h, layer, cfg)
    else:
        h = jax.nn.gelu(h @ layer["w1"]) @ layer["w2"]
    return x + h


def forward_hidden(params: dict, tokens, cfg: ModelConfig,
                   mesh: Mesh | None = None):
    """tokens [B, L] int32 → final hidden states [B, L, D] (model dtype)."""
    B, L = tokens.shape
    x = params["embed"][tokens] + params["pos"][:L]
    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(2,))
    for layer in params["layers"]:
        x = block(x, layer, cfg, mesh)
    return _rmsnorm(x, params["ln_f"])


def forward(params: dict, tokens, cfg: ModelConfig,
            mesh: Mesh | None = None):
    """tokens [B, L] int32 → logits [B, L, V] (dtype f32)."""
    x = forward_hidden(params, tokens, cfg, mesh)
    return (x @ params["embed"].T).astype(jnp.float32)


def _chunked_ce(x, targets, embed, chunk: int):
    """Cross entropy over [N, D] hidden states in `chunk`-token slices:
    each slice's [chunk, V] f32 logits live only inside its (remat'd)
    scan step, so peak loss memory is one chunk instead of the whole
    batch. targets < 0 are padding and contribute nothing."""
    N, D = x.shape
    pad = (-N) % chunk
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        targets = jnp.pad(targets, (0, pad), constant_values=-1)
    xc = x.reshape(-1, chunk, D)
    tc = targets.reshape(-1, chunk)
    emb_t = embed.T

    def step(total, xt):
        xs, ts = xt
        logits = (xs @ emb_t).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, jnp.maximum(ts, 0)[:, None], axis=-1)[:, 0]
        return total + jnp.sum(jnp.where(ts >= 0, nll, 0.0)), None

    total, _ = jax.lax.scan(jax.checkpoint(step), jnp.float32(0.0), (xc, tc))
    return total / N


def loss_fn(params, tokens, cfg: ModelConfig, mesh: Mesh | None = None):
    """Next-token cross entropy; last position predicts nothing."""
    x = forward_hidden(params, tokens, cfg, mesh)
    targets = tokens[:, 1:]
    x = x[:, :-1]
    if cfg.ce_chunk > 0:
        return _chunked_ce(x.reshape(-1, x.shape[-1]),
                           targets.reshape(-1),
                           params["embed"], cfg.ce_chunk)
    logits = (x @ params["embed"].T).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_optimizer(lr: float = 3e-4):
    return optax.adamw(lr, weight_decay=0.01)


def make_train_step(cfg: ModelConfig, optimizer=None,
                    mesh: Mesh | None = None):
    optimizer = optimizer or make_optimizer()

    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg, mesh)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


# ---------------- shardings ----------------

_PARAM_SPECS = {
    "embed": P(None, "model"),
    "pos": P(None, None),
    "ln_f": P(None),
    "ln1": P(None), "ln2": P(None),
    "wq": P(None, "model"), "wk": P(None, "model"), "wv": P(None, "model"),
    "wo": P("model", None),
    "w1": P(None, "model"), "w2": P("model", None),
    # MoE: experts sharded over 'ep'
    "router": P(None, None),
    "ew1": P("ep", None, None), "ew2": P("ep", None, None),
}


def param_spec_tree(params: dict) -> dict:
    """PartitionSpec pytree matching init_params structure."""
    def spec_of(path_leaf):
        return _PARAM_SPECS.get(path_leaf, P())

    return {
        "embed": spec_of("embed"), "pos": spec_of("pos"),
        "ln_f": spec_of("ln_f"),
        "layers": [{k: spec_of(k) for k in layer}
                   for layer in params["layers"]],
    }


def _sanitize(spec: P, mesh: Mesh) -> P:
    """Drop axes the mesh doesn't have (e.g. 'ep' on a dp×tp mesh)."""
    return P(*(a if a in mesh.axis_names else None for a in spec))


def shard_params(params: dict, mesh: Mesh) -> dict:
    specs = param_spec_tree(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, _sanitize(s, mesh))),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array))


def batch_spec(mesh: Mesh) -> P:
    """tokens [B, L]: batch over data, seq over seq (when present)."""
    seq = "seq" if "seq" in mesh.axis_names else None
    return P("data", seq)
