"""HBM tier-0: device-resident block cache.

The TPU-native extension over the reference's MEM/SSD/HDD tiers: hot
blocks live in TPU HBM as uint8 jax.Arrays, so a training step's input
fetch is an on-device slice instead of a host→device copy. Capacity is
accounted explicitly; LRU spills back to the host tier (the DRAM tier
keeps the backing file, so spilling is just dropping the device copy)."""

from __future__ import annotations

import logging
import time

import jax
import numpy as np

log = logging.getLogger(__name__)


class HbmExportTable:
    """Peer-addressable view of the HBM tier: block_id → device buffer
    descriptor, advertised in heartbeats and GET_BLOCK_INFO so an
    ICI-adjacent peer can source the replica device-to-device instead of
    re-pulling bytes over TCP (tpu/ici_plane.py).

    Bounded LRU, mirroring the shm-export table (worker/shm.py): the
    advertisement is capability metadata, not ownership — dropping an
    entry only stops advertising; the tier still holds the block."""

    def __init__(self, cap: int = 128):
        from collections import OrderedDict
        self.cap = max(1, int(cap))
        self._entries: "OrderedDict[int, dict]" = OrderedDict()
        self.exports = 0        # lifetime advertisements
        self.evictions = 0      # LRU pressure on the table itself

    def add(self, block_id: int, device_id: int, arr) -> None:
        e = {"device_id": int(device_id),
             "shape": list(arr.shape),
             "dtype": str(arr.dtype),
             "nbytes": int(arr.nbytes)}
        if block_id in self._entries:
            self._entries.pop(block_id)
        elif len(self._entries) >= self.cap:
            self._entries.popitem(last=False)
            self.evictions += 1
        self._entries[block_id] = e
        self.exports += 1

    def remove(self, block_id: int) -> None:
        self._entries.pop(block_id, None)

    def get(self, block_id: int) -> dict | None:
        e = self._entries.get(block_id)
        if e is not None:
            self._entries.move_to_end(block_id)
        return e

    def __contains__(self, block_id: int) -> bool:
        return block_id in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """Most-recently-exported first, bounded — the heartbeat payload."""
        out = []
        for bid in reversed(self._entries):
            if limit is not None and len(out) >= limit:
                break
            out.append({"block_id": bid, **self._entries[bid]})
        return out


class HbmTier:
    def __init__(self, capacity_bytes: int, device=None,
                 admission: str = "lru", ghost_entries: int = 2048,
                 exports: HbmExportTable | None = None, policy=None):
        from curvine_tpu.common.cache import make_policy
        self.capacity = capacity_bytes
        self.device = device if device is not None else jax.devices()[0]
        self.used = 0
        self._blocks: dict[int, jax.Array] = {}
        self._atime: dict[int, float] = {}
        self.hits = 0
        self.misses = 0
        self.spills = 0
        # peer-addressable advertisement (shared across chips under
        # MultiHbmTier); None → tier is private, nothing advertised
        self.exports = exports
        # ghost-cache admission (common/cache.py): HBM is the scarcest
        # tier of all — an autopin sweep over a cold scan must not spill
        # the hot training blocks, so s3fifo protection applies here too.
        # An injected shared policy (MultiHbmTier) lets a block evicted
        # on one chip re-admit straight to main on ANY chip.
        self.policy = policy if policy is not None else \
            make_policy(admission, ghost_entries=ghost_entries)

    def __contains__(self, block_id: int) -> bool:
        return block_id in self._blocks

    def put(self, block_id: int, data) -> jax.Array:
        """Pin a block (bytes / numpy view) into HBM. Zero-copy on the host
        side: a numpy view (e.g. the client's mmap_view) is handed straight
        to device_put."""
        if block_id in self._blocks:
            self._atime[block_id] = time.monotonic()
            self.policy.on_access(block_id)
            return self._blocks[block_id]
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else data
        need = arr.nbytes
        if need > self.capacity:
            raise ValueError(f"block of {need}B exceeds HBM tier capacity")
        self._evict_for(need)
        dev_arr = jax.device_put(arr, self.device)
        self._blocks[block_id] = dev_arr
        self._atime[block_id] = time.monotonic()
        self.used += need
        self.policy.on_admit(block_id, need)
        if self.exports is not None:
            self.exports.add(block_id, self.device.id, dev_arr)
        return dev_arr

    def get(self, block_id: int) -> jax.Array | None:
        arr = self._blocks.get(block_id)
        if arr is None:
            self.misses += 1
            self.policy.misses += 1
            return None
        self.hits += 1
        self.policy.hits += 1
        self._atime[block_id] = time.monotonic()
        self.policy.on_access(block_id)
        return arr

    def drop(self, block_id: int, evicted: bool = False) -> None:
        arr = self._blocks.pop(block_id, None)
        self._atime.pop(block_id, None)
        if arr is not None:
            self.policy.on_remove(block_id, evicted=evicted)
            if self.exports is not None:
                self.exports.remove(block_id)
            self.used -= arr.nbytes
            arr.delete()

    def _evict_for(self, need: int) -> None:
        while self.used + need > self.capacity and self._blocks:
            order = self.policy.victim_order(list(self._atime.items()))
            victim = order[0] if order else min(self._atime,
                                                key=self._atime.get)
            log.debug("hbm tier evicting block %d", victim)
            self.spills += 1
            self.drop(victim, evicted=True)

    def stats(self) -> dict:
        ps = self.policy.stats()
        return {"capacity": self.capacity, "used": self.used,
                "blocks": len(self._blocks), "hits": self.hits,
                "misses": self.misses, "spills": self.spills,
                "ghost_hits": ps.get("ghost_hits", 0),
                "scan_evicted": ps.get("scan_evicted", 0)}


class MultiHbmTier:
    """HBM tier-0 across ALL local chips of a TPU host (a v5e host drives
    4-8). One HbmTier per device with independent capacity accounting;
    placement picks the least-used chip (or an explicit target), and hot
    blocks can be spread as replicas across chips so every consumer
    reads HBM-locally instead of crossing PCIe or ICI.

    This is the multi-chip completion of the round-2 single-device tier
    (which bound jax.devices()[0] only)."""

    def __init__(self, capacity_bytes: int, devices=None,
                 admission: str = "lru", ghost_entries: int = 2048,
                 export_cap: int = 128):
        """``capacity_bytes`` is the TOTAL HBM budget for the tier (the
        operator's `worker.hbm_capacity`), split evenly across the local
        chips — same semantics as the round-2 single-device tier, so the
        advertised capacity doesn't silently multiply by chip count."""
        from curvine_tpu.common.cache import make_policy
        devices = devices if devices is not None else jax.local_devices()
        if not devices:
            raise ValueError("no local devices for the HBM tier")
        per_chip = max(1, capacity_bytes // len(devices))
        # ONE admission policy and ONE export table across all chips:
        # the ghost queue must be tier-wide (a block evicted on chip A
        # and re-broadcast onto chip B is the same hot block — it
        # re-admits straight to main), and peers address the worker's
        # HBM tier as a whole, not a chip
        self.policy = make_policy(admission, ghost_entries=ghost_entries)
        self.exports = HbmExportTable(cap=export_cap)
        self.tiers: dict = {d.id: HbmTier(per_chip, device=d,
                                          exports=self.exports,
                                          policy=self.policy)
                            for d in devices}
        self.devices = list(devices)

    # ---- capacity (per chip, for heartbeat advertisement) ----
    @property
    def capacity(self) -> int:
        return sum(t.capacity for t in self.tiers.values())

    @property
    def used(self) -> int:
        return sum(t.used for t in self.tiers.values())

    def per_device_stats(self) -> list[dict]:
        return [{"device_id": did, **t.stats()}
                for did, t in sorted(self.tiers.items())]

    # ---- placement ----
    def _pick(self) -> "HbmTier":
        return min(self.tiers.values(), key=lambda t: t.used)

    def _tier_of(self, device) -> "HbmTier":
        did = getattr(device, "id", device)
        t = self.tiers.get(did)
        if t is None:
            raise ValueError(f"device {did} is not part of the HBM tier")
        return t

    def put(self, block_id: int, data, device=None) -> jax.Array:
        """Pin on one chip: the consumer's chip when given, else the
        least-used chip (capacity-balanced placement)."""
        for t in self.tiers.values():         # already resident somewhere?
            if block_id in t:
                if device is None or getattr(device, "id", device) == \
                        t.device.id:
                    return t.get(block_id)
        t = self._tier_of(device) if device is not None else self._pick()
        try:
            return t.put(block_id, data)
        except ValueError as e:
            # hbm_capacity is the TOTAL budget split over len(tiers)
            # chips; a block can only live on ONE chip, so the per-chip
            # share is the real ceiling — make that actionable
            raise ValueError(
                f"{e} (per-chip share: {t.capacity}B = total hbm_capacity "
                f"/ {len(self.tiers)} chips — raise worker.hbm_capacity "
                f"or use a smaller block_size)") from e

    def put_replicated(self, block_id: int, data, k: int | None = None
                       ) -> list[jax.Array]:
        """Spread a hot block as replicas across k chips (all local chips
        by default) — every consumer then reads its own HBM copy. Replica
        chips are chosen least-used-first (ICI-local by construction:
        local_devices share the host's ICI neighborhood)."""
        targets = sorted(self.tiers.values(), key=lambda t: t.used)
        targets = targets[:k if k is not None else len(targets)]
        return [t.put(block_id, data) for t in targets]

    def get(self, block_id: int, device=None) -> jax.Array | None:
        """Prefer the copy on `device` (HBM-local read); fall back to any
        chip holding it."""
        if device is not None:
            t = self.tiers.get(getattr(device, "id", device))
            if t is not None and block_id in t:
                return t.get(block_id)
        for t in self.tiers.values():
            if block_id in t:
                return t.get(block_id)
        return None

    def holders(self, block_id: int) -> list[int]:
        return [did for did, t in sorted(self.tiers.items())
                if block_id in t]

    def drop(self, block_id: int, evicted: bool = False) -> None:
        """``evicted=True`` marks a capacity/pressure drop: the shared
        ghost queue remembers the block so a re-broadcast re-admits
        straight to main. Master-commanded deletes stay evicted=False —
        a deleted block must NOT enjoy fast re-admission."""
        for t in self.tiers.values():
            t.drop(block_id, evicted=evicted)

    def __contains__(self, block_id: int) -> bool:
        return any(block_id in t for t in self.tiers.values())

    def stats(self) -> dict:
        # policy counters come off the ONE shared policy — per-tier
        # sums would multiply-count it by chip count
        ps = self.policy.stats()
        agg = {"capacity": self.capacity, "used": self.used,
               "devices": len(self.tiers),
               "blocks": len({b for t in self.tiers.values()
                              for b in t._blocks}),
               "hits": sum(t.hits for t in self.tiers.values()),
               "misses": sum(t.misses for t in self.tiers.values()),
               "spills": sum(t.spills for t in self.tiers.values()),
               "ghost_hits": ps.get("ghost_hits", 0),
               "scan_evicted": ps.get("scan_evicted", 0),
               "exports": len(self.exports),
               "export_adds": self.exports.exports}
        agg["per_device"] = self.per_device_stats()
        return agg


def export_metrics(tier, registry, prefix: str = "hbm") -> None:
    """Surface HbmTier/MultiHbmTier counters on a MetricsRegistry
    (/metrics): hits, misses, spills, occupancy. Counted since round 2,
    but never exported until now."""
    st = tier.stats()
    registry.gauge(f"{prefix}.hits", st.get("hits", 0))
    registry.gauge(f"{prefix}.misses", st.get("misses", 0))
    registry.gauge(f"{prefix}.spills", st.get("spills", 0))
    registry.gauge(f"{prefix}.ghost_hits", st.get("ghost_hits", 0))
    registry.gauge(f"{prefix}.scan_evicted", st.get("scan_evicted", 0))
    registry.gauge(f"{prefix}.used", st["used"])
    registry.gauge(f"{prefix}.capacity", st["capacity"])
    registry.gauge(f"{prefix}.occupancy",
                   st["used"] / st["capacity"] if st["capacity"] else 0.0)
