"""HBM tier-0: device-resident block cache.

The TPU-native extension over the reference's MEM/SSD/HDD tiers: hot
blocks live in TPU HBM as uint8 jax.Arrays, so a training step's input
fetch is an on-device slice instead of a host→device copy. Capacity is
accounted explicitly; LRU spills back to the host tier (the DRAM tier
keeps the backing file, so spilling is just dropping the device copy)."""

from __future__ import annotations

import logging
import time

import jax
import numpy as np

log = logging.getLogger(__name__)


class HbmTier:
    def __init__(self, capacity_bytes: int, device=None):
        self.capacity = capacity_bytes
        self.device = device if device is not None else jax.devices()[0]
        self.used = 0
        self._blocks: dict[int, jax.Array] = {}
        self._atime: dict[int, float] = {}
        self.hits = 0
        self.misses = 0

    def __contains__(self, block_id: int) -> bool:
        return block_id in self._blocks

    def put(self, block_id: int, data) -> jax.Array:
        """Pin a block (bytes / numpy view) into HBM. Zero-copy on the host
        side: a numpy view (e.g. the client's mmap_view) is handed straight
        to device_put."""
        if block_id in self._blocks:
            self._atime[block_id] = time.monotonic()
            return self._blocks[block_id]
        arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
            data, (bytes, bytearray, memoryview)) else data
        need = arr.nbytes
        if need > self.capacity:
            raise ValueError(f"block of {need}B exceeds HBM tier capacity")
        self._evict_for(need)
        dev_arr = jax.device_put(arr, self.device)
        self._blocks[block_id] = dev_arr
        self._atime[block_id] = time.monotonic()
        self.used += need
        return dev_arr

    def get(self, block_id: int) -> jax.Array | None:
        arr = self._blocks.get(block_id)
        if arr is None:
            self.misses += 1
            return None
        self.hits += 1
        self._atime[block_id] = time.monotonic()
        return arr

    def drop(self, block_id: int) -> None:
        arr = self._blocks.pop(block_id, None)
        self._atime.pop(block_id, None)
        if arr is not None:
            self.used -= arr.nbytes
            arr.delete()

    def _evict_for(self, need: int) -> None:
        while self.used + need > self.capacity and self._blocks:
            victim = min(self._atime, key=self._atime.get)
            log.debug("hbm tier evicting block %d", victim)
            self.drop(victim)

    def stats(self) -> dict:
        return {"capacity": self.capacity, "used": self.used,
                "blocks": len(self._blocks), "hits": self.hits,
                "misses": self.misses}
