"""Device mesh + ICI topology helpers.

The sharding design follows the standard TPU recipe: pick a Mesh, annotate
array shardings with NamedSharding/PartitionSpec, let XLA insert the
collectives, keep collectives on ICI by putting the fast-varying axes
innermost. Axes used across the framework:

  data  — batch (DP): gradients all-reduced over this axis
  model — hidden/heads (TP): matmul-sharded, activations all-gathered
  seq   — sequence (SP/context parallel): ring attention ppermutes KV here
"""

from __future__ import annotations

import math

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map_compat(fn, mesh, in_specs, out_specs, replication_ok=True):
    """shard_map across JAX generations: `jax.shard_map(check_vma=...)`
    (new API) when present, `jax.experimental.shard_map.shard_map(
    check_rep=...)` otherwise — same semantics, renamed kwarg."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=not replication_ok)
    from jax.experimental.shard_map import shard_map as sm
    return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
              check_rep=not replication_ok)


def factor_mesh(n: int, axes: int = 2) -> tuple[int, ...]:
    """Balanced near-square factorization of n devices into `axes` dims,
    larger factor first (data axis gets the larger share)."""
    if axes == 1:
        return (n,)
    best = (n, 1)
    for d in range(1, int(math.isqrt(n)) + 1):
        if n % d == 0:
            best = (n // d, d)
    if axes == 2:
        return best
    rest = factor_mesh(best[1], axes - 1)
    return (best[0], *rest)


def make_mesh(devices=None, axis_names: tuple[str, ...] = ("data", "model"),
              shape: tuple[int, ...] | None = None) -> Mesh:
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if shape is None:
        shape = factor_mesh(n, len(axis_names))
    if math.prod(shape) != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    dev_array = np.array(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over `axis`."""
    return NamedSharding(mesh, P(axis))


class IciTopology:
    """Model of a TPU pod's ICI torus used for placement decisions.

    Hosts own contiguous sub-blocks of chips; workers co-located with a
    host inherit its coordinates (WorkerInfo.ici_coords). The master's
    ``ici`` placement policy (curvine_tpu/master/placement.py) uses
    ``hops`` as its distance metric."""

    def __init__(self, mesh_shape: tuple[int, ...],
                 chips_per_host: int = 4):
        self.mesh_shape = tuple(mesh_shape)
        self.chips_per_host = chips_per_host

    def num_chips(self) -> int:
        return math.prod(self.mesh_shape)

    def num_hosts(self) -> int:
        return max(1, self.num_chips() // self.chips_per_host)

    def coords_of(self, chip_index: int) -> tuple[int, ...]:
        coords = []
        rest = chip_index
        for dim in reversed(self.mesh_shape):
            coords.append(rest % dim)
            rest //= dim
        return tuple(reversed(coords))

    def host_of(self, chip_index: int) -> int:
        return chip_index // self.chips_per_host

    def host_coords(self, host_index: int) -> tuple[int, ...]:
        return self.coords_of(host_index * self.chips_per_host)

    def hops(self, a: tuple[int, ...], b: tuple[int, ...]) -> int:
        total = 0
        for i, (x, y) in enumerate(zip(a, b)):
            d = abs(x - y)
            dim = self.mesh_shape[i]
            total += min(d, dim - d)   # torus wraparound
        return total
