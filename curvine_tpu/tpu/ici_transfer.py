"""Block replica movement over the ICI mesh.

The reference moves block replicas worker→worker over TCP/RDMA (orpc
zero-copy transport). On a TPU pod, HBM-resident replicas move
device-to-device over ICI instead: XLA routes `device_put` between
devices and resharding collectives (all-gather / scatter) over the ICI
links without touching the host. These helpers are the HBM-tier
counterpart of worker replication (curvine_tpu/master/replication.py
stays the host-tier path)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate_to_devices(arr: jax.Array, devices: list) -> list[jax.Array]:
    """Copy an HBM-resident block to each target device (ICI d2d copies;
    never staged through the host)."""
    return [arr if d in arr.devices() else jax.device_put(arr, d)
            for d in devices]


def scatter_block(arr, mesh: Mesh, axis: str | None = None) -> jax.Array:
    """Spread a block across the mesh — each chip holds 1/N of the bytes
    (striped model distribution: N chips pull N× faster, then all_gather
    on demand)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    pad = (-len(arr)) % n
    if pad:
        arr = np.pad(np.asarray(arr), (0, pad)) if isinstance(
            arr, np.ndarray) else jnp.pad(arr, (0, pad))
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def gather_block(sharded: jax.Array, mesh: Mesh) -> jax.Array:
    """Re-replicate a scattered block: XLA emits an all-gather over ICI."""
    return jax.device_put(sharded, NamedSharding(mesh, P()))


def broadcast_block(host_block, mesh: Mesh) -> jax.Array:
    """Host bytes → every chip. Scatter first (each chip receives 1/N over
    the host link), then all-gather over ICI — the standard fast-broadcast
    recipe for model distribution (beats N full host→device copies)."""
    scattered = scatter_block(host_block, mesh)
    return gather_block(scattered, mesh)
