"""Block replica movement over the ICI mesh.

The reference moves block replicas worker→worker over TCP/RDMA (orpc
zero-copy transport). On a TPU pod, HBM-resident replicas move
device-to-device over ICI instead: XLA routes `device_put` between
devices and resharding collectives (all-gather / scatter) over the ICI
links without touching the host. These helpers are the HBM-tier
counterpart of worker replication (curvine_tpu/master/replication.py
stays the host-tier path)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicate_to_devices(arr: jax.Array, devices: list) -> list[jax.Array]:
    """Copy an HBM-resident block to each target device (ICI d2d copies;
    never staged through the host)."""
    return [arr if d in arr.devices() else jax.device_put(arr, d)
            for d in devices]


def scatter_block(arr, mesh: Mesh, axis: str | None = None) -> jax.Array:
    """Spread a block across the mesh — each chip holds 1/N of the bytes
    (striped model distribution: N chips pull N× faster, then all_gather
    on demand)."""
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    pad = (-len(arr)) % n
    if pad:
        arr = np.pad(np.asarray(arr), (0, pad)) if isinstance(
            arr, np.ndarray) else jnp.pad(arr, (0, pad))
    return jax.device_put(arr, NamedSharding(mesh, P(axis)))


def gather_block(sharded: jax.Array, mesh: Mesh) -> jax.Array:
    """Re-replicate a scattered block: XLA emits an all-gather over ICI."""
    return jax.device_put(sharded, NamedSharding(mesh, P()))


def broadcast_block(host_block, mesh: Mesh) -> jax.Array:
    """Host bytes → every chip. Scatter first (each chip receives 1/N over
    the host link), then all-gather over ICI — the standard fast-broadcast
    recipe for model distribution (beats N full host→device copies)."""
    scattered = scatter_block(host_block, mesh)
    return gather_block(scattered, mesh)


# jitted collectives cached per (mesh, axis, …): rebuilding shard_map +
# jax.jit per call would retrace/recompile on EVERY rebalance event —
# the ppermute itself is microseconds, a retrace is ~100ms+
_SHIFT_FNS: dict = {}
_SUM_FNS: dict = {}


def ring_shift(sharded: jax.Array, mesh: Mesh, axis: str | None = None,
               steps: int = 1) -> jax.Array:
    """Rotate block shards one (or `steps`) hop around the ICI ring:
    chip i's shard moves to chip (i+steps) % N via ppermute — the
    neighbor-transfer primitive under HBM-tier replica rebalancing
    (replicas spread to adjacent chips at link speed, no host hop, no
    full all-gather). Numerics: shard k of the result equals shard
    (k-steps) % N of the input."""
    from curvine_tpu.tpu.mesh import shard_map_compat

    axis = axis or mesh.axis_names[0]
    key = (mesh, axis, steps, sharded.ndim)
    fn = _SHIFT_FNS.get(key)
    if fn is None:
        n = mesh.shape[axis]
        perm = [(i, (i + steps) % n) for i in range(n)]
        spec = P(axis, *([None] * (sharded.ndim - 1)))

        def shift(x):
            return jax.lax.ppermute(x, axis, perm)

        fn = _SHIFT_FNS[key] = jax.jit(
            shard_map_compat(shift, mesh, spec, spec))
    return fn(sharded)


def reshard_stripes(sharded: jax.Array, mesh: Mesh, from_axis: str,
                    to_axis: str) -> jax.Array:
    """Move a block's striping from one mesh axis to another (e.g. the
    'data' ring to the 'model' ring when a consumer wants model-parallel
    locality) without re-staging through the host: one device_put with
    the target NamedSharding — XLA lowers it to the ICI all-to-all /
    collective-permute pattern for the reshard. `from_axis` is
    validated against the input's actual sharding (a wrong caller
    assumption must fail loudly, not silently reshard from elsewhere)."""
    got = getattr(sharded.sharding, "spec", None)
    if got is not None and len(got) and got[0] != from_axis:
        raise ValueError(
            f"input striped over {got[0]!r}, not from_axis={from_axis!r}")
    tail = [None] * (sharded.ndim - 1)
    return jax.device_put(sharded, NamedSharding(mesh, P(to_axis, *tail)))


def verify_scattered(sharded: jax.Array, mesh: Mesh,
                     axis: str | None = None) -> np.ndarray:
    """Per-shard byte-sums MOD 2^32 computed ON the owning chips (one
    jitted shard_map, no host gather of the data): the integrity probe
    for scattered replicas — compare against
    ``host_bytes.astype(np.uint32).sum(dtype=np.uint32)`` per shard.
    uint32 wrap-around is deliberate (x64 is disabled under jit on TPU
    and a truncated int64 would wrap SILENTLY; mod-2^32 is the defined
    checksum). Returns [N] uint32 sums, one per shard."""
    from curvine_tpu.tpu.mesh import shard_map_compat

    axis = axis or mesh.axis_names[0]
    key = (mesh, axis, sharded.ndim)
    fn = _SUM_FNS.get(key)
    if fn is None:
        spec = P(axis, *([None] * (sharded.ndim - 1)))

        def shard_sum(x):
            # keepdims-style [1] result per shard → concatenates to [N]
            return jnp.sum(x.astype(jnp.uint32)).reshape(1)

        fn = _SUM_FNS[key] = jax.jit(
            shard_map_compat(shard_sum, mesh, spec, P(axis)))
    return np.asarray(fn(sharded)).astype(np.uint32)
