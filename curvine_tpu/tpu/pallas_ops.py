"""Pallas TPU kernels for the data plane.

block_checksum: integrity hash of an HBM-resident cached block computed
on-device (VPU tile reduction) — verifying a block after an ICI/DCN
transfer without ever copying it back to the host. Falls back to pallas
interpret mode off-TPU so tests run on CPU.

pq_lut_scan: the IVF-PQ ADC inner loop (vector/index.py) — score W
candidates by summing M one-byte codeword lookups against a per-query
LUT, fused over candidate tiles so codes stream HBM→VMEM once and the
score accumulation never leaves the chip."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

LANE = 128
SUBLANE = 8
TILE_WORDS = 64 * SUBLANE * LANE     # 64 f32-tiles per grid step (256 KiB)


def _checksum_kernel(x_ref, out_ref):
    # wraparound sums in int32 (same bit pattern as uint32; Mosaic has no
    # unsigned reductions) + a position-mixed term for order sensitivity.
    # Scalars can't be stored to VMEM → accumulate (8,128) partial tiles;
    # the final cross-lane reduction happens outside the kernel.
    x = x_ref[:]                                   # (TILE_WORDS/LANE, LANE)
    step = pl.program_id(0)
    sub = x.shape[0] // SUBLANE
    s_part = jnp.sum(x.reshape(sub, SUBLANE, LANE), axis=0, dtype=jnp.int32)
    idx = jax.lax.broadcasted_iota(jnp.int32, x.shape, 1)
    mixed = (x ^ (idx + step * TILE_WORDS)).reshape(sub, SUBLANE, LANE)
    m_part = jnp.sum(mixed, axis=0, dtype=jnp.int32)

    @pl.when(step == 0)
    def _init():
        out_ref[:] = jnp.zeros_like(out_ref)

    out_ref[0:SUBLANE, :] += s_part
    out_ref[SUBLANE:, :] += m_part


@functools.partial(jax.jit, static_argnames=("interpret",))
def _checksum_words(words: jax.Array, interpret: bool = False) -> jax.Array:
    n = words.shape[0]
    padded = ((n + TILE_WORDS - 1) // TILE_WORDS) * TILE_WORDS
    words = jnp.pad(words, (0, padded - n))
    rows = padded // LANE
    grid = rows // (TILE_WORDS // LANE)
    out = pl.pallas_call(
        _checksum_kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((TILE_WORDS // LANE, LANE),
                               lambda i: (i, 0))],
        out_specs=pl.BlockSpec((2 * SUBLANE, LANE), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((2 * SUBLANE, LANE), jnp.int32),
        interpret=interpret,
    )(words.reshape(rows, LANE))
    s = jax.lax.bitcast_convert_type(
        jnp.sum(out[:SUBLANE], dtype=jnp.int32), jnp.uint32)
    m = jax.lax.bitcast_convert_type(
        jnp.sum(out[SUBLANE:], dtype=jnp.int32), jnp.uint32)
    return s ^ (m << jnp.uint32(1))


def block_checksum(block: jax.Array) -> int:
    """Checksum of a device-resident uint8 block (stays on device)."""
    interpret = jax.devices()[0].platform != "tpu" or \
        block.devices().pop().platform != "tpu"
    nbytes = block.shape[0]
    pad = (-nbytes) % 4
    if pad:
        block = jnp.pad(block, (0, pad))
    words = jax.lax.bitcast_convert_type(
        block.reshape(-1, 4), jnp.int32).reshape(-1)
    return int(_checksum_words(words, interpret=interpret))


def block_checksum_host(data: bytes | np.ndarray) -> int:
    """Reference/host implementation (numpy) of the same hash."""
    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(data)
    pad = (-arr.size) % 4
    if pad:
        arr = np.pad(arr, (0, pad))
    words = arr.view(np.uint32).astype(np.uint64)
    n = words.size
    padded = ((n + TILE_WORDS - 1) // TILE_WORDS) * TILE_WORDS
    w = np.zeros(padded, dtype=np.uint64)
    w[:n] = words
    s = np.uint64(w.sum()) & np.uint64(0xFFFFFFFF)
    # mixed term: index within each lane-row (column id), offset per tile
    cols = np.tile(np.arange(LANE, dtype=np.uint64), padded // LANE)
    tile_of = (np.arange(padded, dtype=np.uint64) // TILE_WORDS) \
        * np.uint64(TILE_WORDS)
    mixed = np.bitwise_xor(w, (cols + tile_of) & np.uint64(0xFFFFFFFF))
    m = np.uint64(mixed.sum()) & np.uint64(0xFFFFFFFF)
    return int((s ^ ((m << np.uint64(1)) & np.uint64(0xFFFFFFFF))))


# ---------------------------------------------------------------- PQ ADC

PQ_TILE = 128      # candidates scored per grid step


def _pq_scan_kernel(lut_ref, codes_ref, out_ref, *, pre_offset: bool):
    # ADC without a hardware gather: codes are compared against a lane
    # iota and the matching LUT entry selected per subspace — an
    # [TILE, ksub] VPU select+reduce per subspace, all in VMEM. The
    # subspace count M is small (8-64) so the python loop unrolls.
    # pre_offset: codes carry the m·ksub flat-LUT offset already (the
    # device-pinned layout the IVF-PQ search uses).
    m, ksub = lut_ref.shape
    codes = codes_ref[:]                         # [PQ_TILE, M] int32
    col = jax.lax.broadcasted_iota(jnp.int32, (codes.shape[0], ksub), 1)
    acc = jnp.zeros((codes.shape[0], 1), jnp.float32)
    for mi in range(m):
        want = col + mi * ksub if pre_offset else col
        eq = codes[:, mi:mi + 1] == want
        acc = acc + jnp.sum(
            jnp.where(eq, lut_ref[mi:mi + 1, :], 0.0),
            axis=1, keepdims=True)
    out_ref[:] = acc


@functools.partial(jax.jit, static_argnames=("interpret", "pre_offset"))
def _pq_scan_padded(lut: jax.Array, codes: jax.Array,
                    interpret: bool = False,
                    pre_offset: bool = False) -> jax.Array:
    w, m = codes.shape
    ksub = lut.shape[1]
    out = pl.pallas_call(
        functools.partial(_pq_scan_kernel, pre_offset=pre_offset),
        grid=(w // PQ_TILE,),
        in_specs=[pl.BlockSpec((m, ksub), lambda i: (0, 0)),
                  pl.BlockSpec((PQ_TILE, m), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((PQ_TILE, 1), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((w, 1), jnp.float32),
        interpret=interpret,
    )(lut, codes)
    return out[:, 0]


def pq_lut_scan(lut: jax.Array, codes: jax.Array,
                interpret: bool | None = None,
                pre_offset: bool = False) -> jax.Array:
    """ADC scores out[w] = sum_m lut[m, codes[w, m]].

    lut [M, ksub] f32 (one query's per-codeword contributions), codes
    [W, M] int — W is padded to the candidate tile internally.
    pre_offset=True means codes already hold code + m·ksub (the pinned
    flat-LUT layout). Traceable (used inside the jitted IVF-PQ search);
    interpret=None picks interpret mode off-TPU like block_checksum."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    w = codes.shape[0]
    pad = (-w) % PQ_TILE
    if pad:
        codes = jnp.pad(codes, ((0, pad), (0, 0)))
    return _pq_scan_padded(lut.astype(jnp.float32),
                           codes.astype(jnp.int32),
                           interpret=interpret,
                           pre_offset=pre_offset)[:w]
