"""Pod-scale ICI data plane: peer-addressable HBM tier-0.

Three coupled pieces (docs/ici-plane.md):

* **Export advertisement** — a worker with an HBM tier advertises its
  device-resident blocks (device ordinal, ICI mesh coords, buffer
  shape/dtype) through an `HbmExportTable` (tpu/hbm.py). The bounded
  snapshot rides every heartbeat and the per-block flags ride
  GET_BLOCK_INFO, mirroring the shm-export capability negotiation of
  the 100 µs data plane (worker/shm.py).

* **Endpoint registry + device-path pull** — participants that share a
  device domain (workers and SDK loaders embedded on the same TPU host,
  or the whole in-process MiniCluster harness) register an
  `IciEndpoint`. `fetch_device_block` then serves a peer's HBM-resident
  block as a jax.Array moved device-to-device (XLA routes the copy over
  ICI; on the CPU interpret path it degrades to a host-backed device
  copy) — zero bytes on the TCP rail. Anything outside the device
  domain simply misses the registry and falls back to the TCP pull;
  fallback is a COUNTER, never an error.

* **Mesh broadcast rail** — `broadcast_bytes` streams a byte payload to
  every chip as a pipeline of bounded chunks instead of one monolithic
  replicated transfer. On a real pod the chunks ride the ICI fan-out
  back-to-back so every link stays busy (classic pipelined-tree
  broadcast); on the CPU interpret mesh the same chunking keeps each
  transfer inside the runtime's recycled-buffer fast path, measured ~4x
  the flat single-put baseline (bench.py::_ici_smoke). The
  topology-derived schedule (`broadcast_schedule`) plans one reader per
  host with log2-depth ICI fan-out rounds after it.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

import numpy as np

log = logging.getLogger(__name__)

# pipelined-broadcast chunk size: large enough to amortize dispatch,
# small enough that every transfer stays in the runtime's recycled
# buffer pool (the >32MB allocation path re-faults fresh pages per
# transfer and runs ~4x slower on the CPU harness; real TPU runtimes
# have the same preference for bounded staging buffers on the links)
DEFAULT_CHUNK_BYTES = 8 << 20


# --------------------------------------------------------------------
# endpoint registry (process-wide: the device domain)
# --------------------------------------------------------------------

@dataclass
class IciEndpoint:
    """One participant of the device domain: a worker (or embedded SDK
    loader) holding an HBM tier plus its position in the ICI mesh."""

    worker_id: int
    hbm: object                      # HbmTier | MultiHbmTier
    coords: tuple[int, ...] = ()


_lock = threading.Lock()
_endpoints: dict[int, IciEndpoint] = {}


def register_endpoint(worker_id: int, hbm, coords=()) -> IciEndpoint:
    """Join the device domain. Idempotent per worker_id (re-register
    replaces — a restarted worker's stale tier must not serve)."""
    ep = IciEndpoint(worker_id=int(worker_id), hbm=hbm,
                     coords=tuple(coords or ()))
    with _lock:
        _endpoints[ep.worker_id] = ep
    return ep


def unregister_endpoint(worker_id: int) -> None:
    with _lock:
        _endpoints.pop(int(worker_id), None)


def lookup_endpoint(worker_id: int) -> IciEndpoint | None:
    with _lock:
        return _endpoints.get(int(worker_id))


def endpoints() -> list[IciEndpoint]:
    with _lock:
        return list(_endpoints.values())


def fetch_device_block(src_worker_id: int, block_id: int,
                       device=None):
    """Pull a peer's HBM-resident block over the device path.

    Returns a jax.Array (on `device` when given, else wherever the
    source holds it) or None when the peer is outside this device
    domain or no longer holds the block — the caller falls back to the
    TCP rail. Never raises for "not reachable this way": that is the
    fallback contract, not an error."""
    ep = lookup_endpoint(src_worker_id)
    if ep is None or ep.hbm is None:
        return None
    try:
        arr = ep.hbm.get(block_id)
    except Exception as e:      # noqa: BLE001 — a dying tier is a miss
        log.debug("ici fetch of block %d from worker %d failed: %s",
                  block_id, src_worker_id, e)
        return None
    if arr is None:
        return None
    if device is not None:
        import jax
        if device not in arr.devices():
            # device-to-device move: XLA routes this over ICI on a pod;
            # the CPU interpret path degrades to a host-backed copy
            arr = jax.device_put(arr, device)
    return arr


# --------------------------------------------------------------------
# topology-derived broadcast schedule
# --------------------------------------------------------------------

@dataclass
class BroadcastSchedule:
    """Plan for one mesh broadcast: which participant reads from the
    cache (one per host) and the ICI fan-out rounds after it.

    ``rounds`` is a list of lists of (src_index, dst_index) edges over
    the participant order; round k may only use sources that already
    hold the data (the root, or destinations of earlier rounds)."""

    root: int
    order: list[int]
    rounds: list[list[tuple[int, int]]]
    chunk_bytes: int = DEFAULT_CHUNK_BYTES

    def receivers(self) -> set[int]:
        out = {self.root}
        for r in self.rounds:
            for _, dst in r:
                out.add(dst)
        return out

    def depth(self) -> int:
        return len(self.rounds)


def broadcast_schedule(n: int, coords: list[tuple[int, ...]] | None = None,
                       mesh_shape: tuple[int, ...] | None = None,
                       root: int = 0,
                       chunk_bytes: int = DEFAULT_CHUNK_BYTES
                       ) -> BroadcastSchedule:
    """Binomial-tree broadcast plan over ``n`` participants.

    With ``coords`` (ICI positions) the participant order walks outward
    from the root by hop distance, so every tree edge connects
    ICI-adjacent pairs where the torus allows it — each doubling round
    forwards to the nearest not-yet-covered participants. Without
    coords the order is index order (still log2 depth)."""
    from curvine_tpu.master.placement import ici_hops

    if n <= 0:
        raise ValueError("broadcast needs at least one participant")
    idxs = [i for i in range(n) if i != root]
    if coords:
        shape = list(mesh_shape) if mesh_shape else None
        idxs.sort(key=lambda i: (ici_hops(list(coords[root]),
                                          list(coords[i]), shape), i))
    order = [root] + idxs
    rounds: list[list[tuple[int, int]]] = []
    have = 1                      # prefix of `order` that holds the data
    while have < n:
        edges = []
        for k in range(min(have, n - have)):
            # holder k forwards to the next uncovered participant; with
            # hop-sorted order the earliest holders (nearest the root)
            # reach outward to the nearest frontier
            edges.append((order[k], order[have + k]))
        rounds.append(edges)
        have += len(edges)
    return BroadcastSchedule(root=root, order=order, rounds=rounds,
                             chunk_bytes=chunk_bytes)


# --------------------------------------------------------------------
# pipelined mesh broadcast rail
# --------------------------------------------------------------------

@dataclass
class ReplicatedBytes:
    """A byte payload resident on EVERY device of a mesh, as the
    pipeline's bounded chunks. ``np()`` gives the host view (bit-exact
    with the source); ``chunks`` are uint8 jax.Arrays replicated over
    the mesh."""

    length: int
    chunks: list = field(default_factory=list)

    @property
    def nbytes(self) -> int:
        return self.length

    def np(self) -> np.ndarray:
        if not self.chunks:
            return np.empty(0, dtype=np.uint8)
        return np.concatenate(
            [np.asarray(c) for c in self.chunks])[:self.length]


def broadcast_bytes(data, mesh, chunk_bytes: int = DEFAULT_CHUNK_BYTES,
                    counters: dict | None = None) -> ReplicatedBytes:
    """Stream host bytes to every chip of ``mesh`` as pipelined chunks.

    The flat baseline (one replicated device_put of the whole payload)
    serializes one oversized transfer per device; chunking keeps each
    transfer on the runtime's pooled fast path and lets the next chunk's
    fan-out overlap the previous one — the pipelined tree/ring broadcast
    shape. Bit-exact: ``result.np() == bytes(data)``."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(data)
    arr = arr.reshape(-1).view(np.uint8)
    rep = NamedSharding(mesh, P())
    t0 = time.perf_counter()
    chunk_bytes = max(1, int(chunk_bytes))
    out = ReplicatedBytes(length=arr.nbytes)
    for off in range(0, max(arr.nbytes, 1), chunk_bytes):
        piece = arr[off:off + chunk_bytes]
        if piece.nbytes == 0 and off:
            break
        # dispatch without blocking: chunk k+1's host-link stage rides
        # behind chunk k's fan-out
        out.chunks.append(jax.device_put(piece, rep))
    for c in out.chunks:
        c.block_until_ready()
    if counters is not None:
        counters["ici.broadcast_bytes"] = \
            counters.get("ici.broadcast_bytes", 0) + arr.nbytes
        counters["ici.broadcast_ms"] = counters.get("ici.broadcast_ms", 0) \
            + int((time.perf_counter() - t0) * 1000)
    return out


def flat_replicate(data, mesh):
    """The pre-tree baseline: one monolithic replicated transfer. Kept
    as the A/B control for the bench gate and the bit-exactness test."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    arr = np.frombuffer(data, dtype=np.uint8) if isinstance(
        data, (bytes, bytearray, memoryview)) else np.asarray(data)
    return jax.block_until_ready(
        jax.device_put(arr.reshape(-1).view(np.uint8),
                       NamedSharding(mesh, P())))
