"""Ring attention: exact attention over a sequence-sharded mesh axis.

Long-context path: Q stays put, K/V blocks rotate around the ``seq`` mesh
axis via ppermute while each step accumulates flash-style online-softmax
partial results. P steps of compute overlap P-1 ICI hops, so sequence
length scales linearly with the number of chips on the axis with no
all-gather of K/V (memory stays O(L/P) per chip).

Causal masking: with Q block index i fixed and the KV block visiting from
index j = (i - step) mod P, a block is fully visible when j < i, fully
masked when j > i, and diagonal (per-token causal) when j == i.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One (q-block, kv-block) flash step → (out_unnorm, row_max, row_sum).

    q: [B, H, Lq, D], k/v: [B, H, Lk, D], mask broadcastable [Lq, Lk]."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    scores = jnp.where(mask, scores, NEG_INF)
    m = jnp.max(scores, axis=-1, keepdims=True)            # [B,H,Lq,1]
    m = jnp.maximum(m, NEG_INF)                            # avoid -inf - -inf
    p = jnp.exp(scores - m)
    p = jnp.where(mask, p, 0.0)
    s = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, s


def _merge(o1, m1, s1, o2, m2, s2):
    """Merge two online-softmax partials."""
    m = jnp.maximum(m1, m2)
    a1 = jnp.exp(m1 - m)
    a2 = jnp.exp(m2 - m)
    return o1 * a1 + o2 * a2, m, s1 * a1 + s2 * a2


def ring_attention(q, k, v, axis_name: str, causal: bool = True):
    """Runs inside shard_map: q/k/v are the local shards [B, H, L/P, D].

    Returns the local attention output shard [B, H, L/P, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    lq = q.shape[2]

    q_pos = my_idx * lq + jnp.arange(lq)

    def step(carry, s):
        o, m, acc_s, kv_k, kv_v = carry
        kv_idx = (my_idx - s) % axis_size
        if causal:
            kv_pos = kv_idx * lq + jnp.arange(kv_k.shape[2])
            mask = q_pos[:, None] >= kv_pos[None, :]
        else:
            mask = jnp.ones((lq, kv_k.shape[2]), dtype=bool)
        o2, m2, s2 = _block_attn(q, kv_k, kv_v, mask)
        o, m, acc_s = _merge(o, m, acc_s, o2, m2, s2)
        # rotate kv to the next chip on the ring (skip after last step)
        perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
        kv_k = jax.lax.ppermute(kv_k, axis_name, perm)
        kv_v = jax.lax.ppermute(kv_v, axis_name, perm)
        return (o, m, acc_s, kv_k, kv_v), None

    o0 = jnp.zeros_like(q)
    m0 = jnp.full(q.shape[:3] + (1,), NEG_INF, dtype=q.dtype)
    s0 = jnp.zeros(q.shape[:3] + (1,), dtype=q.dtype)
    (o, m, s, _, _), _ = jax.lax.scan(
        step, (o0, m0, s0, k, v), jnp.arange(axis_size))
    return o / jnp.maximum(s, 1e-20)


def dense_attention(q, k, v, causal: bool = True):
    """Reference single-device attention (numerics check + small models)."""
    L = q.shape[2]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(q.shape[-1])
    if causal:
        mask = jnp.tril(jnp.ones((L, L), dtype=bool))
        scores = jnp.where(mask, scores, NEG_INF)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", w, v)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "seq",
                           causal: bool = True):
    """shard_map wrapper: q/k/v are global [B, H, L, D] arrays sharded on
    L over `axis_name`; output has the same sharding."""
    spec = P(None, None, axis_name, None)
    fn = functools.partial(ring_attention, axis_name=axis_name, causal=causal)
    from curvine_tpu.tpu.mesh import shard_map_compat
    return shard_map_compat(
        fn, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec)(q, k, v)
