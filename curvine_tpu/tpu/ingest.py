"""Host→HBM ingest pipeline.

Replaces the reference's cudaMemcpy/pinned-host streaming with JAX-native
transfer: `jax.device_put` with NamedSharding (per-device addressable
shards assembled host-side), prefetch-depth double buffering so the next
batch's host fetch and device transfer overlap the current step's compute.
"""

from __future__ import annotations

import asyncio
import collections
from typing import AsyncIterator, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def put_sharded(batch: np.ndarray, mesh: Mesh,
                spec: P | None = None) -> jax.Array:
    """Place a host batch as a global array sharded over the mesh.

    Single-process: device_put with a NamedSharding splits the host array
    across local devices. Multi-host: each process passes its local part
    and we assemble with make_array_from_process_local_data."""
    spec = spec if spec is not None else P(mesh.axis_names[0])
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)


class DevicePrefetcher:
    """Wraps a host-batch iterator; keeps `depth` batches in flight on
    device so the consumer never waits on the host→HBM copy."""

    def __init__(self, host_batches: Iterator[np.ndarray], mesh: Mesh | None,
                 spec: P | None = None, depth: int = 2, device=None,
                 profiler=None):
        self.src = iter(host_batches)
        self.mesh = mesh
        self.spec = spec
        self.depth = max(1, depth)
        self.device = device
        # optional StepProfiler (obs/profiler.py): host→HBM dispatch time
        self.profiler = profiler
        self._queue: collections.deque[jax.Array] = collections.deque()

    def _transfer(self, batch: np.ndarray) -> jax.Array:
        import time as _time
        t0 = _time.perf_counter()
        if self.mesh is not None:
            out = put_sharded(batch, self.mesh, self.spec)
        else:
            out = jax.device_put(batch, self.device)
        if self.profiler is not None:
            self.profiler.record("host_to_hbm",
                                 _time.perf_counter() - t0, batch.nbytes)
        return out

    def __iter__(self):
        return self

    def __next__(self) -> jax.Array:
        while len(self._queue) < self.depth:
            try:
                self._queue.append(self._transfer(next(self.src)))
            except StopIteration:
                break
        if not self._queue:
            raise StopIteration
        return self._queue.popleft()


class AsyncDevicePrefetcher:
    """Async variant for cache-backed sources (CurvineClient readers).

    A background PRODUCER task keeps `depth` batches in flight on
    device: the host fetch + host→HBM transfer of batch k+1 overlap the
    consumer's compute on batch k without the consumer doing anything —
    jax dispatch is async, so the consumer's step call returns while the
    producer's next `device_put` streams. (The round-4 version filled
    its window inside __anext__, i.e. only while the consumer was
    ASKING — fetches never overlapped a running step.)"""

    def __init__(self, host_batches: AsyncIterator[np.ndarray],
                 mesh: Mesh | None, spec: P | None = None, depth: int = 2,
                 device=None, profiler=None):
        self.src = host_batches
        self.mesh = mesh
        self.spec = spec
        self.depth = max(1, depth)
        self.device = device
        # optional StepProfiler (obs/profiler.py): attributes each step
        # to host→HBM transfer, compute_wait (producer blocked on a full
        # queue — the MODEL is the bottleneck) and input_wait (consumer
        # blocked on an empty queue — the DATA PIPELINE is)
        self.profiler = profiler
        # maxsize bounds device memory: at most depth+1 batches resident
        # (depth queued, plus the one the blocked producer transferred
        # before put()) — size depth with that +1 in the HBM budget
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=self.depth)
        self._producer: asyncio.Task | None = None
        self._error: BaseException | None = None
        self._finished = False

    def _transfer(self, batch: np.ndarray) -> jax.Array:
        import time as _time
        t0 = _time.perf_counter()
        if self.mesh is not None:
            out = put_sharded(batch, self.mesh, self.spec)
        else:
            out = jax.device_put(batch, self.device)
        if self.profiler is not None:
            self.profiler.record("host_to_hbm",
                                 _time.perf_counter() - t0, batch.nbytes)
        return out

    async def _produce(self) -> None:
        import time as _time
        try:
            async for batch in self.src:
                arr = self._transfer(batch)
                t0 = _time.perf_counter()
                await self._queue.put(arr)
                if self.profiler is not None:
                    # blocked put = the device queue is full = the step
                    # function is the pipeline's long pole
                    self.profiler.record("compute_wait",
                                         _time.perf_counter() - t0)
        except asyncio.CancelledError:
            # aclose() initiated this — nobody is waiting for a
            # notification, and putting into a possibly-FULL queue here
            # would deadlock the cancellation
            raise
        except Exception as e:
            await self._queue.put(e)     # surface at the consumer
            return
        await self._queue.put(_DONE)

    def __aiter__(self):
        return self

    async def __anext__(self) -> jax.Array:
        if self._error is not None:
            # sticky: restarting the producer on the dead generator
            # would report a clean StopAsyncIteration and mask the
            # mid-stream failure as successful exhaustion
            raise self._error
        if self._finished:
            raise StopAsyncIteration
        if self._producer is None:
            self._producer = asyncio.ensure_future(self._produce())
        if self.profiler is not None:
            import time as _time
            t0 = _time.perf_counter()
            item = await self._queue.get()
            # blocked get = the queue ran dry = the data pipeline (cache
            # fetch / decode / transfer) is the pipeline's long pole
            self.profiler.record("input_wait", _time.perf_counter() - t0)
        else:
            item = await self._queue.get()
        if item is _DONE:
            self._finished = True
            raise StopAsyncIteration
        if isinstance(item, BaseException):
            self._error = item
            raise item
        if self.profiler is not None:
            self.profiler.step_done()
        return item

    async def aclose(self) -> None:
        if self._producer is not None:
            self._producer.cancel()
            try:
                await self._producer
            except (Exception, asyncio.CancelledError):
                pass
            self._producer = None


_DONE = object()
