"""Host→HBM ingest pipeline.

Replaces the reference's cudaMemcpy/pinned-host streaming with JAX-native
transfer: `jax.device_put` with NamedSharding (per-device addressable
shards assembled host-side), prefetch-depth double buffering so the next
batch's host fetch and device transfer overlap the current step's compute.
"""

from __future__ import annotations

import collections
from typing import AsyncIterator, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def put_sharded(batch: np.ndarray, mesh: Mesh,
                spec: P | None = None) -> jax.Array:
    """Place a host batch as a global array sharded over the mesh.

    Single-process: device_put with a NamedSharding splits the host array
    across local devices. Multi-host: each process passes its local part
    and we assemble with make_array_from_process_local_data."""
    spec = spec if spec is not None else P(mesh.axis_names[0])
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    return jax.make_array_from_process_local_data(sharding, batch)


class DevicePrefetcher:
    """Wraps a host-batch iterator; keeps `depth` batches in flight on
    device so the consumer never waits on the host→HBM copy."""

    def __init__(self, host_batches: Iterator[np.ndarray], mesh: Mesh | None,
                 spec: P | None = None, depth: int = 2, device=None):
        self.src = iter(host_batches)
        self.mesh = mesh
        self.spec = spec
        self.depth = max(1, depth)
        self.device = device
        self._queue: collections.deque[jax.Array] = collections.deque()

    def _transfer(self, batch: np.ndarray) -> jax.Array:
        if self.mesh is not None:
            return put_sharded(batch, self.mesh, self.spec)
        return jax.device_put(batch, self.device)

    def __iter__(self):
        return self

    def __next__(self) -> jax.Array:
        while len(self._queue) < self.depth:
            try:
                self._queue.append(self._transfer(next(self.src)))
            except StopIteration:
                break
        if not self._queue:
            raise StopIteration
        return self._queue.popleft()


class AsyncDevicePrefetcher:
    """Async variant for cache-backed sources (CurvineClient readers)."""

    def __init__(self, host_batches: AsyncIterator[np.ndarray],
                 mesh: Mesh | None, spec: P | None = None, depth: int = 2,
                 device=None):
        self.src = host_batches
        self.mesh = mesh
        self.spec = spec
        self.depth = max(1, depth)
        self.device = device
        self._queue: collections.deque[jax.Array] = collections.deque()
        self._done = False

    def _transfer(self, batch: np.ndarray) -> jax.Array:
        if self.mesh is not None:
            return put_sharded(batch, self.mesh, self.spec)
        return jax.device_put(batch, self.device)

    def __aiter__(self):
        return self

    async def __anext__(self) -> jax.Array:
        while not self._done and len(self._queue) < self.depth:
            try:
                batch = await self.src.__anext__()
            except StopAsyncIteration:
                self._done = True
                break
            self._queue.append(self._transfer(batch))
        if not self._queue:
            raise StopAsyncIteration
        return self._queue.popleft()
