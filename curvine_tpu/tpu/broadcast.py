"""Model checkpoint distribution over a TPU pod.

The reference's "LLM model distribution acceleration" use case
(README.md Case 3): pull checkpoint bytes once from the cache (warmed from
S3 by a load job), materialize tensors host-side, and fan them out to all
devices — replicated params ride the ICI mesh via device_put with a
replicated NamedSharding, sharded params land directly in their TP layout
(no full-size copy per chip).

Checkpoint format: a msgpack manifest ``<name>.json`` + raw tensor files,
or a single .npz — both cache-native (written/read through CurvineClient).
"""

from __future__ import annotations

import json
import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from curvine_tpu.client import CurvineClient

log = logging.getLogger(__name__)

_warned_pickle = False


def _tree_skeleton(tree):
    """JSON-safe structure encoding of a pytree built from dicts, lists,
    tuples and None — leaves become indices into the flat tensor list.
    Returns (skeleton, leaves). Dict keys iterate SORTED to match
    jax.tree.flatten's ordering. Raises TypeError on containers this
    encoding can't represent (custom pytree nodes) — callers fall back
    to the legacy pickled treedef."""
    leaves: list = []

    def enc(node):
        if isinstance(node, dict):
            if not all(isinstance(k, str) for k in node):
                raise TypeError("non-string dict key")
            return {"k": "dict",
                    "v": {k: enc(node[k]) for k in sorted(node)}}
        if isinstance(node, (list, tuple)):
            return {"k": "list" if isinstance(node, list) else "tuple",
                    "v": [enc(c) for c in node]}
        if node is None:
            return {"k": "none"}
        leaves.append(node)
        return {"k": "leaf", "i": len(leaves) - 1}

    return enc(tree), leaves


def _tree_build(skel, leaves):
    k = skel["k"]
    if k == "dict":
        return {key: _tree_build(c, leaves) for key, c in skel["v"].items()}
    if k == "list":
        return [_tree_build(c, leaves) for c in skel["v"]]
    if k == "tuple":
        return tuple(_tree_build(c, leaves) for c in skel["v"])
    if k == "none":
        return None
    return leaves[skel["i"]]


async def save_checkpoint(client: CurvineClient, path: str,
                          params: dict) -> None:
    """Write a pytree of arrays as manifest + raw tensor blobs. The tree
    structure is JSON-encoded INSIDE the manifest (safe to load); only
    trees with custom pytree nodes fall back to a pickled treedef
    side-file, which readers accept with a warn-once."""
    manifest = {"tensors": []}
    treedef = None
    try:
        skel, flat = _tree_skeleton(params)
        manifest["tree"] = skel
    except TypeError:
        flat, treedef = jax.tree.flatten(params)
    await client.meta.mkdir(path)
    for i, arr in enumerate(flat):
        arr = np.asarray(arr)
        name = f"t{i:05d}.bin"
        manifest["tensors"].append(
            {"name": name, "dtype": str(arr.dtype), "shape": list(arr.shape)})
        await client.write_all(f"{path}/{name}", arr.tobytes())
    await client.write_all(f"{path}/manifest.json",
                           json.dumps(manifest).encode())
    if treedef is not None:
        import pickle
        await client.write_all(f"{path}/treedef.pkl", pickle.dumps(treedef))


async def load_checkpoint(client: CurvineClient, path: str,
                          placer=None) -> dict:
    """Read tensors back (short-circuit mmap when co-located). Tensor
    fetches run CONCURRENTLY, and when ``placer`` is given (an arr→jax
    transfer fn), each tensor's host→device transfer is dispatched as
    soon as its bytes land — cache reads overlap device transfers instead
    of the round-2 read-everything-then-transfer-everything sequence."""
    import asyncio
    raw = json.loads(await _read_all(client, f"{path}/manifest.json"))
    if isinstance(raw, list):
        # legacy layout: bare tensor list + pickled treedef side-file
        manifest, skel = raw, None
    else:
        manifest, skel = raw["tensors"], raw.get("tree")
    treedef = None
    if skel is None:
        # unpickling is arbitrary code execution for anyone who can write
        # the checkpoint path — only the legacy fallback still does it
        global _warned_pickle
        if not _warned_pickle:
            _warned_pickle = True
            log.warning("loading legacy pickled treedef from %s; re-save "
                        "the checkpoint to use the safe JSON structure",
                        path)
        import pickle
        treedef = pickle.loads(await _read_all(client, f"{path}/treedef.pkl"))

    async def load_one(t):
        reader = await client.open(f"{path}/{t['name']}")
        view = await reader.mmap_view(0, reader.len)
        if view is None:
            view = np.frombuffer(await reader.read_all(), dtype=np.uint8)
        arr = view.view(np.dtype(t["dtype"])).reshape(t["shape"])
        if placer is not None:
            out = placer(arr)         # async dispatch; device copies now
        else:
            out = np.array(arr)       # own the memory past reader close
        await reader.close()
        return out

    flat = await asyncio.gather(*(load_one(t) for t in manifest))
    if placer is not None:
        flat = [jax.block_until_ready(a) for a in flat]
    if skel is not None:
        return _tree_build(skel, flat)
    return jax.tree.unflatten(treedef, flat)


async def _read_all(client: CurvineClient, path: str) -> bytes:
    reader = await client.open(path)
    try:
        return await reader.read_all()
    finally:
        await reader.close()


def broadcast_params(params, mesh: Mesh, spec_tree=None):
    """Place host params onto the mesh. spec_tree=None → fully replicated
    (classic model distribution); otherwise each leaf lands sharded in its
    TP layout directly (never materializing full copies per chip)."""
    if spec_tree is None:
        sharding = NamedSharding(mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, sharding), params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, spec_tree)


async def distribute_checkpoint(client: CurvineClient, path: str,
                                mesh: Mesh, spec_tree=None):
    """cache → pod in one overlapped pass: each tensor is dispatched to
    its mesh placement the moment its cache read completes (replicated
    when spec_tree is None, else directly in its TP layout). spec_tree
    placement for named leaves is resolved after unflatten, so the fast
    overlapped path is used for the replicated (model-distribution)
    case."""
    if spec_tree is None:
        sharding = NamedSharding(mesh, P())
        return await load_checkpoint(
            client, path, placer=lambda a: jax.device_put(a, sharding))
    host = await load_checkpoint(client, path)
    return broadcast_params(host, mesh, spec_tree)


async def distribute_checkpoint_to_device(client: CurvineClient, path: str,
                                          device):
    """Single-chip variant: overlapped cache→HBM transfer of a whole
    checkpoint onto one device."""
    return await load_checkpoint(
        client, path, placer=lambda a: jax.device_put(a, device))
