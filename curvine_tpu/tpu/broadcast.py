"""Model checkpoint distribution over a TPU pod.

The reference's "LLM model distribution acceleration" use case
(README.md Case 3): pull checkpoint bytes once from the cache (warmed from
S3 by a load job), materialize tensors host-side, and fan them out to all
devices — replicated params ride the ICI mesh via device_put with a
replicated NamedSharding, sharded params land directly in their TP layout
(no full-size copy per chip).

Checkpoint format: a msgpack manifest ``<name>.json`` + raw tensor files,
or a single .npz — both cache-native (written/read through CurvineClient).
"""

from __future__ import annotations

import json
import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from curvine_tpu.client import CurvineClient

log = logging.getLogger(__name__)


async def save_checkpoint(client: CurvineClient, path: str,
                          params: dict) -> None:
    """Write a pytree of arrays as manifest + raw tensor blobs."""
    flat, treedef = jax.tree.flatten(params)
    manifest = {"tree": None, "tensors": []}
    import pickle
    await client.meta.mkdir(path)
    for i, arr in enumerate(flat):
        arr = np.asarray(arr)
        name = f"t{i:05d}.bin"
        manifest["tensors"].append(
            {"name": name, "dtype": str(arr.dtype), "shape": list(arr.shape)})
        await client.write_all(f"{path}/{name}", arr.tobytes())
    await client.write_all(f"{path}/manifest.json",
                           json.dumps(manifest["tensors"]).encode())
    await client.write_all(f"{path}/treedef.pkl", pickle.dumps(treedef))


async def load_checkpoint(client: CurvineClient, path: str) -> dict:
    """Read tensors back host-side (short-circuit mmap when co-located)."""
    import pickle
    manifest = json.loads(await (await client.open(f"{path}/manifest.json")
                                 ).read_all())
    treedef = pickle.loads(await (await client.open(f"{path}/treedef.pkl")
                                  ).read_all())
    flat = []
    for t in manifest:
        reader = await client.open(f"{path}/{t['name']}")
        nbytes = reader.len
        view = await reader.mmap_view(0, nbytes)
        if view is None:
            view = np.frombuffer(await reader.read_all(), dtype=np.uint8)
        arr = view.view(np.dtype(t["dtype"])).reshape(t["shape"])
        flat.append(np.array(arr))    # own the memory past reader close
        await reader.close()
    return jax.tree.unflatten(treedef, flat)


def broadcast_params(params, mesh: Mesh, spec_tree=None):
    """Place host params onto the mesh. spec_tree=None → fully replicated
    (classic model distribution); otherwise each leaf lands sharded in its
    TP layout directly (never materializing full copies per chip)."""
    if spec_tree is None:
        sharding = NamedSharding(mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, sharding), params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, spec_tree)


async def distribute_checkpoint(client: CurvineClient, path: str,
                                mesh: Mesh, spec_tree=None):
    """cache → host → pod in one call; returns device-resident params."""
    host = await load_checkpoint(client, path)
    return broadcast_params(host, mesh, spec_tree)
