"""Model checkpoint distribution over a TPU pod.

The reference's "LLM model distribution acceleration" use case
(README.md Case 3): pull checkpoint bytes once from the cache (warmed from
S3 by a load job), materialize tensors host-side, and fan them out to all
devices — replicated params ride the ICI mesh via device_put with a
replicated NamedSharding, sharded params land directly in their TP layout
(no full-size copy per chip).

Checkpoint format: a msgpack manifest ``<name>.json`` + raw tensor files,
or a single .npz — both cache-native (written/read through CurvineClient).
"""

from __future__ import annotations

import json
import logging

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from curvine_tpu.client import CurvineClient

log = logging.getLogger(__name__)

_warned_pickle = False


def _tree_skeleton(tree):
    """JSON-safe structure encoding of a pytree built from dicts, lists,
    tuples and None — leaves become indices into the flat tensor list.
    Returns (skeleton, leaves). Dict keys iterate SORTED to match
    jax.tree.flatten's ordering. Raises TypeError on containers this
    encoding can't represent (custom pytree nodes) — callers fall back
    to the legacy pickled treedef."""
    leaves: list = []

    def enc(node):
        if isinstance(node, dict):
            if not all(isinstance(k, str) for k in node):
                raise TypeError("non-string dict key")
            return {"k": "dict",
                    "v": {k: enc(node[k]) for k in sorted(node)}}
        if isinstance(node, (list, tuple)):
            return {"k": "list" if isinstance(node, list) else "tuple",
                    "v": [enc(c) for c in node]}
        if node is None:
            return {"k": "none"}
        leaves.append(node)
        return {"k": "leaf", "i": len(leaves) - 1}

    return enc(tree), leaves


def _tree_build(skel, leaves):
    k = skel["k"]
    if k == "dict":
        return {key: _tree_build(c, leaves) for key, c in skel["v"].items()}
    if k == "list":
        return [_tree_build(c, leaves) for c in skel["v"]]
    if k == "tuple":
        return tuple(_tree_build(c, leaves) for c in skel["v"])
    if k == "none":
        return None
    return leaves[skel["i"]]


async def save_checkpoint(client: CurvineClient, path: str,
                          params: dict) -> None:
    """Write a pytree of arrays as manifest + raw tensor blobs. The tree
    structure is JSON-encoded INSIDE the manifest (safe to load); only
    trees with custom pytree nodes fall back to a pickled treedef
    side-file, which readers accept with a warn-once."""
    manifest = {"tensors": []}
    treedef = None
    try:
        skel, flat = _tree_skeleton(params)
        manifest["tree"] = skel
    except TypeError:
        flat, treedef = jax.tree.flatten(params)
    await client.meta.mkdir(path)
    for i, arr in enumerate(flat):
        arr = np.asarray(arr)
        name = f"t{i:05d}.bin"
        manifest["tensors"].append(
            {"name": name, "dtype": str(arr.dtype), "shape": list(arr.shape)})
        await client.write_all(f"{path}/{name}", arr.tobytes())
    await client.write_all(f"{path}/manifest.json",
                           json.dumps(manifest).encode())
    if treedef is not None:
        import pickle
        await client.write_all(f"{path}/treedef.pkl", pickle.dumps(treedef))


async def _load_manifest(client: CurvineClient, path: str,
                         allow_pickle: bool = False):
    """Parse a checkpoint's manifest. Returns (tensors, skel, treedef).

    A manifest without the JSON tree encoding needs the legacy pickled
    treedef side-file — and unpickling is arbitrary code execution for
    anyone who can write the checkpoint path, so it is an explicit
    opt-in (``allow_pickle=True``), not a silent fallback."""
    raw = json.loads(await _read_all(client, f"{path}/manifest.json"))
    if isinstance(raw, list):
        # legacy layout: bare tensor list + pickled treedef side-file
        manifest, skel = raw, None
    else:
        manifest, skel = raw["tensors"], raw.get("tree")
    treedef = None
    if skel is None:
        if not allow_pickle:
            raise ValueError(
                f"checkpoint {path!r} carries only a legacy pickled "
                f"treedef, which this reader does not load by default "
                f"(unpickling runs arbitrary code). Pass "
                f"allow_pickle=True if you trust the writer, or re-save "
                f"the checkpoint with save_checkpoint() to get the safe "
                f"JSON tree encoding.")
        global _warned_pickle
        if not _warned_pickle:
            _warned_pickle = True
            log.warning("loading legacy pickled treedef from %s; re-save "
                        "the checkpoint to use the safe JSON structure",
                        path)
        import pickle
        treedef = pickle.loads(await _read_all(client, f"{path}/treedef.pkl"))
    return manifest, skel, treedef


async def load_checkpoint(client: CurvineClient, path: str,
                          placer=None, allow_pickle: bool = False) -> dict:
    """Read tensors back (short-circuit mmap when co-located). Tensor
    fetches run CONCURRENTLY, and when ``placer`` is given (an arr→jax
    transfer fn), each tensor's host→device transfer is dispatched as
    soon as its bytes land — cache reads overlap device transfers instead
    of the round-2 read-everything-then-transfer-everything sequence."""
    import asyncio
    manifest, skel, treedef = await _load_manifest(client, path,
                                                   allow_pickle)

    async def load_one(t):
        reader = await client.open(f"{path}/{t['name']}")
        view = await reader.mmap_view(0, reader.len)
        if view is None:
            view = np.frombuffer(await reader.read_all(), dtype=np.uint8)
        arr = view.view(np.dtype(t["dtype"])).reshape(t["shape"])
        if placer is not None:
            out = placer(arr)         # async dispatch; device copies now
        else:
            out = np.array(arr)       # own the memory past reader close
        await reader.close()
        return out

    flat = await asyncio.gather(*(load_one(t) for t in manifest))
    if placer is not None:
        flat = [jax.block_until_ready(a) for a in flat]
    if skel is not None:
        return _tree_build(skel, flat)
    return jax.tree.unflatten(treedef, flat)


async def _read_all(client: CurvineClient, path: str) -> bytes:
    reader = await client.open(path)
    try:
        return await reader.read_all()
    finally:
        await reader.close()


def broadcast_params(params, mesh: Mesh, spec_tree=None):
    """Place host params onto the mesh. spec_tree=None → fully replicated
    (classic model distribution); otherwise each leaf lands sharded in its
    TP layout directly (never materializing full copies per chip)."""
    if spec_tree is None:
        sharding = NamedSharding(mesh, P())
        return jax.tree.map(lambda x: jax.device_put(x, sharding), params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, spec_tree)


async def _hbm_source(client: CurvineClient, path: str,
                      counters: dict | None = None):
    """Source a cached file's bytes straight from a peer's HBM tier
    through the ICI device domain (tpu/ici_plane.py) — zero block-read
    RPCs when every block of the file is advertised. Returns a host
    uint8 view, or None (caller falls back to the mmap/RPC read path;
    the fallback is a counter, never an error)."""
    from curvine_tpu.tpu import ici_plane
    if not ici_plane.endpoints():
        return None
    try:
        fb = await client.meta.get_block_locations(path)
    except Exception:            # noqa: BLE001 — any miss → TCP rail
        return None
    if not fb.block_locs:
        return None
    parts = []
    for lb in fb.block_locs:
        got = None
        for loc in lb.locs:
            arr = ici_plane.fetch_device_block(loc.worker_id, lb.block.id)
            if arr is not None and arr.nbytes == lb.block.len:
                got = np.asarray(arr).reshape(-1).view(np.uint8)
                break
        if got is None:
            # all blocks or nothing — a half-device, half-TCP read
            # would serialize behind the slow half anyway
            if counters is not None:
                counters["ici.tcp_fallbacks"] = \
                    counters.get("ici.tcp_fallbacks", 0) + 1
            return None
        parts.append(got)
    if counters is not None:
        counters["ici.peer_pulls"] = \
            counters.get("ici.peer_pulls", 0) + len(parts)
        counters["ici.peer_pull_bytes"] = \
            counters.get("ici.peer_pull_bytes", 0) \
            + sum(p.nbytes for p in parts)
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


async def _distribute_tree(client: CurvineClient, path: str, mesh: Mesh,
                           allow_pickle: bool = False):
    """Topology-scheduled replicated distribution (docs/ici-plane.md):

    * the broadcast plan is derived from the mesh (one reader per host,
      binomial ICI fan-out after — ici_plane.broadcast_schedule);
      on a single-host mesh this process is that one reader
    * tensors dispatch in LPT order (largest first) so the longest
      read→fan-out chains start earliest and the pipeline drains evenly
    * tensor bytes come from peer HBM over the device domain when the
      blocks are advertised (zero TCP block reads), with a transparent
      fallback to the mmap/RPC rail

    Bit-exact with the flat path — only the sourcing and order differ."""
    import asyncio
    import time
    from curvine_tpu.tpu import ici_plane
    manifest, skel, treedef = await _load_manifest(client, path,
                                                   allow_pickle)
    counters = getattr(client, "counters", None)
    devs = mesh.devices.reshape(-1)
    sched = ici_plane.broadcast_schedule(
        len(devs), coords=[tuple(getattr(d, "coords", None) or (i,))
                           for i, d in enumerate(devs)])
    log.debug("broadcast schedule for %s: %d devices, depth %d",
              path, len(devs), sched.depth())
    sharding = NamedSharding(mesh, P())
    t0 = time.perf_counter()

    async def load_one(t):
        name = f"{path}/{t['name']}"
        arr = await _hbm_source(client, name, counters)
        reader = None
        if arr is None:
            reader = await client.open(name)
            view = await reader.mmap_view(0, reader.len)
            if view is None:
                view = np.frombuffer(await reader.read_all(),
                                     dtype=np.uint8)
            arr = view
        out = jax.device_put(
            arr.view(np.dtype(t["dtype"])).reshape(t["shape"]), sharding)
        if reader is not None:
            await reader.close()
        return out

    def size_of(t):
        n = 1
        for d in t["shape"]:
            n *= int(d)
        return n * np.dtype(t["dtype"]).itemsize

    lpt = sorted(range(len(manifest)), key=lambda i: -size_of(manifest[i]))
    tasks = {i: asyncio.ensure_future(load_one(manifest[i])) for i in lpt}
    flat = [await tasks[i] for i in range(len(manifest))]
    flat = [jax.block_until_ready(a) for a in flat]
    if counters is not None:
        counters["ici.broadcast_bytes"] = \
            counters.get("ici.broadcast_bytes", 0) \
            + sum(size_of(t) for t in manifest)
        counters["ici.broadcast_ms"] = \
            counters.get("ici.broadcast_ms", 0) \
            + int((time.perf_counter() - t0) * 1000)
    if skel is not None:
        return _tree_build(skel, flat)
    return jax.tree.unflatten(treedef, flat)


async def distribute_checkpoint(client: CurvineClient, path: str,
                                mesh: Mesh, spec_tree=None,
                                schedule: str = "tree",
                                allow_pickle: bool = False):
    """cache → pod in one overlapped pass: each tensor is dispatched to
    its mesh placement the moment its bytes land (replicated when
    spec_tree is None, else directly in its TP layout).

    ``schedule`` picks the replicated rail: "tree" (default) is the
    topology-scheduled path — LPT tensor order, peer-HBM device-domain
    sourcing, binomial fan-out plan; "flat" is the legacy read→put
    baseline, kept for A/B measurement. Both are bit-exact."""
    if spec_tree is None:
        if schedule == "tree":
            return await _distribute_tree(client, path, mesh,
                                          allow_pickle=allow_pickle)
        sharding = NamedSharding(mesh, P())
        return await load_checkpoint(
            client, path, placer=lambda a: jax.device_put(a, sharding),
            allow_pickle=allow_pickle)
    host = await load_checkpoint(client, path, allow_pickle=allow_pickle)
    return broadcast_params(host, mesh, spec_tree)


async def distribute_checkpoint_to_device(client: CurvineClient, path: str,
                                          device):
    """Single-chip variant: overlapped cache→HBM transfer of a whole
    checkpoint onto one device."""
    return await load_checkpoint(
        client, path, placer=lambda a: jax.device_put(a, device))
