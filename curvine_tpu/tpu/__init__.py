"""TPU-native layer: HBM tier-0 cache, device ingest pipelines, ICI mesh
topology, sharded loaders, ring attention, checkpoint broadcast.

This package replaces the reference's GPU-adjacent data paths
(cudaMemcpy/pinned-host streaming) with JAX/XLA-native ones."""
