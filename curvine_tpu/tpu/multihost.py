"""Multi-host (pod-scale) glue.

A TPU pod runs one process per host; JAX's distributed runtime connects
them so a single Mesh spans every chip. The cache integrates per-host:
each TPU VM runs a curvine worker (ici_coords from its pod position), and
each training process feeds from its local worker via short-circuit reads,
assembling global arrays with make_array_from_process_local_data
(curvine_tpu/tpu/ingest.put_sharded already handles process_count > 1).

This module is the thin initialization/ordering layer; everything else in
the framework is already written against global meshes."""

from __future__ import annotations

import logging
import os

import jax

log = logging.getLogger(__name__)


def initialize(coordinator: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Connect this process to the pod's JAX distributed runtime.

    No-ops for single-process runs; on TPU pods with env-provided topology
    (TPU_WORKER_HOSTNAMES etc.) jax.distributed autodetects everything."""
    coordinator = coordinator or os.environ.get("CURVINE_COORDINATOR")
    if coordinator is None and num_processes is None:
        try:
            jax.distributed.initialize()    # autodetect (TPU pod metadata)
        except Exception as e:  # noqa: BLE001 — single-host fallback
            log.debug("jax.distributed autodetect skipped: %s", e)
        return
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)


def pod_mesh(axis_names=("data", "model"), shape=None):
    """Mesh over every chip in the pod (all processes)."""
    from curvine_tpu.tpu.mesh import make_mesh
    return make_mesh(devices=jax.devices(), axis_names=axis_names,
                     shape=shape)


def local_ici_coords() -> list[int]:
    """Torus coordinates of this host's first chip — what the co-located
    worker should advertise as WorkerInfo.ici_coords."""
    local = jax.local_devices()
    if not local:
        return []
    coords = getattr(local[0], "coords", None)
    return list(coords) if coords is not None else []


def worker_conf_for_pod(conf) -> None:
    """Stamp pod-derived placement info onto a WorkerConf in place."""
    conf.worker.ici_coords = local_ici_coords()
