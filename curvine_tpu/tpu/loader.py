"""Cache-fed data loaders for JAX training loops.

The consumer-facing piece of the north star: WebDataset-style token shards
live in the distributed cache (warmed from S3/UFS by load jobs); this
loader streams them through the short-circuit mmap path into sharded
device arrays feeding a train step.

Shard format: raw little-endian token arrays (configurable dtype), one
file per shard, e.g. ``/datasets/train/shard-00000.bin``."""

from __future__ import annotations

import logging
from typing import AsyncIterator

import numpy as np

from curvine_tpu.client import CurvineClient
from curvine_tpu.common.epoch import epoch_shard_order

log = logging.getLogger(__name__)


class CacheShardSource:
    """Async stream of [batch, seq_len] token batches out of cached shards.

    Shard order is a deterministic per-epoch permutation of the sorted
    listing, seeded by (shuffle_seed, epoch).  With ``prefetch=True`` the
    source advises the master's rolling prefetch-window job as the read
    cursor advances, and pre-advises epoch+1's window near the tail of
    each epoch so the epoch boundary lands on a warm cache."""

    def __init__(self, client: CurvineClient, path: str, batch: int,
                 seq_len: int, dtype=np.int32, shuffle_seed: int | None = None,
                 drop_remainder: bool = True, profiler=None, epoch: int = 0,
                 prefetch: bool = False, prefetch_window: int = 8):
        self.client = client
        self.path = path
        self.batch = batch
        self.seq_len = seq_len
        self.dtype = np.dtype(dtype)
        self.shuffle_seed = shuffle_seed
        self.drop_remainder = drop_remainder
        # optional StepProfiler (obs/profiler.py): cache_fetch + decode
        # stage timings per shard
        self.profiler = profiler
        self.epoch = int(epoch)
        self.prefetch = prefetch
        self.prefetch_window = int(prefetch_window)
        self._advise_tasks: set = set()

    async def shards(self, epoch: int | None = None) -> list[str]:
        statuses = await self.client.meta.list_status(self.path)
        files = sorted(s.path for s in statuses if not s.is_dir)
        return epoch_shard_order(files, self.shuffle_seed,
                                 self.epoch if epoch is None else epoch)

    async def next_epoch_order(self) -> list[str]:
        """Shard order the NEXT epoch will use — public hook so callers
        can warm it (or inspect it) before the current epoch drains."""
        return await self.shards(epoch=self.epoch + 1)

    async def _advise(self, cursor: int, epoch: int | None = None) -> None:
        try:
            await self.client.advise(
                self.path, cursor=cursor, window=self.prefetch_window,
                epoch=self.epoch if epoch is None else epoch,
                seed=self.shuffle_seed or 0)
        except Exception as e:           # advisory: never fail the read path
            log.debug("prefetch advise failed: %s", e)

    def _advise_bg(self, cursor: int, epoch: int | None = None) -> None:
        """Fire-and-forget advise: the window RPC must never sit in the
        read path's latency (it is advisory — input_wait is the number
        this plane exists to shrink)."""
        if not self.prefetch:
            return
        import asyncio
        t = asyncio.ensure_future(self._advise(cursor, epoch))
        self._advise_tasks.add(t)
        t.add_done_callback(self._advise_tasks.discard)

    async def batches(self) -> AsyncIterator[np.ndarray]:
        import time as _time
        tokens_per_batch = self.batch * self.seq_len
        carry = np.empty(0, dtype=self.dtype)
        order = await self.shards()
        self._advise_bg(0)
        advised_next_epoch = False
        for idx, shard in enumerate(order):
            if idx:
                self._advise_bg(idx)
            if not advised_next_epoch \
                    and idx >= len(order) - self.prefetch_window:
                # tail of the epoch: start warming epoch+1's head
                self._advise_bg(0, epoch=self.epoch + 1)
                advised_next_epoch = True
            t0 = _time.perf_counter()
            reader = await self.client.open(shard)
            n_tokens = reader.len // self.dtype.itemsize
            view = await reader.mmap_view(0, n_tokens * self.dtype.itemsize)
            if view is not None:
                data = view.view(self.dtype)
            else:
                raw = await reader.read_all()
                data = np.frombuffer(raw, dtype=self.dtype)
            if self.profiler is not None:
                self.profiler.record("cache_fetch",
                                     _time.perf_counter() - t0,
                                     reader.len)
            t0 = _time.perf_counter()
            if carry.size:
                data = np.concatenate([carry, data])
                carry = np.empty(0, dtype=self.dtype)
            if self.profiler is not None:
                self.profiler.record("decode", _time.perf_counter() - t0)
            usable = (data.size // tokens_per_batch) * tokens_per_batch
            for off in range(0, usable, tokens_per_batch):
                yield data[off:off + tokens_per_batch].reshape(
                    self.batch, self.seq_len)
            rest = data[usable:]
            if rest.size:
                carry = rest.copy()     # own it before the mmap closes
            await reader.close()
        if self._advise_tasks:
            import asyncio
            await asyncio.gather(*list(self._advise_tasks),
                                 return_exceptions=True)
        # epoch drained: subsequent batches() calls replay the next epoch
        self.epoch += 1
        if carry.size and not self.drop_remainder:
            pad = tokens_per_batch - carry.size
            yield np.pad(carry, (0, pad)).reshape(self.batch, self.seq_len)


async def write_token_shards(client: CurvineClient, path: str,
                             tokens: np.ndarray, shard_tokens: int,
                             dtype=np.int32) -> list[str]:
    """Utility: split a token stream into cached shard files.

    Warm-up is ONE batched metadata round trip (META_BATCH): mkdir plus
    deletion of stale shard files from any previous run — re-sharding
    over an existing dir used to leave higher-numbered stale shards that
    the reader would then stream into the token flow."""
    from curvine_tpu.common import errors as err
    tokens = tokens.astype(dtype)
    base = path.rstrip("/")
    n_shards = (tokens.size + shard_tokens - 1) // shard_tokens
    keep = {f"{base}/shard-{i:05d}.bin" for i in range(n_shards)}
    warmup = [{"op": "mkdir", "path": path, "create_parent": True}]
    try:
        stale = [s.path for s in await client.meta.list_status(path)
                 if not s.is_dir and s.path not in keep]
        warmup += [{"op": "delete", "path": p} for p in sorted(stale)]
    except err.FileNotFound:
        pass
    for r in await client.meta.meta_batch(warmup):
        if "error" in r:
            raise err.CurvineError.from_wire(r.get("error_code", 0),
                                             r["error"])
    out = []
    for i, off in enumerate(range(0, tokens.size, shard_tokens)):
        p = f"{base}/shard-{i:05d}.bin"
        await client.write_all(p, tokens[off:off + shard_tokens].tobytes())
        out.append(p)
    return out


class TpuTrainFeed:
    """CacheShardSource → AsyncDevicePrefetcher, batch sharded over the
    mesh 'data' (and 'seq') axes — the full cache→HBM→step pipeline."""

    def __init__(self, client: CurvineClient, path: str, batch: int,
                 seq_len: int, mesh=None, depth: int = 2, dtype=np.int32,
                 profiler=None, shuffle_seed: int | None = None,
                 prefetch: bool = False, prefetch_window: int = 8):
        from jax.sharding import PartitionSpec as P
        from curvine_tpu.obs.profiler import StepProfiler
        from curvine_tpu.tpu.ingest import AsyncDevicePrefetcher
        # one StepProfiler threads the whole pipeline: cache_fetch +
        # decode from the shard source, host_to_hbm + compute_wait +
        # input_wait from the device prefetcher. `feed.profiler.summary()`
        # answers "where did the step go".
        self.profiler = profiler if profiler is not None else StepProfiler()
        self.source = CacheShardSource(client, path, batch, seq_len, dtype,
                                       shuffle_seed=shuffle_seed,
                                       profiler=self.profiler,
                                       prefetch=prefetch,
                                       prefetch_window=prefetch_window)
        spec = None
        if mesh is not None:
            seq = "seq" if "seq" in mesh.axis_names else None
            spec = P("data", seq)
        self.prefetcher = AsyncDevicePrefetcher(
            self.source.batches(), mesh, spec, depth=depth,
            profiler=self.profiler)

    def __aiter__(self):
        return self.prefetcher
