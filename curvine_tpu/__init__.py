"""curvine_tpu — a TPU-native distributed caching file system.

A ground-up rebuild of the capabilities of CurvineIO/curvine (Rust) as a
TPU-pod data-cache layer: POSIX-ish file semantics over object storage with
a multi-tier distributed cache (HBM / MEM / SSD / HDD), asyncio+C++ runtime,
and JAX-native ingest paths (zero-copy blocks into TPU HBM, sharded loaders,
checkpoint broadcast over the ICI mesh).

Reference parity map: see SURVEY.md §2.
"""

__version__ = "0.1.0"

from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.common.errors import CurvineError, ErrorCode

__all__ = ["ClusterConf", "CurvineError", "ErrorCode", "__version__"]
