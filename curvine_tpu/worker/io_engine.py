"""Direct-IO block data plane for the SSD/HDD tiers.

The role the reference fills with its SPDK user-space bdev stack
(orpc/src/io/spdk_bdev.rs, spdk_env.rs, spdk_poller.rs): cold block
reads and tier-move copies go to the device with O_DIRECT — bypassing
the page cache so the MEM tier and the FUSE warm path keep their pages —
through a batched submission/completion ring.

Architecture (what "ring" means here):

  caller (event loop / worker thread)
      │  submit(path, offset, aligned buf)  →  concurrent Future
      ▼
  submission queue  ──batch──►  ring thread(s)
                                 ├─ io_uring (ctypes; kernel ≥5.6):
                                 │  one ring owner thread keeps up to
                                 │  `queue_depth` OP_READ SQEs in flight,
                                 │  reaps CQEs as they land
                                 └─ fallback: `threads` workers each
                                    drain the queue with preadv
                                    (the "preadv2-on-threads" plan)
      │
      ▼
  future resolves with bytes-read (or the OSError)

Every data buffer comes from an mmap-backed pool (page-aligned — the
O_DIRECT contract) and is reused across requests; `read_into` handles
offset/length alignment by over-reading the covering aligned span and
memcpy-ing the requested slice out.

Graceful degradation, per request: a filesystem that rejects O_DIRECT
(EINVAL/ENOTSUP — tmpfs on older kernels, some overlayfs) silently gets
buffered preadv on the same thread pool, and the reason is recorded in
`stats()["fallbacks"]` so benches can stamp it into artifacts instead of
reporting page-cache numbers as device numbers.
"""

from __future__ import annotations

import ctypes
import errno
import logging
import mmap
import os
import queue
import threading
import time
from concurrent.futures import Future

log = logging.getLogger(__name__)

_PAGE = mmap.PAGESIZE
_O_DIRECT = getattr(os, "O_DIRECT", 0)      # 0 on platforms without it


# --------------------------------------------------------------------------
# aligned buffer pool
# --------------------------------------------------------------------------

class AlignedBuf:
    """Page-aligned reusable buffer (mmap allocations are page-aligned,
    which satisfies O_DIRECT's address alignment on every mainstream
    filesystem; 4K logical-block alignment of offset/len is the
    engine's job)."""

    __slots__ = ("mm", "size")

    def __init__(self, size: int):
        self.size = size
        self.mm = mmap.mmap(-1, size)

    def view(self, n: int | None = None) -> memoryview:
        return memoryview(self.mm)[: self.size if n is None else n]

    def close(self) -> None:
        self.mm.close()


class BufferPool:
    """Reusable aligned buffers in power-of-two size classes. Bounded:
    at most `per_class` parked buffers per class — steady-state IO
    recycles the same few buffers instead of faulting fresh pages
    (first-touch faults dominate large allocs on virtualized hosts)."""

    def __init__(self, min_size: int = 64 * 1024,
                 max_size: int = 8 * 1024 * 1024, per_class: int = 8):
        self.min_size = min_size
        self.max_size = max_size
        self.per_class = per_class
        self._classes: dict[int, list[AlignedBuf]] = {}
        self._lock = threading.Lock()

    def _class_for(self, n: int) -> int:
        c = self.min_size
        while c < n:
            c *= 2
        return c

    def acquire(self, n: int) -> AlignedBuf:
        if n > self.max_size:
            return AlignedBuf(n)          # outsized: unpooled one-off
        c = self._class_for(n)
        with self._lock:
            free = self._classes.get(c)
            if free:
                return free.pop()
        return AlignedBuf(c)

    def release(self, buf: AlignedBuf) -> None:
        if buf.size > self.max_size:
            buf.close()
            return
        with self._lock:
            free = self._classes.setdefault(buf.size, [])
            if len(free) < self.per_class:
                free.append(buf)
                return
        buf.close()

    def drain(self) -> None:
        with self._lock:
            for free in self._classes.values():
                for b in free:
                    b.close()
            self._classes.clear()


# --------------------------------------------------------------------------
# minimal io_uring via ctypes (OP_READ only — all this plane needs)
# --------------------------------------------------------------------------

_SYS_IO_URING_SETUP = 425
_SYS_IO_URING_ENTER = 426
_SYS_IO_URING_REGISTER = 427
_IORING_OFF_SQ_RING = 0
_IORING_OFF_CQ_RING = 0x8000000
_IORING_OFF_SQES = 0x10000000
_IORING_ENTER_GETEVENTS = 1
_IORING_FEAT_SINGLE_MMAP = 1
_IORING_OP_READ = 22                     # addr/len read, kernel >= 5.6
_IORING_OP_READ_FIXED = 4                # read into a registered buffer
_IORING_REGISTER_BUFFERS = 0


class _SqringOffsets(ctypes.Structure):
    _fields_ = [("head", ctypes.c_uint32), ("tail", ctypes.c_uint32),
                ("ring_mask", ctypes.c_uint32),
                ("ring_entries", ctypes.c_uint32),
                ("flags", ctypes.c_uint32), ("dropped", ctypes.c_uint32),
                ("array", ctypes.c_uint32), ("resv1", ctypes.c_uint32),
                ("resv2", ctypes.c_uint64)]


class _CqringOffsets(ctypes.Structure):
    _fields_ = [("head", ctypes.c_uint32), ("tail", ctypes.c_uint32),
                ("ring_mask", ctypes.c_uint32),
                ("ring_entries", ctypes.c_uint32),
                ("overflow", ctypes.c_uint32), ("cqes", ctypes.c_uint32),
                ("flags", ctypes.c_uint32), ("resv1", ctypes.c_uint32),
                ("resv2", ctypes.c_uint64)]


class _UringParams(ctypes.Structure):
    _fields_ = [("sq_entries", ctypes.c_uint32),
                ("cq_entries", ctypes.c_uint32),
                ("flags", ctypes.c_uint32),
                ("sq_thread_cpu", ctypes.c_uint32),
                ("sq_thread_idle", ctypes.c_uint32),
                ("features", ctypes.c_uint32),
                ("wq_fd", ctypes.c_uint32),
                ("resv", ctypes.c_uint32 * 3),
                ("sq_off", _SqringOffsets),
                ("cq_off", _CqringOffsets)]


class _Sqe(ctypes.Structure):
    """io_uring_sqe, 64 bytes. The unions collapse to the fields OP_READ
    uses; `rest` pads the tail (buf_index/personality/etc stay zero)."""
    _fields_ = [("opcode", ctypes.c_uint8), ("flags", ctypes.c_uint8),
                ("ioprio", ctypes.c_uint16), ("fd", ctypes.c_int32),
                ("off", ctypes.c_uint64), ("addr", ctypes.c_uint64),
                ("len", ctypes.c_uint32), ("rw_flags", ctypes.c_uint32),
                ("user_data", ctypes.c_uint64),
                ("rest", ctypes.c_uint8 * 24)]


class _Cqe(ctypes.Structure):
    _fields_ = [("user_data", ctypes.c_uint64), ("res", ctypes.c_int32),
                ("flags", ctypes.c_uint32)]


class _Iovec(ctypes.Structure):
    _fields_ = [("iov_base", ctypes.c_void_p),
                ("iov_len", ctypes.c_size_t)]


class UringRing:
    """A submission/completion ring over raw io_uring syscalls. Single
    owner thread: only the engine's ring thread touches the SQ/CQ, so no
    memory-order gymnastics are needed beyond ctypes' volatile-ish
    loads/stores (the kernel side uses acquire/release on head/tail;
    a single user-space writer never races itself)."""

    def __init__(self, entries: int = 32):
        self._libc = ctypes.CDLL(None, use_errno=True)
        p = _UringParams()
        fd = self._libc.syscall(_SYS_IO_URING_SETUP, entries,
                                ctypes.byref(p))
        if fd < 0:
            raise OSError(ctypes.get_errno(), "io_uring_setup failed")
        self.fd = fd
        self.entries = p.sq_entries
        try:
            sq_size = p.sq_off.array + p.sq_entries * 4
            cq_size = p.cq_off.cqes + p.cq_entries * ctypes.sizeof(_Cqe)
            if p.features & _IORING_FEAT_SINGLE_MMAP:
                sq_size = cq_size = max(sq_size, cq_size)
            self._sq_mm = mmap.mmap(fd, sq_size, offset=_IORING_OFF_SQ_RING)
            self._cq_mm = (self._sq_mm
                           if p.features & _IORING_FEAT_SINGLE_MMAP
                           else mmap.mmap(fd, cq_size,
                                          offset=_IORING_OFF_CQ_RING))
            self._sqes_mm = mmap.mmap(fd, p.sq_entries * ctypes.sizeof(_Sqe),
                                      offset=_IORING_OFF_SQES)
        except OSError:
            os.close(fd)
            raise

        def _u32(mm, off):
            return ctypes.c_uint32.from_buffer(mm, off)

        self._sq_head = _u32(self._sq_mm, p.sq_off.head)
        self._sq_tail = _u32(self._sq_mm, p.sq_off.tail)
        self._sq_mask = _u32(self._sq_mm, p.sq_off.ring_mask).value
        self._sq_array = (ctypes.c_uint32 * p.sq_entries).from_buffer(
            self._sq_mm, p.sq_off.array)
        self._cq_head = _u32(self._cq_mm, p.cq_off.head)
        self._cq_tail = _u32(self._cq_mm, p.cq_off.tail)
        self._cq_mask = _u32(self._cq_mm, p.cq_off.ring_mask).value
        self._cqes = (_Cqe * p.cq_entries).from_buffer(
            self._cq_mm, p.cq_off.cqes)
        self._sqes = (_Sqe * p.sq_entries).from_buffer(self._sqes_mm, 0)
        self.in_flight = 0

    def sq_space(self) -> int:
        return self.entries - (self._sq_tail.value - self._sq_head.value)

    def register_buffers(self, bufs: list[tuple[int, int]]) -> None:
        """Pin ``bufs`` ([(addr, len)]) into the ring's fixed-buffer
        table. After this, ``prep_read_fixed`` ops may name a buffer by
        index and the kernel skips the per-op get_user_pages walk — the
        point of the registered receive path (rpc/transport.RingRecv).
        Raises OSError where the kernel lacks IORING_REGISTER_BUFFERS
        or refuses to pin (RLIMIT_MEMLOCK); callers fall back."""
        iovs = (_Iovec * len(bufs))()
        for i, (addr, ln) in enumerate(bufs):
            iovs[i].iov_base = addr
            iovs[i].iov_len = ln
        r = self._libc.syscall(_SYS_IO_URING_REGISTER, self.fd,
                               _IORING_REGISTER_BUFFERS,
                               ctypes.byref(iovs), len(bufs))
        if r < 0:
            raise OSError(ctypes.get_errno(), "io_uring_register failed")
        self._reg_iovs = iovs       # keep the table alive for the ring

    def prep_read_fixed(self, fd: int, buf_addr: int, length: int,
                        offset: int, buf_index: int,
                        user_data: int) -> None:
        """Like prep_read but against a registered buffer: buf_addr must
        point inside registered buffer ``buf_index``. The sqe buf_index
        union member is the u16 at offset 40 — the first two bytes of
        the ``rest`` pad."""
        tail = self._sq_tail.value
        idx = tail & self._sq_mask
        sqe = self._sqes[idx]
        ctypes.memset(ctypes.byref(sqe), 0, ctypes.sizeof(_Sqe))
        sqe.opcode = _IORING_OP_READ_FIXED
        sqe.fd = fd
        sqe.off = offset
        sqe.addr = buf_addr
        sqe.len = length
        sqe.user_data = user_data
        sqe.rest[0] = buf_index & 0xFF
        sqe.rest[1] = (buf_index >> 8) & 0xFF
        self._sq_array[idx] = idx
        self._sq_tail.value = tail + 1

    def prep_read(self, fd: int, buf_addr: int, length: int, offset: int,
                  user_data: int) -> None:
        tail = self._sq_tail.value
        idx = tail & self._sq_mask
        sqe = self._sqes[idx]
        ctypes.memset(ctypes.byref(sqe), 0, ctypes.sizeof(_Sqe))
        sqe.opcode = _IORING_OP_READ
        sqe.fd = fd
        sqe.off = offset
        sqe.addr = buf_addr
        sqe.len = length
        sqe.user_data = user_data
        self._sq_array[idx] = idx
        self._sq_tail.value = tail + 1

    def submit_and_wait(self, min_complete: int) -> int:
        """Submit everything staged; block for at least `min_complete`
        completions (0 → just submit)."""
        to_submit = self._sq_tail.value - self._sq_head.value
        flags = _IORING_ENTER_GETEVENTS if min_complete else 0
        r = self._libc.syscall(_SYS_IO_URING_ENTER, self.fd, to_submit,
                               min_complete, flags, None, 0)
        if r < 0:
            e = ctypes.get_errno()
            if e == errno.EINTR:
                return 0
            raise OSError(e, "io_uring_enter failed")
        self.in_flight += r
        return r

    def reap(self) -> list[tuple[int, int]]:
        """Drain the CQ: [(user_data, res)]."""
        out = []
        head = self._cq_head.value
        tail = self._cq_tail.value
        while head != tail:
            cqe = self._cqes[head & self._cq_mask]
            out.append((cqe.user_data, cqe.res))
            head += 1
        self._cq_head.value = head
        self.in_flight -= len(out)
        return out

    def close(self) -> None:
        # ctypes structures hold exported buffers; drop them before the
        # mmaps close or mmap.close() raises BufferError
        for name in ("_sq_head", "_sq_tail", "_sq_array", "_cq_head",
                     "_cq_tail", "_cqes", "_sqes"):
            if hasattr(self, name):
                delattr(self, name)
        import gc
        gc.collect()
        for mm in {id(m): m for m in (getattr(self, "_sq_mm", None),
                                      getattr(self, "_cq_mm", None),
                                      getattr(self, "_sqes_mm", None))
                   if m is not None}.values():
            try:
                mm.close()
            except BufferError:        # a straggler view; kernel cleans up
                pass
        os.close(self.fd)


# --------------------------------------------------------------------------
# the engine
# --------------------------------------------------------------------------

class _Request:
    __slots__ = ("fd", "offset", "length", "buf_addr", "future", "buffered",
                 "t0")

    def __init__(self, fd: int, offset: int, length: int, buf_addr: int,
                 buffered: bool):
        self.fd = fd
        self.offset = offset
        self.length = length
        self.buf_addr = buf_addr
        self.buffered = buffered
        self.future: Future = Future()
        # submit timestamp: completion observes submit→complete latency
        # into the worker's io.submit_to_complete histogram
        self.t0 = time.perf_counter()


class EngineShutdown(RuntimeError):
    pass


class DirectIOEngine:
    """Batched O_DIRECT read engine. One instance serves every SSD/HDD
    tier on the worker; submissions come from the event loop (async) or
    from tier-move worker threads (sync) and resolve on the ring
    thread(s).

    `engine`: "auto" (io_uring when the kernel cooperates, else thread
    pool), "uring" (require io_uring, raise otherwise), "threads"
    (never try io_uring), "off" (constructor raises — callers keep the
    buffered path)."""

    def __init__(self, queue_depth: int = 32, alignment: int = 4096,
                 threads: int = 2, engine: str = "auto",
                 segment_bytes: int = 1024 * 1024):
        if engine == "off":
            raise ValueError("direct-IO engine disabled by conf")
        if alignment <= 0 or alignment & (alignment - 1):
            raise ValueError(f"alignment {alignment} not a power of two")
        self.queue_depth = max(1, queue_depth)
        self.alignment = alignment
        self.segment_bytes = max(alignment,
                                 (segment_bytes // alignment) * alignment)
        # park a full ring window per class: steady-state IO recycles
        # buffers instead of re-mmapping (first-touch faults) each batch
        self.pool = BufferPool(min_size=max(64 * 1024, alignment),
                               per_class=self.queue_depth + 4)
        self._q: queue.Queue[_Request | None] = queue.Queue()
        # optional MetricsRegistry (set by WorkerServer): completions
        # observe submit→complete latency (io.submit_to_complete).
        # Histogram mutation is dict arithmetic under the GIL — safe
        # enough from the engine threads for metrics purposes.
        self.metrics = None
        # optional DiskFaultInjector (fault/disk.py, set by WorkerServer
        # alongside BlockStore.fault_hook): submissions consult it so
        # injected per-dir EIO reaches direct-IO readers too
        self.fault_hook = None
        self._fds: dict[str, tuple[int, bool]] = {}   # path -> (fd, direct)
        self._fd_lock = threading.Lock()
        self._closed = False
        self.stats_lock = threading.Lock()
        self.counters: dict[str, int] = {
            "submitted": 0, "completed": 0, "batches": 0,
            "direct_bytes": 0, "buffered_bytes": 0, "errors": 0}
        self.fallbacks: dict[str, int] = {}       # reason -> count
        self._ring: UringRing | None = None
        if engine in ("auto", "uring"):
            try:
                self._ring = UringRing(self.queue_depth)
            except OSError as e:
                if engine == "uring":
                    raise
                self._note_fallback(f"io_uring unavailable: "
                                    f"{errno.errorcode.get(e.errno, e.errno)}")
        self.mode = "uring" if self._ring is not None else "threads"
        n_threads = 1 if self._ring is not None else max(1, threads)
        self._threads = [
            threading.Thread(target=self._ring_loop if self._ring is not None
                             else self._thread_loop,
                             name=f"direct-io-{i}", daemon=True)
            for i in range(n_threads)]
        for t in self._threads:
            t.start()

    # ---------------- fd cache / O_DIRECT probing ----------------

    def _note_fallback(self, reason: str) -> None:
        with self.stats_lock:
            self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    def _get_fd(self, path: str) -> tuple[int, bool]:
        """(fd, is_direct). One open per path; filesystems rejecting
        O_DIRECT get a buffered fd and a recorded reason — the
        per-request transparent fallback."""
        with self._fd_lock:
            ent = self._fds.get(path)
            if ent is not None:
                return ent
        if not _O_DIRECT:
            self._note_fallback("O_DIRECT unsupported on this platform")
            ent = (os.open(path, os.O_RDONLY), False)
        else:
            try:
                fd = os.open(path, os.O_RDONLY | _O_DIRECT)
                ent = (fd, True)
            except OSError as e:
                if e.errno not in (errno.EINVAL, errno.ENOTSUP,
                                   errno.EOPNOTSUPP):
                    raise
                self._note_fallback(
                    f"O_DIRECT rejected "
                    f"({errno.errorcode.get(e.errno, e.errno)})")
                ent = (os.open(path, os.O_RDONLY), False)
        with self._fd_lock:
            cur = self._fds.get(path)
            if cur is not None:           # raced another opener
                os.close(ent[0])
                return cur
            self._fds[path] = ent
        return ent

    def forget(self, path: str) -> None:
        """Drop the cached fd (block file deleted / tier moved)."""
        with self._fd_lock:
            ent = self._fds.pop(path, None)
        if ent is not None:
            try:
                os.close(ent[0])
            except OSError:
                pass

    # ---------------- submission ----------------

    def submit(self, path: str, offset: int, length: int,
               buf: AlignedBuf) -> Future:
        """Queue one aligned read into `buf`; returns a concurrent
        Future resolving to bytes-read. `offset` and `length` must
        already be aligned (use read_into for arbitrary ranges)."""
        if self._closed:
            f: Future = Future()
            f.set_exception(EngineShutdown("engine is shut down"))
            return f
        hook = self.fault_hook
        if hook is not None:
            try:
                hook.check_read(path)
            except OSError as e:
                with self.stats_lock:
                    self.counters["errors"] += 1
                f = Future()
                f.set_exception(e)
                return f
        fd, direct = self._get_fd(path)
        addr = ctypes.addressof(ctypes.c_char.from_buffer(buf.mm))
        req = _Request(fd, offset, length, addr, buffered=not direct)
        with self.stats_lock:
            self.counters["submitted"] += 1
        self._q.put(req)
        return req.future

    # ---------------- ring thread (io_uring mode) ----------------

    def _ring_loop(self) -> None:
        ring = self._ring
        pending: dict[int, _Request] = {}
        next_id = 1
        while True:
            # Idle → block for the first request (or shutdown). With IO
            # in flight → never block on the queue: grab whatever is
            # already there and go wait on COMPLETIONS (enter with
            # GETEVENTS), or completion latency becomes queue-poll
            # latency.
            if pending:
                try:
                    req = self._q.get_nowait()
                except queue.Empty:
                    req = False           # no new work; reap below
            else:
                req = self._q.get()
            if req is None:
                break
            batch: list[_Request] = [req] if req else []
            while len(batch) + len(pending) < self.queue_depth:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._q.put(None)     # re-post for the outer check
                    break
                batch.append(nxt)
            staged_ids: list[int] = []
            for r in batch:
                if r.buffered:
                    self._do_buffered(r)
                    continue
                if ring.sq_space() <= 0:
                    # ring full: execute inline rather than stall the loop
                    self._do_preadv(r)
                    continue
                ring.prep_read(r.fd, r.buf_addr, r.length, r.offset, next_id)
                pending[next_id] = r
                staged_ids.append(next_id)
                next_id += 1
            if staged_ids or pending:
                try:
                    ring.submit_and_wait(1 if pending else 0)
                except OSError as e:
                    # a poisoned submission batch (bad fd after delete):
                    # fail THIS batch only — earlier submissions are
                    # in flight and the kernel still owns their buffers
                    with self.stats_lock:
                        self.counters["errors"] += len(staged_ids)
                    for sid in staged_ids:
                        r = pending.pop(sid, None)
                        if r is not None:
                            r.future.set_exception(e)
                    continue
                for user_data, res in ring.reap():
                    r = pending.pop(user_data, None)
                    if r is None:
                        continue
                    self._complete(r, res)
            with self.stats_lock:
                self.counters["batches"] += 1
        # shutdown: fail whatever is still queued, reap in-flight
        self._drain_on_shutdown(pending)

    def _drain_on_shutdown(self, pending: dict[int, _Request]) -> None:
        ring = self._ring
        while pending:
            try:
                ring.submit_and_wait(1)
            except OSError as e:
                for r in pending.values():
                    r.future.set_exception(e)
                pending.clear()
                break
            for user_data, res in ring.reap():
                r = pending.pop(user_data, None)
                if r is not None:
                    self._complete(r, res)
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.future.set_exception(
                    EngineShutdown("engine is shut down"))

    # ---------------- thread pool mode ----------------

    def _thread_loop(self) -> None:
        while True:
            req = self._q.get()
            if req is None:
                self._q.put(None)         # wake the next worker
                break
            if req.buffered:
                self._do_buffered(req)
            else:
                self._do_preadv(req)
            with self.stats_lock:
                self.counters["batches"] += 1

    def _do_preadv(self, req: _Request) -> None:
        try:
            mv = (ctypes.c_char * req.length).from_address(req.buf_addr)
            got = os.preadv(req.fd, [memoryview(mv).cast("B")], req.offset)
        except OSError as e:
            if e.errno == errno.EINVAL:
                # the fs accepted O_DIRECT at open but rejects it at
                # read (some network/overlay stacks): buffered retry
                self._note_fallback("O_DIRECT read EINVAL")
                self._do_buffered(req)
                return
            with self.stats_lock:
                self.counters["errors"] += 1
            req.future.set_exception(e)
            return
        self._complete(req, got)

    def _do_buffered(self, req: _Request) -> None:
        try:
            mv = (ctypes.c_char * req.length).from_address(req.buf_addr)
            got = os.preadv(req.fd, [memoryview(mv).cast("B")], req.offset)
        except OSError as e:
            with self.stats_lock:
                self.counters["errors"] += 1
            req.future.set_exception(e)
            return
        with self.stats_lock:
            self.counters["completed"] += 1
            self.counters["buffered_bytes"] += max(0, got)
        req.future.set_result(got)

    def _complete(self, req: _Request, res: int) -> None:
        m = self.metrics
        if m is not None:
            m.observe("io.submit_to_complete",
                      time.perf_counter() - req.t0)
        if res < 0:
            with self.stats_lock:
                self.counters["errors"] += 1
            req.future.set_exception(OSError(-res, os.strerror(-res)))
            return
        with self.stats_lock:
            self.counters["completed"] += 1
            if req.buffered:
                self.counters["buffered_bytes"] += res
            else:
                self.counters["direct_bytes"] += res
        req.future.set_result(res)

    # ---------------- aligned-range frontends ----------------

    def _plan(self, offset: int, length: int) -> tuple[int, int]:
        """Covering aligned span (start, len) for [offset, offset+len)."""
        a = self.alignment
        start = (offset // a) * a
        end = -(-(offset + length) // a) * a
        return start, end - start

    def pread_sync(self, path: str, offset: int, length: int) -> bytes:
        """Blocking read of an arbitrary range — the tier-move copy path
        (already running on a worker thread). Splits the covering span
        into `segment_bytes` submissions so a multi-MB copy batches at
        `queue_depth` instead of serializing."""
        if length <= 0:
            return b""
        start, span = self._plan(offset, length)
        segs = []
        out = bytearray()
        try:
            pos = start
            while pos < start + span:
                n = min(self.segment_bytes, start + span - pos)
                buf = self.pool.acquire(n)
                segs.append((pos, n, buf, self.submit(path, pos, n, buf)))
                pos += n
            for seg_off, n, buf, fut in segs:
                got = fut.result()
                lo = max(0, offset - seg_off)
                hi = min(got, offset + length - seg_off)
                if hi > lo:
                    out += buf.view()[lo:hi]
                if got < n:
                    break                  # EOF inside this segment
        finally:
            for _o, _n, buf, fut in segs:
                if not fut.done():
                    try:
                        fut.result()
                    except Exception:  # noqa: BLE001 — buf reuse gate only
                        pass
                self.pool.release(buf)
        return bytes(out)

    async def read_into(self, path: str, offset: int, out) -> int:
        """Async read of an arbitrary range into `out` (memoryview /
        ndarray). Alignment is absorbed here: the engine reads the
        covering aligned span into pooled buffers and copies the
        requested slice out. Returns bytes filled (short on EOF)."""
        import asyncio
        length = len(out)
        if length <= 0:
            return 0
        start, span = self._plan(offset, length)
        segs = []
        filled = 0
        try:
            pos = start
            while pos < start + span:
                n = min(self.segment_bytes, start + span - pos)
                buf = self.pool.acquire(n)
                segs.append((pos, n, buf, asyncio.wrap_future(
                    self.submit(path, pos, n, buf))))
                pos += n
            mv = memoryview(out)
            if hasattr(mv, "cast"):
                mv = mv.cast("B")
            eof = False
            for seg_off, n, buf, fut in segs:
                got = await fut
                if eof:
                    continue               # drained for buffer safety only
                lo = max(0, offset - seg_off)
                hi = min(got, offset + length - seg_off)
                if hi > lo:
                    mv[filled:filled + hi - lo] = buf.view()[lo:hi]
                    filled += hi - lo
                if got < n:
                    eof = True
        finally:
            # a mid-loop error must not release buffers the kernel may
            # still be writing: wait out every in-flight segment first
            for _o, _n, buf, fut in segs:
                try:
                    await fut
                except Exception:  # noqa: BLE001 — buffer-reuse gate only
                    pass
                self.pool.release(buf)
        return filled

    async def pread(self, path: str, offset: int, length: int) -> bytes:
        import numpy as np
        buf = np.empty(length, dtype=np.uint8)
        got = await self.read_into(path, offset, buf)
        return buf[:got].tobytes()

    # ---------------- lifecycle / reporting ----------------

    def stats(self) -> dict:
        with self.stats_lock:
            out = dict(self.counters)
            out["fallbacks"] = dict(self.fallbacks)
        out["mode"] = self.mode
        out["queue_depth"] = self.queue_depth
        out["alignment"] = self.alignment
        return out

    def shutdown(self, wait: bool = True) -> None:
        """Stop the ring: in-flight submissions complete (their callers'
        futures resolve), queued-but-unstarted ones fail with
        EngineShutdown. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._q.put(None)
        if wait:
            for t in self._threads:
                t.join(timeout=10.0)
        # thread-pool mode leaves the sentinel cycling; drain leftovers
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                req.future.set_exception(
                    EngineShutdown("engine is shut down"))
        if self._ring is not None:
            self._ring.close()
            self._ring = None
        with self._fd_lock:
            for fd, _direct in self._fds.values():
                try:
                    os.close(fd)
                except OSError:
                    pass
            self._fds.clear()
        self.pool.drain()


def create_engine(conf) -> DirectIOEngine | None:
    """Build the worker's engine from WorkerConf; None when disabled or
    construction fails (callers keep the buffered path)."""
    if not getattr(conf, "direct_io", True):
        return None
    mode = getattr(conf, "direct_io_engine", "auto")
    if mode == "off":
        return None
    try:
        return DirectIOEngine(
            queue_depth=getattr(conf, "direct_io_queue_depth", 32),
            alignment=getattr(conf, "direct_io_alignment", 4096),
            threads=getattr(conf, "direct_io_threads", 2),
            engine=mode,
            segment_bytes=getattr(conf, "direct_io_segment", 1024 * 1024))
    except (OSError, ValueError) as e:
        log.warning("direct-IO engine unavailable (%s); SSD/HDD tiers "
                    "stay on the buffered path", e)
        return None
