from curvine_tpu.worker.server import WorkerServer

__all__ = ["WorkerServer"]
