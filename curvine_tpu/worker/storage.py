"""Tiered block storage.

Parity: curvine-server/src/worker/storage/ (vfs_dataset, vfs_dir, dir_state,
file_layout) + worker/block/block_store.rs. Tiers are ordered fastest-first
(MEM > SSD > HDD); a block is created on the fastest tier with room, spills
downward under pressure, and is evicted LRU when every tier is full.
Block files live in hashed subdirs (``<root>/<id % 256>/<id>.blk``), temp
files alongside (``.tmp``) renamed on commit — same layout discipline as
the reference's file_layout.rs."""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import BlockState, StorageInfo, StorageType

log = logging.getLogger(__name__)

_SUBDIRS = 256


@dataclass
class BlockInfo:
    block_id: int
    tier: "TierDir"
    len: int = 0
    state: BlockState = BlockState.TEMP
    atime: float = field(default_factory=time.time)
    crc32c: int | None = None     # content checksum recorded at commit
    crc_algo: str = "crc32c"      # crc32 (wire/zlib) or crc32c (native)
    # bdev layout: extent inside the tier's single backing file
    offset: int = 0
    alloc_len: int = 0
    heat: int = 0                 # reads since the last promotion scan
    verified_at: float = 0.0      # last successful scrub pass (0 = never)
    # writer's tenant id (qos TENANT_KEY off the RPC header): feeds the
    # per-tenant tier-0 occupancy gauges and the over-quota-first
    # eviction preference; "" for cluster-internal writes (replication,
    # EC cells, tier moves)
    tenant: str = ""

    @property
    def is_extent(self) -> bool:
        return isinstance(self.tier, BdevTier)

    @property
    def path(self) -> str:
        if self.is_extent:
            return self.tier.path
        suffix = ".tmp" if self.state == BlockState.TEMP else ".blk"
        return self.tier.block_path(self.block_id, suffix)


class DiskHealth:
    """Per-tier-directory health state machine (GFS/HDFS volume-failure
    discipline): decaying IO-error counts drive HEALTHY → SUSPECT; a
    background write/read/unlink probe (WorkerServer duty) either
    rehabilitates a SUSPECT dir or condemns it to QUARANTINED.
    Quarantined dirs advertise zero available capacity, are excluded
    from allocation / demotion / promotion, and the master evacuates
    their committed blocks. Quarantine is sticky for the process
    lifetime — a dir that failed its probes is not trusted again until
    an operator restarts the worker."""

    HEALTHY = "healthy"
    SUSPECT = "suspect"
    QUARANTINED = "quarantined"

    def __init__(self, error_threshold: int = 3, decay_s: float = 60.0,
                 probe_failures: int = 2, probe_successes: int = 3):
        self.state = self.HEALTHY
        self.error_threshold = max(1, error_threshold)
        self.decay_s = decay_s
        self.probe_failures = max(1, probe_failures)
        self.probe_successes = max(1, probe_successes)
        self.quarantined_at = 0.0
        self.errors_total = 0
        self._errors: list[float] = []    # recent error timestamps
        self._probe_fail = 0
        self._probe_ok = 0
        self._lock = threading.Lock()

    @property
    def healthy(self) -> bool:
        return self.state == self.HEALTHY

    @property
    def suspect(self) -> bool:
        return self.state == self.SUSPECT

    @property
    def quarantined(self) -> bool:
        return self.state == self.QUARANTINED

    def note_error(self, now: float | None = None) -> bool:
        """Record one IO error; True on the HEALTHY → SUSPECT edge."""
        now = time.time() if now is None else now
        with self._lock:
            self.errors_total += 1
            if self.state == self.QUARANTINED:
                return False
            cut = now - self.decay_s
            self._errors = [t for t in self._errors if t >= cut]
            self._errors.append(now)
            if self.state == self.HEALTHY \
                    and len(self._errors) >= self.error_threshold:
                self.state = self.SUSPECT
                self._probe_fail = self._probe_ok = 0
                return True
        return False

    def probe_result(self, ok: bool, now: float | None = None) -> str:
        """Fold one background-probe outcome in; returns the resulting
        state. Only SUSPECT dirs are probed — consecutive failures
        condemn, consecutive successes rehabilitate."""
        now = time.time() if now is None else now
        with self._lock:
            if self.state != self.SUSPECT:
                return self.state
            if ok:
                self._probe_ok += 1
                self._probe_fail = 0
                if self._probe_ok >= self.probe_successes:
                    self.state = self.HEALTHY
                    self._errors.clear()
            else:
                self._probe_fail += 1
                self._probe_ok = 0
                if self._probe_fail >= self.probe_failures:
                    self.state = self.QUARANTINED
                    self.quarantined_at = now
            return self.state


class TierDir:
    # direct-IO engine serving this tier's cold reads/copies (attached
    # by WorkerServer for SSD/HDD tiers; None → buffered path)
    io_engine = None
    # submission depth advertised to parallel readers (0 → engine default)
    io_queue_depth = 0

    def __init__(self, storage_type: StorageType, root: str, capacity: int,
                 dir_id: str = ""):
        self.storage_type = storage_type
        self.root = root
        self.capacity = capacity
        self.used = 0
        self.dir_id = dir_id or f"{storage_type.name.lower()}:{root}"
        self.health = DiskHealth()
        # admission policy (common/cache.py); BlockStore.__init__
        # replaces this per the configured worker.cache_admission
        from curvine_tpu.common.cache import LruPolicy
        self.policy = LruPolicy()
        os.makedirs(root, exist_ok=True)

    def block_path(self, block_id: int, suffix: str = ".blk") -> str:
        sub = os.path.join(self.root, f"{block_id % _SUBDIRS:02x}")
        os.makedirs(sub, exist_ok=True)
        return os.path.join(sub, f"{block_id}{suffix}")

    @property
    def probe_path(self) -> str:
        return os.path.join(self.root, ".cv_probe")

    @property
    def available(self) -> int:
        # a quarantined dir has no allocatable space: placement, spill
        # and promotion all key off this, and the heartbeat advertises
        # it so the master stops counting the capacity
        if self.health.quarantined:
            return 0
        return max(0, self.capacity - self.used)

    def info(self, block_num: int = 0) -> StorageInfo:
        return StorageInfo(storage_type=self.storage_type, dir_id=self.dir_id,
                           capacity=self.capacity, available=self.available,
                           block_num=block_num, health=self.health.state)


class BdevTier(TierDir):
    """Raw-device layout: blocks live as EXTENTS inside one preallocated
    backing file (or raw block device path) instead of one file per block
    — no per-block inode/dentry cost, sequential extents, O(1) allocation
    from a first-fit free list. Parity:
    curvine-server/src/worker/storage/layout/bdev_layout.rs.

    The allocation table persists in ``<path>.idx`` (msgpack, written
    atomically on commit/delete); uncommitted extents are reclaimed on
    restart like ``.tmp`` files in the file layout.

    LEASED extents are QUARANTINED on free: unlike the file layout,
    where POSIX unlink semantics keep an open fd valid after the block
    moves, a reused extent inside the shared backing file would hand a
    stale reader another block's bytes. Serving GET_BLOCK_INFO for an
    extent records a lease (quarantine_s / 2, after which the client
    must re-probe); freeing a still-live extent parks it in quarantine
    until the lease expires PLUS lease_slack_s (the client's lease
    clock starts at its request send; the slack absorbs any residual
    client/worker skew), while never-leased extents (fresh writes,
    aborted moves, never-probed victims) return to the free list
    immediately. The quarantine persists in the allocation index so a
    restart inside the window can't resurrect the space."""

    quarantine_s: float = 60.0
    # The client's lease clock starts when the GET_BLOCK_INFO reply
    # ARRIVES, not when the worker granted it — a reply delayed by load
    # or retries extends the window the client believes it may preadv
    # the extent. The slack must therefore cover the whole RPC deadline
    # (past it the client abandons the call and re-probes), not a fixed
    # local-clock fudge. Keep ≥ ClientConf.rpc_timeout_ms
    # (common/conf.py:118, 30s default).
    lease_slack_s: float = 30.0

    def __init__(self, storage_type: StorageType, path: str, capacity: int,
                 dir_id: str = ""):
        self.storage_type = storage_type
        self.path = path
        self.capacity = capacity
        self.used = 0
        self.dir_id = dir_id or f"bdev:{path}"
        self.health = DiskHealth()
        from curvine_tpu.common.cache import LruPolicy
        self.policy = LruPolicy()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if not os.path.exists(path):
            with open(path, "wb") as f:
                f.truncate(capacity)           # sparse preallocation
        # block_id -> (offset, alloc_len); free list of (offset, len)
        self.extents: dict[int, tuple[int, int]] = {}
        self._free: list[tuple[int, int]] = [(0, capacity)]
        # freed-but-not-yet-reusable extents:
        # (ready_time, off, len, block_id) — block_id lets reclaim skip
        # extents whose (deleted) block still has an active read pin
        self._quarantine: list[tuple[float, int, int, int]] = []
        self._quarantined = 0
        # block_id -> expiry of the latest short-circuit grant
        self._leases: dict[int, float] = {}

    def block_path(self, block_id: int, suffix: str = ".blk") -> str:
        raise err.Unsupported("bdev tier has no per-block files")

    @property
    def probe_path(self) -> str:
        # media-health probe rides a sidecar next to the backing file
        # (the backing file itself is the allocator's, extent-for-extent)
        return self.path + ".probe"

    @property
    def available(self) -> int:
        # pure read (heartbeat storages() reads it without the store
        # lock); BlockStore._reclaim_locked harvests expired quarantine
        # before every allocation/eviction decision
        if self.health.quarantined:
            return 0
        return max(0, self.capacity - self.used - self._quarantined)

    @property
    def lease_s(self) -> float:
        return self.quarantine_s / 2

    def note_lease(self, block_id: int, expiry: float) -> None:
        if expiry > self._leases.get(block_id, 0.0):
            self._leases[block_id] = expiry

    def free_would_quarantine(self, block_id: int,
                              now: float | None = None) -> bool:
        """True when freeing this block yields no allocatable space yet
        (an unexpired short-circuit lease forces quarantine) — eviction
        planning skips such victims: dropping them destroys data without
        helping the allocation that triggered the eviction."""
        if self.quarantine_s <= 0:
            return False
        now = time.time() if now is None else now
        # the client's lease clock starts at reply ARRIVAL: a lease
        # expired worker-side may still be live client-side for up to
        # the RPC deadline, so the liveness guard carries the same
        # slack as the quarantine duration
        return self._leases.get(block_id, 0.0) + self.lease_slack_s > now

    # ---- extent allocation (first-fit, merge on free) ----
    def reclaim(self, now: float | None = None,
                skip: frozenset | set = frozenset()) -> int:
        """Move expired quarantine entries back to the free list,
        leaving entries whose block id is in `skip` (active read pins)
        parked. Returns bytes reclaimed. Callers hold the store lock."""
        if not self._quarantine:
            return 0
        now = time.time() if now is None else now
        ready = [q for q in self._quarantine
                 if q[0] <= now and q[3] not in skip]
        if not ready:
            return 0
        taken = set(map(id, ready))
        self._quarantine = [q for q in self._quarantine
                            if id(q) not in taken]
        got = 0
        for _t, off, size, _bid in ready:
            self._free.append((off, size))
            self._quarantined -= size
            got += size
        self._merge_free()
        return got

    def _merge_free(self) -> None:
        # merge adjacent free extents (keeps the list from fragmenting)
        self._free.sort()
        merged: list[tuple[int, int]] = []
        for o, ln in self._free:
            if merged and merged[-1][0] + merged[-1][1] == o:
                merged[-1] = (merged[-1][0], merged[-1][1] + ln)
            else:
                merged.append((o, ln))
        self._free = merged

    def alloc(self, block_id: int, size: int) -> int:
        for i, (off, flen) in enumerate(self._free):
            if flen >= size:
                self.extents[block_id] = (off, size)
                if flen == size:
                    del self._free[i]
                else:
                    self._free[i] = (off + size, flen - size)
                self.used += size
                return off
        raise err.CapacityExceeded(
            f"{self.dir_id}: no extent of {size}B free")

    def free(self, block_id: int) -> None:
        ext = self.extents.pop(block_id, None)
        if ext is None:
            return
        off, size = ext
        self.used -= size
        lease = self._leases.pop(block_id, 0.0)
        now = time.time()
        if self.quarantine_s > 0 and lease + self.lease_slack_s > now:
            # an unexpired short-circuit grant may still read this
            # extent through a cached fd: unusable until the lease
            # passes PLUS the RPC deadline (the client's lease clock
            # starts at reply arrival, which can lag the grant by up to
            # the full RPC timeout)
            self._quarantine.append(
                (lease + self.lease_slack_s, off, size, block_id))
            self._quarantined += size
        else:
            self._free.append((off, size))
            self._merge_free()

    def quarantine_block(self, block_id: int) -> None:
        """Free a block's extent while an in-process reader still holds
        a pin on it (delete-mid-stream): the extent goes straight to
        quarantine — persisted via save_index, so a crash before the pin
        drops can't resurrect the space — and reclaim skips it while the
        pin lives."""
        ext = self.extents.pop(block_id, None)
        if ext is None:
            return
        off, size = ext
        self.used -= size
        lease = self._leases.pop(block_id, 0.0)
        ready = max(time.time() + max(self.quarantine_s, 1.0),
                    lease + self.lease_slack_s)
        self._quarantine.append((ready, off, size, block_id))
        self._quarantined += size

    # ---- persistent allocation table ----
    @property
    def index_path(self) -> str:
        return self.path + ".idx"

    def save_index(self, blocks: dict) -> None:
        """blocks: block_id -> BlockInfo (committed, this tier)."""
        import msgpack
        table = {b.block_id: [b.offset, b.alloc_len, b.len,
                              b.crc32c, b.crc_algo]
                 for b in blocks.values()
                 if b.tier is self and b.state == BlockState.COMMITTED}
        tmp = self.index_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb({"capacity": self.capacity,
                                   "blocks": table,
                                   # live quarantine rides the index: a
                                   # restart inside the window must not
                                   # resurrect leased space
                                   "quarantine": self._quarantine}))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.index_path)

    def load_index(self) -> dict[int, tuple[int, int, int, int | None, str]]:
        import msgpack
        try:
            with open(self.index_path, "rb") as f:
                d = msgpack.unpackb(f.read(), raw=False,
                                    strict_map_key=False)
        except (FileNotFoundError, ValueError, msgpack.UnpackException):
            return {}
        out = {}
        now = time.time()
        for bid, (off, alen, ln, crc, algo) in d.get("blocks", {}).items():
            bid = int(bid)
            self.extents[bid] = (off, alen)
            out[bid] = (off, alen, ln, crc, algo)
            # leases don't survive the restart, but the fds they cover
            # might: assume every surviving block was granted one just
            # before the crash, so an early free still quarantines
            if self.quarantine_s > 0:
                self._leases[bid] = now + self.lease_s
        # restore the unexpired quarantine (reclaim() harvests the rest;
        # pins don't survive a restart, so the ids only matter pre-crash)
        self._quarantine = [
            (t, off, ln, bid)
            for t, off, ln, bid in d.get("quarantine", []) if t > now]
        self._quarantined = sum(ln for _t, _o, ln, _b in self._quarantine)
        quarantined = {(off, ln) for _t, off, ln, _b in self._quarantine}
        # rebuild the free list from the allocated + quarantined extents
        occupied = sorted(list(self.extents.values()) + list(quarantined))
        self._free = []
        pos = 0
        for off, alen in occupied:
            if off > pos:
                self._free.append((pos, off - pos))
            pos = max(pos, off + alen)
        if pos < self.capacity:
            self._free.append((pos, self.capacity - pos))
        self.used = sum(alen for _, alen in self.extents.values())
        return out


class BlockStore:
    """Thread-safe tiered store (handlers run on the event loop; file IO in
    worker threads)."""

    def __init__(self, tiers: list[TierDir], high_water: float = 0.95,
                 low_water: float = 0.80, admission: str = "lru",
                 ghost_entries: int = 8192, small_ratio: float = 0.1):
        if not tiers:
            raise err.InvalidArgument("worker needs at least one tier")
        self.tiers = sorted(tiers, key=lambda t: int(t.storage_type))
        # per-tier-dir admission policy (common/cache.py): ghost-cache
        # scan resistance applies to the MEM-and-faster tiers (the ones
        # a backfill scan can flush); capacity tiers keep plain LRU —
        # their victims demote/drop by age and scans pass through anyway
        from curvine_tpu.common.cache import make_policy
        self.admission = admission
        for t in self.tiers:
            kind = admission if int(t.storage_type) <= int(StorageType.MEM) \
                else "lru"
            t.policy = make_policy(kind, ghost_entries=ghost_entries,
                                   small_ratio=small_ratio)
        # tier-0 byte quota per tenant (worker/server.py wires this to
        # the qos plane's tenant specs): callable tenant -> bytes|None.
        # None (no hook / no quota) keeps eviction order byte-identical.
        self.tier0_quota = None
        self.miss_total = 0           # lookups of blocks we don't hold
        self.blocks: dict[int, BlockInfo] = {}
        self.high_water = high_water
        self.low_water = low_water
        self.started_at = time.time()
        # disk-level fault injection (fault/disk.DiskFaultInjector);
        # None in production — storms and tests install one
        self.fault_hook = None
        # block-removal hook (worker/shm.py ShmExporter.invalidate): a
        # deleted/evicted block must drop its sealed-memfd export so a
        # stale copy is never handed to a new client. Fired under the
        # store lock; the callback must not call back into the store.
        self.on_delete = None
        # tier-move hook (same contract as on_delete — fired under the
        # store lock in _move_block's swap phase, must not re-enter the
        # store): a promoted/demoted block drops its shm exports, since
        # the copy was admitted under the OLD tier's policy and a
        # below-MEM warm copy must never outlive the block's tier
        # residency (docs/data-plane.md)
        self.on_move = None
        # last scrub cycle's outcome counts (metrics exporter reads it)
        self.scrub_last = {"verified": 0, "mismatch": 0, "truncated": 0,
                           "io_error": 0}
        # last cycle's per-block verdicts (block_id -> "mismatch" |
        # "truncated"): rides the corrupt-block report so the master
        # picks the repair path — a truncated copy is re-pulled whole,
        # a bit-rotten EC cell is re-encoded from its siblings
        self.scrub_verdicts: dict[int, str] = {}
        self._lock = threading.Lock()
        # block ids mid-tier-move (copy runs lock-free; see _move_block)
        self._moving: set[int] = set()
        # active in-process readers per block (worker streaming reads,
        # HBM autopin): a pinned bdev-resident block is never moved, so
        # its extent can't be freed and reused under the reader
        self._read_pins: dict[int, int] = {}
        # lifetime tier-movement stats (dropped = data actually left the
        # cache; demoted/promoted = moved between tiers, nothing lost)
        self.dropped_total = 0
        self.demoted_total = 0
        self.promoted_total = 0
        self._load_existing()

    def _load_existing(self) -> None:
        """Rebuild the index from disk (worker restart)."""
        for tier in self.tiers:
            if isinstance(tier, BdevTier):
                for bid, (off, alen, ln, crc, algo) in \
                        tier.load_index().items():
                    self.blocks[bid] = BlockInfo(
                        block_id=bid, tier=tier, len=ln,
                        state=BlockState.COMMITTED, crc32c=crc,
                        crc_algo=algo or "crc32", offset=off,
                        alloc_len=alen)
                continue
            for sub in os.listdir(tier.root):
                subdir = os.path.join(tier.root, sub)
                if not os.path.isdir(subdir):
                    continue
                for name in os.listdir(subdir):
                    full = os.path.join(subdir, name)
                    if name.endswith((".tmp", ".mov")):
                        os.unlink(full)  # torn write/move from a prior run
                        continue
                    if not name.endswith(".blk"):
                        continue
                    bid = int(name[:-4])
                    size = os.path.getsize(full)
                    self.blocks[bid] = BlockInfo(block_id=bid, tier=tier,
                                                 len=size,
                                                 state=BlockState.COMMITTED)
                    tier.used += size
        if self.blocks:
            log.info("block store recovered %d blocks", len(self.blocks))

    # ---------- lifecycle ----------
    def pick_tier(self, hint: StorageType | None, size_hint: int) -> TierDir:
        # Preferred tier first, then any tier fastest-first with room.
        # Quarantined dirs never allocate — their blocks are being
        # evacuated, writing new data there would feed the failure.
        self._reclaim_locked()
        ordered = [t for t in self.tiers if not t.health.quarantined]
        if not ordered:
            raise err.CapacityExceeded("all tier dirs quarantined")
        if hint is not None:
            ordered = ([t for t in ordered if t.storage_type == hint]
                       + [t for t in ordered if t.storage_type != hint])
        for tier in ordered:
            if tier.available >= size_hint:
                return tier
        # Under pressure: evict on the preferred tier, then fall through
        # to the others — a bdev tier whose victims are all leased (e.g.
        # every surviving block right after a restart, load_index grants
        # synthetic leases) frees nothing until the leases lapse, and
        # writes must not bounce off the whole worker because one tier
        # is temporarily unevictable.
        for tier in ordered:
            self._evict_locked(tier, size_hint)
            if tier.available >= size_hint:
                return tier
        tried = ", ".join(f"{t.dir_id}={t.available}" for t in ordered)
        # Transient shortfall: a bdev tier whose room is merely parked in
        # unexpired quarantine or behind lease-encumbered victims (the
        # whole tier right after a restart — load_index grants synthetic
        # leases) WILL clear within lease_s + slack. Surface that as the
        # retryable CapacityPending so writers back off and re-place
        # instead of hard-failing for the window.
        now = time.time()
        for tier in ordered:
            if not isinstance(tier, BdevTier):
                continue
            pending = tier._quarantined + sum(
                b.alloc_len for b in self.blocks.values()
                if b.tier is tier and b.state == BlockState.COMMITTED
                and b.block_id not in self._moving
                and not self._read_pins.get(b.block_id)
                and tier.free_would_quarantine(b.block_id, now))
            if tier.available + pending >= size_hint:
                raise err.CapacityPending(
                    f"need {size_hint}B on {tier.dir_id}: {pending}B "
                    f"lease-encumbered/quarantined, clears within "
                    f"~{tier.lease_s + tier.lease_slack_s:.0f}s")
        raise err.CapacityExceeded(
            f"need {size_hint}B, all tiers tried after eviction: {tried}")

    def create_temp(self, block_id: int, hint: StorageType | None = None,
                    size_hint: int = 0, tenant: str = "") -> BlockInfo:
        with self._lock:
            if block_id in self._moving:
                # a tier move holds this id's paths/extents; a new
                # incarnation now would collide with the move's phase-3
                # cleanup (id-reuse data loss). Caller retries.
                raise err.FileAlreadyExists(
                    f"block {block_id} busy (tier move in flight)")
            if block_id in self.blocks:
                old = self.blocks[block_id]
                if old.state == BlockState.COMMITTED:
                    raise err.FileAlreadyExists(f"block {block_id} committed")
                self._remove_locked(old)
            tier = self.pick_tier(hint, size_hint)
            info = BlockInfo(block_id=block_id, tier=tier, tenant=tenant)
            if isinstance(tier, BdevTier):
                # extents are fixed at allocation: the client's len_hint
                # (block_size) bounds the block
                size = size_hint or 64 * 1024 * 1024
                info.offset = tier.alloc(block_id, size)
                info.alloc_len = size
            self.blocks[block_id] = info
            return info

    def commit(self, block_id: int, length: int,
               checksum: int | None = None,
               checksum_algo: str = "crc32") -> BlockInfo:
        """`checksum` is the streaming checksum already computed on the
        write path (no re-read); absent → computed natively from disk."""
        with self._lock:
            info = self._get_locked(block_id)
            if info.state == BlockState.COMMITTED:
                return info
            if info.is_extent:
                if length > info.alloc_len:
                    raise err.CapacityExceeded(
                        f"block {block_id}: {length}B > extent "
                        f"{info.alloc_len}B")
                info.state = BlockState.COMMITTED
                info.len = length
                # used was accounted at alloc; index persists below
            else:
                tmp = info.path
                info.state = BlockState.COMMITTED
                info.len = length
                os.replace(tmp, info.path)
                info.tier.used += length
        if checksum is None:
            # file IO outside the lock; fields published under it
            from curvine_tpu.common import native
            checksum = native.checksum_file(info.path, info.offset, length)
            checksum_algo = "crc32c"
        with self._lock:
            info.crc32c = checksum
            info.crc_algo = checksum_algo
            info.tier.policy.on_admit(block_id, length)
            if info.is_extent:
                # ONE index write per commit, under the lock (save_index
                # iterates self.blocks, which eviction mutates under it)
                info.tier.save_index(self.blocks)
        return info

    def verify(self, block_id: int) -> bool:
        """Re-checksum a committed block against its commit-time value."""
        ok, _reason = self.verify_detail(block_id)
        return ok

    def verify_detail(self, block_id: int) -> tuple[bool, str]:
        """Re-checksum a committed block; (ok, reason) where reason is
        "ok", "mismatch" (bit-rot: the full length read back but hashed
        wrong) or "truncated" (a torn write / shrunk file: fewer bytes
        than committed) — operators triage the two very differently.
        OSError from the media (including injected faults) propagates to
        the caller, which feeds the dir health machinery."""
        import zlib
        from curvine_tpu.common import native
        info = self.get(block_id, touch=False)
        if info.state != BlockState.COMMITTED or info.crc32c is None:
            return True, "ok"
        hook = self.fault_hook
        if hook is not None:
            hook.check_read(info.path)
        # file-layout blocks can cheaply pre-detect truncation; extent
        # blocks live inside the shared backing file, so the read loop's
        # short-read check is the only signal there
        if not info.is_extent:
            try:
                size = os.path.getsize(info.path)
            except FileNotFoundError:
                return False, "truncated"
            if size < info.len:
                return False, "truncated"
        use_native = info.crc_algo != "crc32" \
            and (hook is None or not hook.wants_read_data(info.path))
        if use_native:
            got = native.checksum_file(info.path, info.offset, info.len or 0)
            return got == info.crc32c, \
                ("ok" if got == info.crc32c else "mismatch")
        # chunked python read: streaming crc (zlib for crc32, the native
        # helper's incremental crc32c otherwise) with the fault hook
        # applied per chunk so injected bit-flips are observable
        crc = 0
        left = info.len
        with open(info.path, "rb") as f:
            f.seek(info.offset)
            while left > 0:
                chunk = f.read(min(1 << 20, left))
                if not chunk:
                    return False, "truncated"
                if hook is not None and hook.wants_read_data(info.path):
                    buf = bytearray(chunk)
                    hook.mutate_read(info.path, buf)
                    chunk = bytes(buf)
                crc = (zlib.crc32(chunk, crc)
                       if info.crc_algo == "crc32"
                       else native.crc32c(chunk, crc))
                left -= len(chunk)
        return crc == info.crc32c, \
            ("ok" if crc == info.crc32c else "mismatch")

    def scrub(self, limit: int = 16) -> list[int]:
        """Verify up to `limit` least-recently-verified blocks; corrupt
        blocks are REPORTED but kept — only the master may order the
        delete, and only once another live replica exists. Deleting
        locally would destroy the last copy when the mismatch is a
        transient read fault (or every other holder is down); a kept
        corrupt replica is harmless because readers verify and refuse
        it. Parity: the reference's abnormal-data detection on the
        worker data path. `scrub_last` holds the last cycle's verified /
        mismatch / truncated / io_error counts for the metrics
        exporter."""
        with self._lock:
            candidates = [b.block_id for b in sorted(
                (b for b in self.blocks.values()
                 if b.state == BlockState.COMMITTED
                 and b.crc32c is not None),
                key=lambda b: b.verified_at)[:limit]]
        stats = {"verified": 0, "mismatch": 0, "truncated": 0,
                 "io_error": 0}
        corrupt = []
        verdicts: dict[int, str] = {}
        for bid in candidates:
            try:
                ok, reason = self.verify_detail(bid)
            except err.CurvineError:
                continue
            except OSError as e:
                # the media refused the read: not evidence of bit-rot —
                # keep the block, count the error against the dir health
                stats["io_error"] += 1
                with self._lock:
                    b = self.blocks.get(bid)
                    tier = b.tier if b is not None else None
                if tier is not None:
                    tier.health.note_error()
                log.warning("scrub read of block %d failed: %s", bid, e)
                continue
            if ok:
                stats["verified"] += 1
                with self._lock:
                    b = self.blocks.get(bid)
                    if b is not None:
                        b.verified_at = time.time()
                continue
            log.error("block %d failed checksum scrub (%s); reporting "
                      "to master (kept until a clean replica exists)",
                      bid, reason)
            stats[reason] += 1
            # stamp it checked so the rotation moves on — re-reporting
            # is bounded to once per full scrub sweep
            with self._lock:
                b = self.blocks.get(bid)
                if b is not None:
                    b.verified_at = time.time()
            corrupt.append(bid)
            verdicts[bid] = reason
        self.scrub_last = stats
        self.scrub_verdicts = verdicts
        return corrupt

    def get(self, block_id: int, touch: bool = True) -> BlockInfo:
        with self._lock:
            info = self._get_locked(block_id)
            if touch:
                info.atime = time.time()
                info.heat += 1
                info.tier.policy.hits += 1
                info.tier.policy.on_access(block_id)
            return info

    def touch_reads(self, block_id: int, reads: int) -> None:
        """Account reads that bypassed get() — short-circuit clients hit
        the store once per open (the GET_BLOCK_INFO probe) and then read
        through a cached fd; they report per-block read counters on
        heartbeat so heat/atime reflect actual traffic and promotion
        targets the right blocks."""
        with self._lock:
            info = self.blocks.get(block_id)
            if info is not None and reads > 0:
                info.atime = time.time()
                info.heat += reads
                info.tier.policy.hits += reads
                info.tier.policy.on_access(block_id)

    def pin_read(self, block_id: int, touch: bool = True) -> BlockInfo:
        """Atomically look up a block and take a read pin on it; pair
        with unpin_read(). While pinned, tier moves of bdev-resident
        blocks are refused (_move_block), so the extent under an active
        reader can never be freed and reallocated mid-stream."""
        with self._lock:
            info = self._get_locked(block_id)
            if touch:
                info.atime = time.time()
                info.heat += 1
                info.tier.policy.hits += 1
                info.tier.policy.on_access(block_id)
            self._read_pins[block_id] = self._read_pins.get(block_id, 0) + 1
            return info

    def unpin_read(self, block_id: int) -> None:
        with self._lock:
            n = self._read_pins.get(block_id, 0) - 1
            if n <= 0:
                self._read_pins.pop(block_id, None)
            else:
                self._read_pins[block_id] = n

    def grant_sc(self, block_id: int) -> tuple[BlockInfo, int]:
        """Short-circuit grant: look up the block and, for bdev
        extents, record the lease ATOMICALLY with the lookup (a free
        slipping between get() and note_lease would lease an extent
        already on the free list). Returns (info, lease_ms) —
        lease_ms 0 for file-layout blocks (unlink semantics, no lease
        needed)."""
        with self._lock:
            info = self._get_locked(block_id)
            info.atime = time.time()
            info.heat += 1
            info.tier.policy.hits += 1
            info.tier.policy.on_access(block_id)
            lease_ms = 0
            if isinstance(info.tier, BdevTier) \
                    and info.tier.quarantine_s > 0:
                ls = info.tier.lease_s
                info.tier.note_lease(block_id, time.time() + ls)
                lease_ms = int(ls * 1000)
            return info, lease_ms

    def _reclaim_locked(self) -> None:
        """Harvest expired bdev quarantine before any allocation or
        eviction decision, skipping extents whose (deleted) block still
        has an active read pin."""
        pinned = set(self._read_pins)
        for t in self.tiers:
            if isinstance(t, BdevTier):
                t.reclaim(skip=pinned)

    def contains(self, block_id: int) -> bool:
        return block_id in self.blocks

    def delete(self, block_id: int) -> None:
        with self._lock:
            info = self.blocks.get(block_id)
            if info is not None:
                self._remove_locked(info)

    def _remove_locked(self, info: BlockInfo, evicted: bool = False) -> None:
        # `evicted` = removal under cache pressure (trim/evict): the id
        # enters the policy's ghost queue so a near-future re-admission
        # skips probation. Plain deletes/overwrites never ghost.
        info.tier.policy.on_remove(info.block_id, evicted=evicted)
        if self.on_delete is not None:
            try:
                self.on_delete(info.block_id)
            except Exception:  # noqa: BLE001 — removal must proceed
                pass
        if info.is_extent:
            if self._read_pins.get(info.block_id):
                # an active stream holds (fd, offset) into the backing
                # file: park the extent in quarantine (persisted below);
                # reclaim skips it while the pin lives
                info.tier.quarantine_block(info.block_id)
            else:
                info.tier.free(info.block_id)  # adjusts used by alloc_len
            self.blocks.pop(info.block_id, None)
            if info.state == BlockState.COMMITTED:
                info.tier.save_index(self.blocks)
            return
        try:
            os.unlink(info.path)
        except FileNotFoundError:
            pass
        except OSError as e:
            # a dying disk may refuse even the unlink: drop the index
            # entry anyway (GET_BLOCK_INFO must stop serving the block)
            # and let the health machinery see the error
            log.warning("unlink of %s failed: %s", info.path, e)
            info.tier.health.note_error()
        if info.tier.io_engine is not None:
            # drop the engine's cached fd: a recreated block at this
            # path must never be served from the unlinked file
            info.tier.io_engine.forget(info.path)
        if info.state == BlockState.COMMITTED:
            info.tier.used -= info.len
        self.blocks.pop(info.block_id, None)

    def _get_locked(self, block_id: int) -> BlockInfo:
        info = self.blocks.get(block_id)
        if info is None:
            self.miss_total += 1
            raise err.BlockNotFound(f"block {block_id}")
        return info

    # ---------- tier movement ----------
    @staticmethod
    def _copy_bytes(sf, df, block_id: int, length: int, src_id: str) -> None:
        left = length
        while left > 0:
            chunk = sf.read(min(4 << 20, left))
            if not chunk:
                raise err.AbnormalData(
                    f"block {block_id} truncated on {src_id}")
            df.write(chunk)
            left -= len(chunk)

    @staticmethod
    def _copy_bytes_direct(engine, src_path: str, src_off: int, df,
                           block_id: int, length: int, src_id: str) -> None:
        """Tier-move source read through the direct-IO engine: the cold
        copy bypasses the page cache instead of evicting MEM-tier pages
        to stage a block that is LEAVING the fast tiers. Runs on a
        worker thread (pread_sync blocks on the ring's completion)."""
        done = 0
        while done < length:
            n = min(4 << 20, length - done)
            chunk = engine.pread_sync(src_path, src_off + done, n)
            if not chunk:
                raise err.AbnormalData(
                    f"block {block_id} truncated on {src_id}")
            df.write(chunk)
            done += len(chunk)

    def _move_block(self, block_id: int, dest: TierDir) -> bool:
        """Move a committed block's bytes to `dest` and swap the index
        entry. Returns False (leaving the block where it is) when dest
        lacks room or the block changed underneath. The byte copy runs
        WITHOUT the store lock (a multi-MB copy must not stall every
        other block op on the worker): space is reserved under the lock,
        the copy streams lock-free, and the swap revalidates under the
        lock — a block deleted or evicted mid-copy just discards the new
        copy. Readers holding an fd on the old file keep a complete,
        consistent view (POSIX unlink semantics); new opens resolve the
        new location via GET_BLOCK_INFO."""
        # Phase 1 (locked): validate + reserve destination space.
        with self._lock:
            self._reclaim_locked()
            info = self.blocks.get(block_id)
            if info is None or info.state != BlockState.COMMITTED \
                    or info.tier is dest or block_id in self._moving:
                return False
            if self._read_pins.get(block_id):
                # an active in-process reader snapshots (path, offset)
                # lock-free; a move would tear that pair under it — for
                # a bdev source it would even free the extent mid-read.
                # Refuse moves of ANY pinned block.
                return False
            src_path, src_off, src_tier = info.path, info.offset, info.tier
            length = info.len
            if dest.available < length:
                return False
            if isinstance(dest, BdevTier):
                try:
                    new_off = dest.alloc(block_id, length)
                except err.CapacityExceeded:   # fragmented free list
                    return False
                new_alloc = length
            else:
                dest.used += length            # reservation
                new_off, new_alloc = 0, 0
            self._moving.add(block_id)

        def release_dest():
            if isinstance(dest, BdevTier):
                dest.free(block_id)
            else:
                dest.used -= length

        # Phase 2 (unlocked): stream the bytes. A source tier with a
        # direct-IO engine reads O_DIRECT — promote/demote staging must
        # not flush the page cache the MEM tier and FUSE warm path use.
        engine = src_tier.io_engine
        try:
            with open(src_path, "rb") as sf:
                sf.seek(src_off)

                def copy_to(df) -> None:
                    if engine is not None:
                        self._copy_bytes_direct(engine, src_path, src_off,
                                                df, block_id, length,
                                                src_tier.dir_id)
                    else:
                        self._copy_bytes(sf, df, block_id, length,
                                         src_tier.dir_id)

                if isinstance(dest, BdevTier):
                    with open(dest.path, "r+b") as df:
                        df.seek(new_off)
                        copy_to(df)
                else:
                    dst_path = dest.block_path(block_id, ".mov")
                    with open(dst_path, "wb") as df:
                        copy_to(df)
                    os.replace(dst_path, dest.block_path(block_id, ".blk"))
        except (OSError, err.CurvineError) as e:
            log.warning("move block %d %s -> %s failed: %s", block_id,
                        src_tier.dir_id, dest.dir_id, e)
            if not isinstance(dest, BdevTier):
                try:     # don't leak the partial copy
                    os.unlink(dest.block_path(block_id, ".mov"))
                except OSError:
                    pass
            with self._lock:
                release_dest()
                self._moving.discard(block_id)
            return False

        # Phase 3 (locked): revalidate and swap, or discard the copy.
        # create_temp refuses ids in _moving, so no NEW incarnation of
        # this block can exist yet — the cleanup below only ever removes
        # OUR copy.
        with self._lock:
            self._moving.discard(block_id)
            info = self.blocks.get(block_id)
            if info is None or info.state != BlockState.COMMITTED \
                    or info.tier is not src_tier or info.len != length \
                    or self._read_pins.get(block_id):
                # deleted/evicted mid-copy, or a reader pinned the
                # source during the lock-free copy (swapping tier/offset
                # would tear the pair under their preadv; a bdev source
                # would even free the extent): ours is the stale copy
                release_dest()
                if not isinstance(dest, BdevTier):
                    try:
                        os.unlink(dest.block_path(block_id, ".blk"))
                    except OSError:
                        pass
                return False
            was_extent = info.is_extent
            if was_extent:
                src_tier.free(block_id)
            else:
                try:
                    os.unlink(src_path)
                except FileNotFoundError:
                    pass
                if src_tier.io_engine is not None:
                    src_tier.io_engine.forget(src_path)
                src_tier.used -= length
            # dest accounting already reserved; just swap the entry.
            # Policy handoff: a demotion is an eviction from the fast
            # tier's viewpoint (ghost-eligible — a re-heated block skips
            # probation on its way back up); a promotion is not.
            demoting = int(dest.storage_type) > int(src_tier.storage_type)
            src_tier.policy.on_remove(block_id, evicted=demoting)
            dest.policy.on_admit(block_id, length)
            if self.on_move is not None:
                try:
                    self.on_move(block_id)
                except Exception:  # noqa: BLE001 — the move must land
                    pass
            info.tier, info.offset, info.alloc_len = dest, new_off, new_alloc
            if was_extent:
                src_tier.save_index(self.blocks)
            if isinstance(dest, BdevTier):
                dest.save_index(self.blocks)
            return True

    def _move_candidates_locked(self, tier: TierDir, need: int,
                                demote: bool) -> tuple[list, int, int]:
        """Under the lock: pick LRU victims on `tier` until `need` (or the
        low-water trim target) fits, deciding drop-vs-demote per victim.
        Returns (plan, target_free, projected) where plan is
        [(block_id, dest|None)] — dest None means drop — and projected
        is the bytes free on `tier` if the whole plan executes."""
        self._reclaim_locked()
        target_free = max(need, int(tier.capacity * (1 - self.low_water)))
        now = time.time()
        eligible = [
            b for b in self.blocks.values()
            if b.tier is tier and b.state == BlockState.COMMITTED
            and b.block_id not in self._moving
            # never evict a block with an active reader, and skip
            # leased bdev extents entirely: their free lands in
            # quarantine, so dropping destroys data without making
            # room and demoting burns copy IO for zero freed bytes —
            # the lease lapses within lease_s + lease_slack_s and the
            # next scan takes them
            and not self._read_pins.get(b.block_id)
            and not (isinstance(tier, BdevTier)
                     and tier.free_would_quarantine(b.block_id, now))]
        order = tier.policy.victim_order(
            [(b.block_id, b.atime) for b in eligible])
        by_id = {b.block_id: b for b in eligible}
        victims = [by_id[k] for k in order if k in by_id]
        victims = self._quota_first(tier, victims)
        plan: list[tuple[int, TierDir | None]] = []
        freed = tier.available
        for b in victims:
            if freed >= target_free:
                break
            dest = self._slower_tier_for(tier, b.len) if demote else None
            plan.append((b.block_id, dest))
            freed += b.len if not isinstance(tier, BdevTier) else b.alloc_len
        return plan, target_free, freed

    def _quota_first(self, tier: TierDir, victims: list) -> list:
        """Per-job cache partitions: on tier-0 (MEM and faster), blocks
        of tenants over their tier-0 byte quota are evicted before
        anyone else's — a bulk export that blew past its partition pays
        for the pressure it created, in policy order within each group.
        No quota hook / nobody over quota → order untouched."""
        if self.tier0_quota is None \
                or int(tier.storage_type) > int(StorageType.MEM):
            return victims
        occ = self._tenant_occupancy_locked()
        over = set()
        for tenant, used in occ.items():
            q = self.tier0_quota(tenant)
            if q is not None and q > 0 and used > q:
                over.add(tenant)
        if not over:
            return victims
        return ([b for b in victims if b.tenant in over]
                + [b for b in victims if b.tenant not in over])

    def _tenant_occupancy_locked(self) -> dict[str, int]:
        occ: dict[str, int] = {}
        for b in self.blocks.values():
            if b.state == BlockState.COMMITTED \
                    and int(b.tier.storage_type) <= int(StorageType.MEM):
                occ[b.tenant or "default"] = \
                    occ.get(b.tenant or "default", 0) + b.len
        return occ

    def tenant_occupancy(self) -> dict[str, int]:
        """Committed tier-0 (MEM and faster) bytes per tenant — the
        per-tenant occupancy gauges behind the cache partitions."""
        with self._lock:
            return self._tenant_occupancy_locked()

    def cache_stats(self) -> dict[str, dict[str, int]]:
        """Per-tier-dir admission/hit counters plus a store-wide rollup
        (the worker heartbeats the rollup; `cv report` prints it)."""
        with self._lock:
            out: dict[str, dict[str, int]] = {}
            total: dict[str, int] = {}
            for t in self.tiers:
                s = t.policy.stats()
                out[t.dir_id] = s
                for k, v in s.items():
                    if k in ("small", "main", "ghost"):
                        continue
                    total[k] = total.get(k, 0) + v
            total["misses"] = total.get("misses", 0) + self.miss_total
            out["total"] = total
            return out

    def _slower_tier_for(self, tier: TierDir, size: int) -> TierDir | None:
        """Next tier strictly slower than `tier` with room for `size`.
        Quarantined dirs are never demotion targets."""
        for t in self.tiers:
            if int(t.storage_type) > int(tier.storage_type) \
                    and not t.health.quarantined and t.available >= size:
                return t
        return None

    # ---------- eviction / demotion ----------
    def _evict_locked(self, tier: TierDir, need: int) -> list[int]:
        """Drop-only LRU trim, for callers already holding the lock (the
        synchronous create path): when this fires every tier is full, so
        there is no demotion target anyway — dropping is the only move,
        and it must not stall the write behind multi-MB copies.

        A plan that cannot reach `need` is NOT executed: destroying
        cached blocks without unblocking the allocation that asked is
        pure cache loss (pick_tier falls through to the next tier
        instead)."""
        plan, _target, projected = self._move_candidates_locked(
            tier, need, demote=False)
        if projected < need:
            return []
        evicted = []
        for bid, _dest in plan:
            info = self.blocks.get(bid)
            if info is None:
                continue
            self._remove_locked(info, evicted=True)
            evicted.append(bid)
            self.dropped_total += 1
        if evicted:
            log.info("evicted %d blocks from %s", len(evicted), tier.dir_id)
        return evicted

    def trim(self, tier: TierDir, need: int,
             demote: bool = True) -> list[int]:
        """LRU-trim committed blocks from `tier` until `need` fits or the
        low-water mark is reached. Cold blocks spill DOWN to the next
        slower tier with room (demotion); only when no slower tier can
        take them are they dropped. Byte copies run without the store
        lock (see _move_block). Returns ids no longer on `tier`."""
        removed, demoted = [], 0
        for _attempt in range(2):      # one retry if planned moves failed
            with self._lock:
                plan, target, _projected = self._move_candidates_locked(
                    tier, need, demote)
            if not plan:
                break
            progress = False
            for bid, dest in plan:
                with self._lock:
                    if tier.available >= target:
                        break
                if dest is not None and self._move_block(bid, dest):
                    removed.append(bid)
                    demoted += 1
                    progress = True
                    continue
                if demote:
                    # the planned destination filled up (the plan shares
                    # one availability snapshot) or the copy failed:
                    # replan against LIVE availability before giving up
                    with self._lock:
                        info = self.blocks.get(bid)
                        dest2 = (self._slower_tier_for(tier, info.len)
                                 if info is not None
                                 and info.tier is tier else None)
                    if dest2 is not None:
                        if dest2 is not dest and \
                                self._move_block(bid, dest2):
                            removed.append(bid)
                            demoted += 1
                            progress = True
                        # a demotion target EXISTS but the copy failed
                        # (transient IO): never destroy a healthy replica
                        # over that — leave the block for the next scan
                        continue
                with self._lock:
                    info = self.blocks.get(bid)
                    if info is not None and info.tier is tier \
                            and info.state == BlockState.COMMITTED \
                            and bid not in self._moving \
                            and not self._read_pins.get(bid) \
                            and not (isinstance(tier, BdevTier)
                                     and tier.free_would_quarantine(bid)):
                        # same futile-drop guard as the planner: a leased
                        # extent's free lands in quarantine — destroying
                        # data without making room
                        self._remove_locked(info, evicted=True)
                        removed.append(bid)
                        self.dropped_total += 1
                        progress = True
            with self._lock:
                if tier.available >= target:
                    break
            if not progress:
                break
        if removed:
            with self._lock:
                self.demoted_total += demoted
            log.info("trimmed %d blocks from %s (%d demoted, %d dropped)",
                     len(removed), tier.dir_id, demoted,
                     len(removed) - demoted)
        return removed

    def maybe_evict(self) -> list[int]:
        """Background check: any tier above high-water gets trimmed."""
        out = []
        for tier in self.tiers:
            with self._lock:
                over = tier.capacity \
                    and tier.used > tier.capacity * self.high_water
            if over:
                out.extend(self.trim(tier, 0))
        return out

    def hot_blocks(self, min_reads: int,
                   max_len: int | None = None) -> list[tuple[int, int, int]]:
        """Snapshot of committed blocks with heat >= min_reads, hottest
        first, as (block_id, heat, len) — the single source of the
        promotion predicate for both the host-tier scan and the worker's
        HBM auto-pin."""
        with self._lock:
            return sorted(
                ((b.block_id, b.heat, b.len)
                 for b in self.blocks.values()
                 if b.state == BlockState.COMMITTED
                 and b.heat >= min_reads
                 and (max_len is None or b.len <= max_len)),
                key=lambda t: t[1], reverse=True)

    # ---------- promotion ----------
    def promote_scan(self, min_reads: int = 3,
                     max_bytes: int = 256 << 20) -> list[int]:
        """Hot-data promotion: blocks on slower tiers read >= `min_reads`
        times since the last scan move to the fastest tier with room,
        hottest first; the move may demote the destination's coldest
        blocks downward to make space (never dropping them when a slower
        tier has room). Heat decays by half each scan so a once-hot block
        cools off. Byte copies run without the store lock. Parity: the
        reference README's transparent hot-data promotion headline (its
        code ships write-time tiering only — this EXCEEDS parity)."""
        with self._lock:
            # promotion targets the fastest HEALTHY-enough tier: pinning
            # hot data onto a quarantined dir would race its evacuation
            fastest = next((t for t in self.tiers
                            if not t.health.quarantined), None)
            if fastest is None:
                return []
            hot = [(b.block_id, b.len) for b in sorted(
                (b for b in self.blocks.values()
                 if b.state == BlockState.COMMITTED and b.tier is not fastest
                 and b.heat >= min_reads),
                key=lambda b: b.heat, reverse=True)]
        promoted: list[int] = []
        budget = max_bytes
        for bid, blen in hot:
            if blen > budget:
                continue
            if blen > fastest.capacity:
                # can never fit even an empty tier: don't flush the hot
                # tier chasing an impossible promotion
                continue
            if blen > fastest.available:
                # demote the destination's coldest blocks to make space
                # (the background high-water trim restores headroom after
                # a scan that fills the tier)
                self.trim(fastest, blen, demote=True)
                if blen > fastest.available:
                    continue
            if self._move_block(bid, fastest):
                promoted.append(bid)
                budget -= blen
        with self._lock:
            for b in self.blocks.values():
                b.heat //= 2
        if promoted:
            with self._lock:
                self.promoted_total += len(promoted)
            log.info("promoted %d hot blocks to %s", len(promoted),
                     self.tiers[0].dir_id)
        return promoted

    # ---------- disk health ----------
    def probe_dir(self, tier: TierDir) -> bool:
        """One write/read/unlink media probe against `tier`. Consults
        the fault hook so injected dir faults fail the probe exactly
        like real media would. Blocking — run via asyncio.to_thread.
        Returns True when the round-trip came back intact."""
        path = tier.probe_path
        payload = os.urandom(4096)
        hook = self.fault_hook
        try:
            if hook is not None:
                hook.check_write(path)
            with open(path, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            if hook is not None:
                hook.check_read(path)
            with open(path, "rb") as f:
                back = f.read()
            if hook is not None and len(back):
                buf = bytearray(back)
                hook.mutate_read(path, buf)
                back = bytes(buf)
            os.unlink(path)
            return back == payload
        except OSError:
            try:
                os.unlink(path)
            except OSError:
                pass
            return False

    def note_io_error(self, tier: TierDir) -> bool:
        """Feed one media IO error into `tier`'s health; True on the
        HEALTHY → SUSPECT edge (the caller schedules probing)."""
        moved = tier.health.note_error()
        if moved:
            log.warning("dir %s marked SUSPECT after repeated IO errors",
                        tier.dir_id)
        return moved

    def quarantined_blocks(self, limit: int = 0) -> list[int]:
        """Committed blocks residing on quarantined dirs — the worker
        advertises (a bounded slice of) these every heartbeat so the
        master can drive evacuation; sorted for deterministic batching."""
        with self._lock:
            out = sorted(b.block_id for b in self.blocks.values()
                         if b.state == BlockState.COMMITTED
                         and b.tier.health.quarantined)
        return out[:limit] if limit else out

    def scrub_ages(self) -> dict[str, float]:
        """dir_id → seconds since the oldest committed block on that dir
        was last scrub-verified (i.e. the staleness of the dir's full
        scrub sweep). Dirs with nothing to scrub report 0."""
        now = time.time()
        with self._lock:
            oldest: dict[str, float] = {}
            for b in self.blocks.values():
                if b.state != BlockState.COMMITTED or b.crc32c is None:
                    continue
                t = b.verified_at or self.started_at
                d = b.tier.dir_id
                if d not in oldest or t < oldest[d]:
                    oldest[d] = t
        return {t.dir_id: max(0.0, now - oldest[t.dir_id])
                if t.dir_id in oldest else 0.0
                for t in self.tiers}

    # ---------- reporting ----------
    def storages(self) -> list[StorageInfo]:
        counts: dict[str, int] = {}
        for b in self.blocks.values():
            counts[b.tier.dir_id] = counts.get(b.tier.dir_id, 0) + 1
        return [t.info(counts.get(t.dir_id, 0)) for t in self.tiers]

    def report(self) -> tuple[dict[int, int], dict[int, int]]:
        """(block_id → len, block_id → storage_type) for committed blocks."""
        held, types = {}, {}
        with self._lock:
            for b in self.blocks.values():
                if b.state == BlockState.COMMITTED:
                    held[b.block_id] = b.len
                    types[b.block_id] = int(b.tier.storage_type)
        return held, types
