"""Tiered block storage.

Parity: curvine-server/src/worker/storage/ (vfs_dataset, vfs_dir, dir_state,
file_layout) + worker/block/block_store.rs. Tiers are ordered fastest-first
(MEM > SSD > HDD); a block is created on the fastest tier with room, spills
downward under pressure, and is evicted LRU when every tier is full.
Block files live in hashed subdirs (``<root>/<id % 256>/<id>.blk``), temp
files alongside (``.tmp``) renamed on commit — same layout discipline as
the reference's file_layout.rs."""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import BlockState, StorageInfo, StorageType

log = logging.getLogger(__name__)

_SUBDIRS = 256


@dataclass
class BlockInfo:
    block_id: int
    tier: "TierDir"
    len: int = 0
    state: BlockState = BlockState.TEMP
    atime: float = field(default_factory=time.time)
    crc32c: int | None = None     # content checksum recorded at commit
    crc_algo: str = "crc32c"      # crc32 (wire/zlib) or crc32c (native)

    @property
    def path(self) -> str:
        suffix = ".tmp" if self.state == BlockState.TEMP else ".blk"
        return self.tier.block_path(self.block_id, suffix)


class TierDir:
    def __init__(self, storage_type: StorageType, root: str, capacity: int,
                 dir_id: str = ""):
        self.storage_type = storage_type
        self.root = root
        self.capacity = capacity
        self.used = 0
        self.dir_id = dir_id or f"{storage_type.name.lower()}:{root}"
        os.makedirs(root, exist_ok=True)

    def block_path(self, block_id: int, suffix: str = ".blk") -> str:
        sub = os.path.join(self.root, f"{block_id % _SUBDIRS:02x}")
        os.makedirs(sub, exist_ok=True)
        return os.path.join(sub, f"{block_id}{suffix}")

    @property
    def available(self) -> int:
        return max(0, self.capacity - self.used)

    def info(self, block_num: int = 0) -> StorageInfo:
        return StorageInfo(storage_type=self.storage_type, dir_id=self.dir_id,
                           capacity=self.capacity, available=self.available,
                           block_num=block_num)


class BlockStore:
    """Thread-safe tiered store (handlers run on the event loop; file IO in
    worker threads)."""

    def __init__(self, tiers: list[TierDir], high_water: float = 0.95,
                 low_water: float = 0.80):
        if not tiers:
            raise err.InvalidArgument("worker needs at least one tier")
        self.tiers = sorted(tiers, key=lambda t: int(t.storage_type))
        self.blocks: dict[int, BlockInfo] = {}
        self.high_water = high_water
        self.low_water = low_water
        self._lock = threading.Lock()
        self._load_existing()

    def _load_existing(self) -> None:
        """Rebuild the index from disk (worker restart)."""
        for tier in self.tiers:
            for sub in os.listdir(tier.root):
                subdir = os.path.join(tier.root, sub)
                if not os.path.isdir(subdir):
                    continue
                for name in os.listdir(subdir):
                    full = os.path.join(subdir, name)
                    if name.endswith(".tmp"):
                        os.unlink(full)  # torn write from a previous run
                        continue
                    if not name.endswith(".blk"):
                        continue
                    bid = int(name[:-4])
                    size = os.path.getsize(full)
                    self.blocks[bid] = BlockInfo(block_id=bid, tier=tier,
                                                 len=size,
                                                 state=BlockState.COMMITTED)
                    tier.used += size
        if self.blocks:
            log.info("block store recovered %d blocks", len(self.blocks))

    # ---------- lifecycle ----------
    def pick_tier(self, hint: StorageType | None, size_hint: int) -> TierDir:
        # Preferred tier first, then any tier fastest-first with room.
        ordered = self.tiers
        if hint is not None:
            ordered = ([t for t in self.tiers if t.storage_type == hint]
                       + [t for t in self.tiers if t.storage_type != hint])
        for tier in ordered:
            if tier.available >= size_hint:
                return tier
        # under pressure: evict on the preferred tier
        tier = ordered[0]
        self.evict(tier, size_hint)
        if tier.available < size_hint:
            raise err.CapacityExceeded(
                f"tier {tier.dir_id}: need {size_hint}, have {tier.available}")
        return tier

    def create_temp(self, block_id: int, hint: StorageType | None = None,
                    size_hint: int = 0) -> BlockInfo:
        with self._lock:
            if block_id in self.blocks:
                old = self.blocks[block_id]
                if old.state == BlockState.COMMITTED:
                    raise err.FileAlreadyExists(f"block {block_id} committed")
                self._remove_locked(old)
            tier = self.pick_tier(hint, size_hint)
            info = BlockInfo(block_id=block_id, tier=tier)
            self.blocks[block_id] = info
            return info

    def commit(self, block_id: int, length: int,
               checksum: int | None = None,
               checksum_algo: str = "crc32") -> BlockInfo:
        """`checksum` is the streaming checksum already computed on the
        write path (no re-read); absent → computed natively from disk."""
        with self._lock:
            info = self._get_locked(block_id)
            if info.state == BlockState.COMMITTED:
                return info
            tmp = info.path
            info.state = BlockState.COMMITTED
            info.len = length
            os.replace(tmp, info.path)
            info.tier.used += length
        if checksum is not None:
            info.crc32c = checksum
            info.crc_algo = checksum_algo
        else:
            from curvine_tpu.common import native
            info.crc32c = native.checksum_file(info.path)
            info.crc_algo = "crc32c"
        return info

    def verify(self, block_id: int) -> bool:
        """Re-checksum a committed block against its commit-time value."""
        import zlib
        from curvine_tpu.common import native
        info = self.get(block_id, touch=False)
        if info.state != BlockState.COMMITTED or info.crc32c is None:
            return True
        if info.crc_algo == "crc32":
            with open(info.path, "rb") as f:
                crc = 0
                while chunk := f.read(1 << 20):
                    crc = zlib.crc32(chunk, crc)
            return crc == info.crc32c
        return native.checksum_file(info.path) == info.crc32c

    def scrub(self, limit: int = 16) -> list[int]:
        """Verify up to `limit` least-recently-verified blocks; corrupt
        blocks are dropped (the master re-replicates them). Parity: the
        reference's abnormal-data detection on the worker data path."""
        with self._lock:
            candidates = [b.block_id for b in self.blocks.values()
                          if b.state == BlockState.COMMITTED
                          and b.crc32c is not None][:limit]
        corrupt = []
        for bid in candidates:
            try:
                if not self.verify(bid):
                    log.error("block %d failed checksum scrub; dropping", bid)
                    self.delete(bid)
                    corrupt.append(bid)
            except err.CurvineError:
                continue
        return corrupt

    def get(self, block_id: int, touch: bool = True) -> BlockInfo:
        with self._lock:
            info = self._get_locked(block_id)
            if touch:
                info.atime = time.time()
            return info

    def contains(self, block_id: int) -> bool:
        return block_id in self.blocks

    def delete(self, block_id: int) -> None:
        with self._lock:
            info = self.blocks.get(block_id)
            if info is not None:
                self._remove_locked(info)

    def _remove_locked(self, info: BlockInfo) -> None:
        try:
            os.unlink(info.path)
        except FileNotFoundError:
            pass
        if info.state == BlockState.COMMITTED:
            info.tier.used -= info.len
        self.blocks.pop(info.block_id, None)

    def _get_locked(self, block_id: int) -> BlockInfo:
        info = self.blocks.get(block_id)
        if info is None:
            raise err.BlockNotFound(f"block {block_id}")
        return info

    # ---------- eviction ----------
    def evict(self, tier: TierDir, need: int) -> list[int]:
        """LRU-evict committed blocks from `tier` until `need` fits or the
        low-water mark is reached. Returns evicted block ids."""
        target_free = max(need, int(tier.capacity * (1 - self.low_water)))
        victims = sorted(
            (b for b in self.blocks.values()
             if b.tier is tier and b.state == BlockState.COMMITTED),
            key=lambda b: b.atime)
        evicted = []
        for b in victims:
            if tier.available >= target_free:
                break
            self._remove_locked(b)
            evicted.append(b.block_id)
        if evicted:
            log.info("evicted %d blocks from %s", len(evicted), tier.dir_id)
        return evicted

    def maybe_evict(self) -> list[int]:
        """Background check: any tier above high-water gets trimmed."""
        out = []
        with self._lock:
            for tier in self.tiers:
                if tier.capacity and tier.used > tier.capacity * self.high_water:
                    out.extend(self.evict(tier, 0))
        return out

    # ---------- reporting ----------
    def storages(self) -> list[StorageInfo]:
        counts: dict[str, int] = {}
        for b in self.blocks.values():
            counts[b.tier.dir_id] = counts.get(b.tier.dir_id, 0) + 1
        return [t.info(counts.get(t.dir_id, 0)) for t in self.tiers]

    def report(self) -> tuple[dict[int, int], dict[int, int]]:
        """(block_id → len, block_id → storage_type) for committed blocks."""
        held, types = {}, {}
        with self._lock:
            for b in self.blocks.values():
                if b.state == BlockState.COMMITTED:
                    held[b.block_id] = b.len
                    types[b.block_id] = int(b.tier.storage_type)
        return held, types
