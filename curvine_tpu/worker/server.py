"""Worker server: block read/write handlers, heartbeat, tasks, replication.

Parity: curvine-server/src/worker/ (worker_server.rs, handler/read_handler,
handler/write_handler, block/heartbeat_task, task/load_task_runner,
replication/worker_replication_handler)."""

from __future__ import annotations

import asyncio
import logging
import os
import re
import time
import zlib

from curvine_tpu.common import checksum
from curvine_tpu.common import errors as err
from curvine_tpu.common.conf import ClusterConf
from curvine_tpu.common.metrics import MetricsRegistry
from curvine_tpu.common.types import (
    BlockState, JobState, StorageType, TaskInfo, WorkerAddress, WorkerInfo,
    now_ms,
)
from curvine_tpu.obs.trace import Tracer
from curvine_tpu.rpc import Message, RpcCode, RpcServer, ServerConn
from curvine_tpu.rpc.client import Connection, ConnectionPool
from curvine_tpu.rpc.frame import Flags, pack, response_for, unpack
from curvine_tpu.worker.storage import BdevTier, BlockStore, TierDir

log = logging.getLogger(__name__)

_TIER_NAMES = {"hbm": StorageType.HBM, "mem": StorageType.MEM,
               "ssd": StorageType.SSD, "hdd": StorageType.HDD}


def _tenant_of(msg) -> str:
    """Writer's tenant id off the RPC header (qos front-door rail) —
    stamped onto the block for the tier-0 cache partitions; "" for
    cluster-internal traffic that carries no tenant."""
    from curvine_tpu.common.qos import TENANT_KEY
    try:
        return str(msg.header.get(TENANT_KEY) or "")
    except AttributeError:
        return ""


def worker_id_for(hostname: str, port: int) -> int:
    return zlib.crc32(f"{hostname}:{port}".encode()) & 0x7FFFFFFF


def _open_block_writer(info):
    """File layout: fresh per-block file. Bdev layout: seek to the
    block's extent inside the shared backing file (NEVER truncate it)."""
    if getattr(info, "is_extent", False):
        f = open(info.path, "r+b")
        f.seek(info.offset)
        return f
    return open(info.path, "wb")


def _read_back(info, length: int) -> bytes:
    """Re-read a just-written block file (cross-algo checksum check on
    the replication pull path — rare: only when the source committed
    with an algo this worker doesn't stream)."""
    with open(info.path, "rb") as f:
        if getattr(info, "is_extent", False):
            f.seek(info.offset)
        return f.read(length)


def _write_block_bytes(info, data: bytes, hook=None) -> None:
    if hook is not None:
        hook.check_write(info.path)
        data = data[:hook.torn_write_len(info.path, len(data))]
    with _open_block_writer(info) as f:
        f.write(data)


_HEALTH_LEVEL = {"healthy": 0, "suspect": 1, "quarantined": 2}


def _metric_key(dir_id: str) -> str:
    """dir ids carry ':' and '/' — flatten to a metric-safe suffix."""
    return re.sub(r"[^0-9A-Za-z_.]+", "_", dir_id).strip("_")


def _integrity_header(info) -> dict:
    """Commit-time checksum riding every READ_BLOCK EOF frame (pure
    metadata — no extra IO): clients verify full-block reads against it
    end to end, catching media rot the wire checksums can't see."""
    if info.crc32c is None:
        return {}
    return {"block_crc32": info.crc32c, "block_crc_algo": info.crc_algo}


def _write_file_bytes(path: str, data: bytes) -> None:
    with open(path, "wb") as f:
        f.write(data)


class WorkerServer:
    def __init__(self, conf: ClusterConf | None = None,
                 worker_id: int | None = None):
        self.conf = conf or ClusterConf()
        wc = self.conf.worker
        self.rpc = RpcServer(wc.hostname, wc.rpc_port, "worker",
                             rpc_conf=self.conf.rpc)
        tiers = [
            (BdevTier if getattr(t, "layout", "file") == "bdev" else TierDir)(
                _TIER_NAMES.get(t.storage_type, StorageType.MEM),
                t.dir, t.capacity)
            for t in wc.tiers]
        # direct-IO data plane: SSD/HDD tiers read O_DIRECT through one
        # shared submission ring (worker/io_engine.py); MEM tiers stay
        # on the page cache by design — that IS their storage medium
        from curvine_tpu.worker.io_engine import create_engine
        self.io_engine = None
        if any(t.storage_type >= StorageType.SSD for t in tiers):
            self.io_engine = create_engine(wc)
        if self.io_engine is not None:
            for tier, tc in zip(tiers, wc.tiers):
                if tier.storage_type >= StorageType.SSD:
                    tier.io_engine = self.io_engine
                    tier.io_queue_depth = (getattr(tc, "queue_depth", 0)
                                           or self.io_engine.queue_depth)
        for tier in tiers:
            if isinstance(tier, BdevTier):
                # the extent-reuse safety window must cover the slowest
                # reply a client would still honor (lease clocks start
                # at reply arrival) — keep it tied to the configured
                # RPC deadline, never below the class default
                tier.lease_slack_s = max(
                    tier.lease_slack_s,
                    self.conf.client.rpc_timeout_ms / 1000.0)
        self.store = BlockStore(tiers, wc.eviction_high_water,
                                wc.eviction_low_water,
                                admission=wc.cache_admission,
                                ghost_entries=wc.cache_ghost_entries,
                                small_ratio=wc.cache_small_ratio)
        # shared-memory read plane (worker/shm.py): sealed-memfd export
        # cache + SCM_RIGHTS side channel for co-located clients. The
        # channel itself starts in start() (port must be final); deleted
        # blocks drop their export so a stale copy is never handed out.
        from curvine_tpu.worker.shm import (ShmExporter, WarmShmCache,
                                            shm_supported)
        self.shm = None
        self.shm_warm = None
        self._shm_channel = None
        if wc.shm_reads and shm_supported():
            self.shm = ShmExporter(cap=wc.shm_export_cap)
            if wc.shm_warm_cap_mb > 0:
                # warm-cache exports for the tiers below MEM: read-hot
                # SSD/HDD blocks earn a byte-bounded sealed-memfd copy,
                # admitted through the same policy family as the MEM
                # tier so scans can't flush the warm working set
                self.shm_warm = WarmShmCache(
                    cap_bytes=wc.shm_warm_cap_mb * 1024 * 1024,
                    admission=wc.cache_admission,
                    ghost_entries=wc.cache_ghost_entries)
            # deleted blocks drop both export flavors; a tier move
            # (promote/demote) does too — the copy's bytes would stay
            # correct (blocks are immutable) but the block no longer
            # belongs to the tier whose policy admitted it
            self.store.on_delete = self._shm_invalidate
            self.store.on_move = self._shm_invalidate
        # per-dir DiskHealth thresholds from conf (the state machine
        # itself lives on each TierDir — worker/storage.py)
        for tier in self.store.tiers:
            tier.health.error_threshold = max(1, wc.disk_error_threshold)
            tier.health.decay_s = wc.disk_error_decay_s
            tier.health.probe_failures = max(1, wc.disk_probe_failures)
            tier.health.probe_successes = max(1, wc.disk_probe_successes)
        self.metrics = MetricsRegistry("worker")
        # observability plane: server spans per dispatch + per-code
        # rpc.<name> histograms; the io engine reports submit→complete
        # latency into the same registry
        self.tracer = Tracer.from_conf("worker", self.conf.obs,
                                       metrics=self.metrics)
        self.rpc.obs = self.tracer
        self.rpc.metrics = self.metrics
        # multi-tenant admission control on the data plane too: the
        # tenant id stamped at the front door rides every hop, so a
        # quota set once throttles READ_BLOCK/WRITE_BLOCK here the same
        # way it throttles metadata ops on the master
        from curvine_tpu.common.qos import AdmissionController
        self.qos = AdmissionController.from_conf(
            self.conf.qos, slow_op_ms=self.conf.obs.slow_op_ms,
            metrics=self.metrics)
        self.rpc.qos = self.qos
        # per-job cache partitions (docs/caching.md): eviction prefers
        # blocks of tenants over their tier-0 byte quota (from the same
        # "name:qps[:prio[:inflight[:tier0_mb]]]" tenant specs)
        self.store.tier0_quota = self.qos.tier0_quota
        if self.io_engine is not None:
            self.io_engine.metrics = self.metrics
        self.master_pool = ConnectionPool(size=2, rpc_conf=self.conf.rpc)
        self.peer_pool = ConnectionPool(size=2, rpc_conf=self.conf.rpc)
        self.worker_id = worker_id if worker_id is not None else 0
        self.chunk_size = wc.io_chunk_size
        # HBM tier-0: device-resident block cache for workers co-located
        # with a TPU (in-process consumers get on-device fetches)
        self.hbm = None
        if wc.hbm_capacity > 0:
            try:
                # one tier per local chip (a TPU host drives 4-8): per-chip
                # capacity accounting, least-used placement, replica spread
                from curvine_tpu.tpu.hbm import MultiHbmTier
                self.hbm = MultiHbmTier(wc.hbm_capacity,
                                        admission=wc.cache_admission,
                                        ghost_entries=wc.cache_ghost_entries,
                                        export_cap=wc.hbm_export_cap)
            except Exception as e:  # noqa: BLE001 — no device available
                log.warning("hbm tier disabled: %s", e)
        self._bg: list[asyncio.Task] = []
        from curvine_tpu.common.executor import ScheduledExecutor
        self.executor = ScheduledExecutor("worker")
        self._task_sem = asyncio.Semaphore(wc.task_parallelism)
        self._leader_idx = 0
        # heartbeat failure dedup/backoff state
        self._hb_fails = 0
        self._hb_backoff_until = 0.0
        # rate limit for master-requested full block reports (report_now)
        self._forced_report_at = 0.0
        # decommission drain (heartbeat-driven): refuse NEW write streams
        # with a retryable error so clients re-place elsewhere; streams
        # already open keep flowing until they finish
        self.draining = False
        self._register_handlers()

    @property
    def address(self) -> WorkerAddress:
        return WorkerAddress(
            worker_id=self.worker_id, hostname=self.conf.worker.hostname,
            ip_addr=self.conf.worker.hostname, rpc_port=self.rpc.port,
            web_port=self.conf.worker.web_port)

    @property
    def addr(self) -> str:
        return self.rpc.addr

    async def start(self) -> None:
        await self.rpc.start()
        if not self.worker_id:
            self.worker_id = worker_id_for(self.conf.worker.hostname,
                                           self.rpc.port)
        # join the ICI device domain (docs/ici-plane.md): peers sharing
        # this process's device runtime can then pull our HBM-resident
        # blocks device-to-device instead of over the TCP rail
        if self.hbm is not None and self.conf.worker.ici_transfer:
            from curvine_tpu.tpu import ici_plane
            ici_plane.register_endpoint(self.worker_id, self.hbm,
                                        self.conf.worker.ici_coords)
        # periodic duties ride the scheduled executor
        # (parity: curvine-common/src/executor/ ScheduledExecutor)
        wc = self.conf.worker
        self.executor.submit_periodic("heartbeat", self.heartbeat_once,
                                      wc.heartbeat_ms / 1000,
                                      initial_delay_s=0.0)
        # first full report right after the first heartbeat registers us:
        # the master's drain/replication logic distrusts its view of this
        # worker's holdings until one arrives
        self.executor.submit_periodic("block-report", self.block_report_once,
                                      wc.block_report_interval_ms / 1000,
                                      initial_delay_s=1.0)
        self.executor.submit_periodic("eviction", self._evict_once, 1.0)
        self.executor.submit_periodic("scrub", self._scrub_once,
                                      max(0.1, wc.scrub_interval_s))
        self.executor.submit_periodic("disk-probe", self._disk_probe_once,
                                      max(0.05, wc.disk_probe_interval_s))
        # host tiers to promote between, OR an HBM tier-0 to auto-pin
        # into — either gives the promote cycle work to do
        if wc.promote_interval_ms > 0 and (len(self.store.tiers) > 1
                                           or self.hbm is not None):
            self.executor.submit_periodic("promote", self._promote_once,
                                          wc.promote_interval_ms / 1000)
        if self.shm is not None:
            from curvine_tpu.worker.shm import ShmChannel, channel_path
            ch = ShmChannel(channel_path(self.rpc.port), self._shm_grant)
            try:
                ch.start()
                self._shm_channel = ch
            except OSError as e:
                # no unix sockets here (exotic sandbox): clients simply
                # never see the shm capability flags — clean fallback
                log.warning("shm side channel disabled: %s", e)
                self.shm = None
        log.info("worker %d started at %s", self.worker_id, self.addr)

    async def stop(self) -> None:
        if self.hbm is not None:
            from curvine_tpu.tpu import ici_plane
            ici_plane.unregister_endpoint(self.worker_id)
        await self.executor.stop()
        for t in self._bg:
            t.cancel()
        self._bg.clear()
        if self._shm_channel is not None:
            await asyncio.to_thread(self._shm_channel.stop)
            self._shm_channel = None
        if self.shm is not None:
            self.shm.close()
        if self.shm_warm is not None:
            self.shm_warm.close()
        await self.rpc.stop()
        await self.master_pool.close()
        await self.peer_pool.close()
        if self.io_engine is not None:
            await asyncio.to_thread(self.io_engine.shutdown)
            self.io_engine = None

    # ---------------- master plane ----------------

    async def _master_conn(self) -> Connection:
        """Connection to the current LEADER (rotates on failure —
        `_leader_call` handles NOT_LEADER rotation for actual calls)."""
        addrs = self.conf.client.master_addrs
        return await self.master_pool.get(addrs[self._leader_idx
                                                % len(addrs)])

    async def _leader_call(self, code, data):
        """Call the leader, rotating through master_addrs on NOT_LEADER
        or connect failure (workers were previously pinned to addrs[0],
        which breaks every worker→master report in an HA cluster whose
        leader isn't the first address)."""
        addrs = self.conf.client.master_addrs
        last: Exception | None = None
        for i in range(len(addrs)):
            idx = (self._leader_idx + i) % len(addrs)
            try:
                conn = await self.master_pool.get(addrs[idx])
                rep = await conn.call(code, data=data)
                self._leader_idx = idx
                return rep
            except err.CurvineError as e:
                if e.code not in (err.ErrorCode.NOT_LEADER,
                                  err.ErrorCode.CONNECT):
                    raise
                last = e
        raise last or err.NotLeader("no reachable master")

    async def _bounded_master_call(self, addr: str, code, payload: bytes,
                                   connect_s: float, call_s: float):
        """Deadline covers BOTH the dial and the RPC. A call that times
        out may have cancelled a send mid-frame, so that connection is
        poisoned — close it so the pool never reuses it."""
        conn = await asyncio.wait_for(self.master_pool.get(addr), connect_s)
        try:
            return await asyncio.wait_for(conn.call(code, data=payload),
                                          call_s)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            await conn.close()
            raise

    def _info(self) -> WorkerInfo:
        storages = self.store.storages()
        if self.hbm is not None:
            from curvine_tpu.common.types import StorageInfo
            if hasattr(self.hbm, "per_device_stats"):
                # one HBM StorageInfo PER CHIP: the master sees per-device
                # capacity, not a single opaque pool
                for s in reversed(self.hbm.per_device_stats()):
                    storages.insert(0, StorageInfo(
                        storage_type=StorageType.HBM,
                        dir_id=f"hbm:{s['device_id']}",
                        capacity=s["capacity"],
                        available=s["capacity"] - s["used"],
                        block_num=s["blocks"]))
            else:                              # single-device tier
                storages.insert(0, StorageInfo(
                    storage_type=StorageType.HBM, dir_id="hbm:0",
                    capacity=self.hbm.capacity,
                    available=self.hbm.capacity - self.hbm.used,
                    block_num=len(self.hbm._blocks)))
        return WorkerInfo(address=self.address, storages=storages,
                          last_heartbeat_ms=now_ms(),
                          ici_coords=list(self.conf.worker.ici_coords))

    def _cache_metrics(self) -> dict[str, float]:
        """Flattened cache.<tier>.<stat> counters: per-storage-type
        admission policy stats (summed over dirs), the HBM tier, and
        per-tenant tier-0 occupancy as cache.tier0.<tenant>."""
        out: dict[str, float] = {}
        for t in self.store.tiers:
            pre = f"cache.{t.storage_type.name.lower()}."
            for k, v in t.policy.stats().items():
                if k in ("small", "main", "ghost"):
                    continue
                out[pre + k] = out.get(pre + k, 0) + v
        out["cache.store.misses"] = self.store.miss_total
        if self.hbm is not None:
            st = self.hbm.stats()
            for k in ("hits", "misses", "spills", "ghost_hits",
                      "scan_evicted"):
                out[f"cache.hbm.{k}"] = st.get(k, 0)
            # ICI-plane counters (docs/ici-plane.md): advertisement
            # volume + the device-path vs TCP-fallback split on pulls
            out["ici.hbm_exports"] = st.get("exports", 0)
            for k in ("ici.peer_pulls", "ici.tcp_fallbacks"):
                out[k] = self.metrics.counters.get(k, 0)
        for tenant, used in self.store.tenant_occupancy().items():
            out[f"cache.tier0.{tenant}"] = used
        if self.shm_warm is not None:
            # warm-cache shm plane (docs/data-plane.md): occupancy and
            # admission outcomes beside the tier caches they shadow
            for k, v in self.shm_warm.stats().items():
                if k in ("entries", "bytes", "exports", "hits",
                         "evictions"):
                    out[f"cache.shm_warm.{k}"] = v
                elif k in ("policy_admits", "policy_ghost_hits",
                           "policy_scan_evicted"):
                    out[f"cache.shm_warm.{k[len('policy_'):]}"] = v
        # ring-registered receive plane: pool-resident bytes only (the
        # satellite-1 accounting contract — caller-pinned views are NOT
        # occupancy), whether the io_uring registration armed, and the
        # READ_FIXED op count; gauges land on /metrics via the heartbeat
        from curvine_tpu.rpc import transport
        for k, v in transport.recv_pool().stats().items():
            out[f"rpc.recv_{k}"] = v
        return out

    async def heartbeat_once(self) -> None:
        """Heartbeat EVERY master: followers serve reads and need live
        worker state + replica locations too (runtime locs never ride the
        journal). Delete commands from any master are idempotent.

        An unreachable cluster (shutdown ordering, master restart, net
        partition) must not traceback-spam every tick: one deduped
        warning, then exponential backoff — the tick returns immediately
        until the backoff lapses, and recovery logs once."""
        if time.monotonic() < self._hb_backoff_until:
            return
        if self.hbm is not None:
            from curvine_tpu.tpu.hbm import export_metrics
            export_metrics(self.hbm, self.metrics)
        wm = {
            "bytes.read": self.metrics.counters.get("bytes.read", 0),
            "bytes.written": self.metrics.counters.get("bytes.written", 0),
        }
        # cache-intelligence counters (docs/caching.md): flattened
        # per-tier admission stats + per-tenant tier-0 occupancy; the
        # master folds them into the `cv report` Cache plane rollup and
        # they double as local /metrics gauges
        cm = self._cache_metrics()
        wm.update(cm)
        for name, v in cm.items():
            self.metrics.gauge(name, v)
        body = {"info": self._info().to_wire(), "metrics": wm}
        # quarantined dirs: advertise (a bounded batch of) their resident
        # committed blocks so the master drives evacuation through the
        # replication manager — re-sent every beat until evacuated, so a
        # master restart mid-storm loses nothing; the cap keeps a fault
        # storm from flooding the replication queue
        evac = self.store.quarantined_blocks(
            limit=self.conf.worker.disk_evac_batch)
        if evac:
            body["evac_blocks"] = evac
            body["worker_id"] = self.worker_id
        # peer-addressable HBM advertisement (docs/ici-plane.md): a
        # bounded most-recent snapshot of the export table, re-sent (or
        # cleared) every beat — the master keeps it as soft state for
        # device-path pull hints, nothing journaled
        exports = getattr(self.hbm, "exports", None)
        if exports is not None and self.conf.worker.ici_transfer:
            body["hbm_blocks"] = [
                e["block_id"] for e in exports.snapshot(
                    limit=self.conf.worker.hbm_advertise_max)]
        payload = pack(body)
        deletes: set[int] = set()
        report_now = False
        draining = False

        async def beat(addr: str) -> bool:
            nonlocal report_now, draining
            try:
                rep = await self._bounded_master_call(
                    addr, RpcCode.WORKER_HEARTBEAT, payload,
                    connect_s=3.0, call_s=5.0)
                body = unpack(rep.data) or {}
                for bid in body.get("delete_blocks", []):
                    deletes.add(bid)
                if body.get("report_now"):
                    report_now = True
                if body.get("draining"):
                    draining = True
                return True
            except Exception as e:  # noqa: BLE001 — peer down is routine
                log.debug("heartbeat to %s failed: %s", addr, e)
                return False

        # CONCURRENT fan-out: one dead/unroutable master must not stall
        # the beat to the others
        oks = await asyncio.gather(*(beat(a)
                                     for a in self.conf.client.master_addrs))
        if not any(oks):
            self._hb_fails += 1
            base = self.conf.worker.heartbeat_ms / 1000.0
            delay = min(base * (2 ** min(self._hb_fails, 6)), 60.0)
            self._hb_backoff_until = time.monotonic() + delay
            if self._hb_fails == 1:
                log.warning(
                    "no master reachable for heartbeat (%s); backing off "
                    "exponentially up to 60s, further failures logged at "
                    "debug", ", ".join(self.conf.client.master_addrs))
            else:
                log.debug("heartbeat still failing (%d consecutive); "
                          "next attempt in %.1fs", self._hb_fails, delay)
            return
        if self._hb_fails:
            log.info("master reachable again after %d failed heartbeats",
                     self._hb_fails)
        self._hb_fails = 0
        self._hb_backoff_until = 0.0
        if draining != self.draining:
            # master state is authoritative either way: recommission
            # clears the refusal just like decommission sets it
            log.info("worker %d %s new write streams (decommission drain)",
                     self.worker_id, "refusing" if draining else "accepting")
            self.draining = draining
        for bid in deletes:
            self.store.delete(bid)
            if self.hbm is not None:
                self.hbm.drop(bid)
        if report_now and time.monotonic() - self._forced_report_at >= 1.0:
            # a master lost track of our holdings (it restarted, or we
            # returned from LOST): push a full report immediately instead
            # of leaving our blocks location-less until the periodic one.
            # In the BACKGROUND — a slow report awaited here would starve
            # the heartbeat tick and get us marked LOST all over again.
            self._forced_report_at = time.monotonic()
            self._bg = [t for t in self._bg if not t.done()]
            self._bg.append(asyncio.ensure_future(self.block_report_once()))

    async def block_report_once(self) -> None:
        held, types = self.store.report()
        payload = pack({"worker_id": self.worker_id, "blocks": held,
                        "storage_types": types})
        deletes: set[int] = set()

        async def report(addr: str) -> None:
            try:
                rep = await self._bounded_master_call(
                    addr, RpcCode.WORKER_BLOCK_REPORT, payload,
                    connect_s=5.0, call_s=30.0)
                for bid in (unpack(rep.data) or {}).get("delete_blocks", []):
                    deletes.add(bid)
            except Exception as e:  # noqa: BLE001
                log.debug("block report to %s failed: %s", addr, e)

        await asyncio.gather(*(report(a)
                               for a in self.conf.client.master_addrs))
        for bid in deletes:
            self.store.delete(bid)
            if self.hbm is not None:
                self.hbm.drop(bid)

    async def _evict_once(self) -> None:
        dropped0 = self.store.dropped_total
        demoted0 = self.store.demoted_total
        removed = await asyncio.to_thread(self.store.maybe_evict)
        if self.hbm is not None:
            for bid in removed:
                if not self.store.contains(bid):   # dropped, not demoted
                    # capacity pressure, not deletion: ghost the device
                    # copy so a re-broadcast of this (still-hot) block
                    # re-admits straight to the policy's main queue
                    self.hbm.drop(bid, evicted=True)
        # evicted counts only blocks that LEFT the cache; demotions moved
        # tiers without losing data and get their own counter
        if self.store.dropped_total > dropped0:
            self.metrics.inc("blocks.evicted",
                             self.store.dropped_total - dropped0)
        if self.store.demoted_total > demoted0:
            self.metrics.inc("blocks.demoted",
                             self.store.demoted_total - demoted0)

    async def _promote_once(self) -> None:
        """Hot-data promotion scan; tier changes reach the master on the
        next block report (storage types reconcile there). With an HBM
        tier enabled, the hottest blocks additionally auto-pin into
        device memory (tier-0 promotion — heat snapshot taken BEFORE the
        host scan halves it)."""
        wc = self.conf.worker
        hbm_hot: list[tuple[int, int, int]] = []
        if self.hbm is not None:
            # per-chip share bounds what can EVER pin; snapshot before
            # the host scan halves the heat counters
            per_chip = min(t.capacity for t in self.hbm.tiers.values()) \
                if hasattr(self.hbm, "tiers") else self.hbm.capacity
            hbm_hot = [t for t in self.store.hot_blocks(
                           wc.promote_min_reads, max_len=per_chip)
                       if t[0] not in self.hbm]
        promoted = await asyncio.to_thread(
            self.store.promote_scan, wc.promote_min_reads)
        if promoted:
            self.metrics.inc("blocks.promoted", len(promoted))
        pinned = 0
        budget = 256 << 20            # bound device transfers per cycle
        for bid, _heat, blen in hbm_hot:
            if budget <= 0:
                break
            try:
                n = await self._autopin_block(bid)
            except (err.CurvineError, OSError, ValueError) as e:
                # deleted/evicted since the snapshot, or the chip can't
                # take it: skip this block, keep pinning colder ones
                log.debug("hbm autopin of %d skipped: %s", bid, e)
                continue
            if n:
                budget -= n
                pinned += 1
        if pinned:
            self.metrics.inc("blocks.hbm_pinned", pinned)
            self.metrics.gauge("hbm.used", self.hbm.used)

    async def _autopin_block(self, block_id: int) -> int:
        """Read a committed block and pin it on the least-used local chip
        (the HBM tier's own LRU makes room). The read+put runs in a
        worker thread — up to 256MB of IO per cycle must not stall the
        event loop. Returns bytes pinned."""
        import numpy as np
        # pinned for the whole read+put: a bdev extent can't be freed
        # and reallocated under the preadv (would pin foreign bytes)
        info = self.store.pin_read(block_id, touch=False)
        try:
            if info.state != BlockState.COMMITTED:
                return 0

            def work() -> int:
                buf = np.empty(info.len, dtype=np.uint8)
                fd = os.open(info.path, os.O_RDONLY)
                try:
                    os.preadv(fd, [memoryview(buf)], info.offset)
                finally:
                    os.close(fd)
                if info.crc32c is not None \
                        and checksum.supported(info.crc_algo):
                    # verify the media copy BEFORE promotion — a bad
                    # replica must never become the hottest copy
                    if checksum.crc_update(info.crc_algo,
                                           buf.data) != info.crc32c:
                        raise err.AbnormalData(
                            f"block {block_id} failed promotion verify")
                arr = self.hbm.put(block_id, buf)
                try:
                    from curvine_tpu.tpu import pallas_ops
                    if (pallas_ops.block_checksum(arr)
                            != pallas_ops.block_checksum_host(buf)):
                        raise err.AbnormalData(
                            f"block {block_id} device copy diverges")
                except ImportError:
                    pass
                return info.len

            try:
                n = await asyncio.to_thread(work)
            except err.AbnormalData:
                # on-disk copy (or the device transfer) is bad: drop the
                # pin, count it, and hand the replica to the heal path
                self.hbm.drop(block_id)
                self.metrics.inc("blocks.corrupt")
                try:
                    await self._leader_call(
                        RpcCode.REPORT_UNDER_REPLICATED_BLOCKS,
                        pack({"block_ids": [block_id],
                              "worker_id": self.worker_id}))
                except Exception as e:  # noqa: BLE001 — scrub retries
                    log.warning("promotion corrupt report failed: %s", e)
                return 0
        finally:
            self.store.unpin_read(block_id)
        if not self.store.contains(block_id):
            # deleted mid-pin: the delete path's hbm.drop may have run
            # BEFORE our put landed — drop again so nothing orphans
            self.hbm.drop(block_id)
            return 0
        return n

    async def _scrub_once(self) -> None:
        """Checksum scrub; corrupt blocks are reported to the master —
        WITH our worker id, so it can retire the location and order the
        physical delete once a clean replica exists. The block stays on
        disk until then: the worker never unilaterally destroys what
        might be the last copy."""
        corrupt = await asyncio.to_thread(self.store.scrub,
                                          self.conf.worker.scrub_batch)
        stats = self.store.scrub_last
        if stats.get("verified"):
            self.metrics.inc("blocks.scrub_verified", stats["verified"])
        if stats.get("truncated"):
            self.metrics.inc("blocks.corrupt_truncated", stats["truncated"])
        if stats.get("io_error"):
            self.metrics.inc("scrub.io_errors", stats["io_error"])
        self._export_dir_health()
        if corrupt:
            self.metrics.inc("blocks.corrupt", len(corrupt))
            if self.hbm is not None:
                for bid in corrupt:
                    self.hbm.drop(bid)     # never serve a corrupt pin
            try:
                await self._leader_call(
                    RpcCode.REPORT_UNDER_REPLICATED_BLOCKS,
                    pack({"block_ids": corrupt,
                          "worker_id": self.worker_id,
                          # verify_detail verdicts: the master repairs a
                          # "truncated" copy by re-pull and a "mismatch"
                          # (bit-rot) EC cell by re-encode from siblings
                          "verdicts": {bid: self.store.scrub_verdicts[bid]
                                       for bid in corrupt
                                       if bid in self.store.scrub_verdicts}}))
            except Exception as e:  # noqa: BLE001 — next scrub retries
                log.warning("corrupt-block report failed: %s", e)

    # ---------------- disk health plane ----------------

    def install_disk_faults(self, injector) -> None:
        """Attach a fault/disk.DiskFaultInjector to every storage IO
        path (block store + direct-IO engine). Test/storm control plane."""
        self.store.fault_hook = injector
        if self.io_engine is not None:
            self.io_engine.fault_hook = injector

    def _export_dir_health(self) -> None:
        """Per-dir health level and scrub staleness gauges (level 0 =
        healthy, 1 = suspect, 2 = quarantined)."""
        ages = self.store.scrub_ages()
        for t in self.store.tiers:
            key = _metric_key(t.dir_id)
            self.metrics.gauge(f"dir.health.{key}",
                               _HEALTH_LEVEL.get(t.health.state, 0))
            self.metrics.gauge(f"dir.scrub_age_s.{key}",
                               round(ages.get(t.dir_id, 0.0), 3))

    async def _disk_probe_once(self) -> None:
        """Background write/read/unlink probe of SUSPECT dirs:
        consecutive failures quarantine the dir (allocation stops, the
        master evacuates), consecutive successes rehabilitate it."""
        for tier in self.store.tiers:
            if not tier.health.suspect:
                continue
            ok = await asyncio.to_thread(self.store.probe_dir, tier)
            state = tier.health.probe_result(ok)
            if state == tier.health.QUARANTINED:
                log.error("dir %s QUARANTINED after failed probes; "
                          "blocks will be evacuated", tier.dir_id)
                self.metrics.inc("disk.quarantined")
            elif state == tier.health.HEALTHY:
                log.info("dir %s rehabilitated by probes", tier.dir_id)
        self._export_dir_health()

    # ---------------- handlers ----------------

    def _register_handlers(self) -> None:
        r = self.rpc.register
        r(RpcCode.WRITE_BLOCK, self._write_block)
        r(RpcCode.READ_BLOCK, self._read_block)
        r(RpcCode.DELETE_BLOCK, self._delete_block)
        r(RpcCode.GET_BLOCK_INFO, self._get_block_info)
        r(RpcCode.SC_WRITE_OPEN, self._sc_write_open)
        r(RpcCode.SC_WRITE_COMMIT, self._sc_write_commit)
        r(RpcCode.SC_WRITE_ABORT, self._sc_write_abort)
        r(RpcCode.SC_READ_REPORT, self._sc_read_report)
        r(RpcCode.WRITE_BLOCKS_BATCH, self._write_blocks_batch)
        r(RpcCode.HBM_PIN, self._hbm_pin)
        r(RpcCode.HBM_UNPIN, self._hbm_unpin)
        r(RpcCode.SUBMIT_BLOCK_REPLICATION_JOB, self._replicate_block)
        r(RpcCode.ICI_TRANSFER, self._ici_transfer)
        r(RpcCode.SUBMIT_TASK, self._submit_task)
        r(RpcCode.GET_SPANS, self._get_spans)

    async def _get_spans(self, msg: Message, conn: ServerConn):
        """This worker's recorded spans for one trace (master collect)."""
        q = unpack(msg.data) or {}
        return {}, pack({"spans":
                         self.tracer.spans_for(str(q.get("trace_id", "")))})

    async def _write_block(self, msg: Message, conn: ServerConn):
        """Chunked upload: request header {block_id, storage_type, len_hint},
        then CHUNK frames, then EOF {crc32}. Parity: write_handler.rs.
        Chunks are consumed zero-copy (stream sink runs inline in the
        connection's receive loop with a view into its reusable buffer)."""
        q = unpack(msg.data) or msg.header
        block_id = q["block_id"]
        if self.draining:
            # refusal happens at stream OPEN only — chunks of streams
            # admitted before the drain keep landing below
            raise err.WorkerDraining(
                f"worker {self.worker_id} is draining; "
                f"re-place block {block_id}")
        hint = StorageType(q.get("storage_type", int(StorageType.MEM)))
        # the dispatch span closes when this handler returns (chunks
        # arrive later, in the receive loop's task); a manually-finished
        # span covers the whole stream: request frame → EOF commit/error
        wspan = self.tracer.span("write_block_stream", parent=msg.trace,
                                 attrs={"block_id": block_id})
        info = self.store.create_temp(block_id, hint, q.get("len_hint", 0),
                                      tenant=_tenant_of(msg))
        hook = self.store.fault_hook
        if hook is not None:
            try:
                hook.check_write(info.path)
            except OSError:
                self.store.note_io_error(info.tier)
                self.store.delete(block_id)
                wspan.finish()
                raise
        inline_io = (info.tier.storage_type <= StorageType.MEM
                     and not info.is_extent)
        try:
            f = _open_block_writer(info) if inline_io else \
                await asyncio.to_thread(_open_block_writer, info)
        except OSError as e:
            # allocation-time media failure (mkdir/open of the temp
            # file) — must count against dir health like a mid-stream
            # write error, or a disk that dies at open never quarantines
            self.store.note_io_error(info.tier)
            self.store.delete(block_id)
            wspan.error(e).finish()
            raise
        # commit-checksum algo is the CLIENT's choice (it streams the
        # same hash for wire verification) — carried in the open header
        algo = q.get("algo", "crc32")
        if not checksum.supported(algo):
            algo = "crc32"
        state = {"crc": 0, "total": 0}
        max_len = info.alloc_len if info.is_extent else None
        # hash+write: on multi-core hosts each chunk is copied out of the
        # reusable receive buffer and processed in a worker thread chained
        # behind the previous one (CRC chain + file order need sequencing)
        # while the receive loop takes the next frame — zlib releases the
        # GIL, so hashing overlaps the socket. On a single core the thread
        # hops are pure overhead, so the original inline path is kept.
        offload = (os.cpu_count() or 1) > 1
        tail: dict = {"t": None}

        def _file_write(data) -> None:
            # fault hook: per-chunk EIO/ENOSPC, and torn writes (the crc
            # covers what the CLIENT sent — a silently truncated write is
            # exactly what verify_detail later flags as "truncated")
            if hook is not None:
                hook.check_write(info.path)
                data = data[:hook.torn_write_len(info.path, len(data))]
            f.write(data)

        def _hash_write(data) -> None:
            state["crc"] = checksum.crc_update(algo, data, state["crc"])
            _file_write(data)

        async def _chained(prev, data: bytes) -> None:
            if prev is not None:
                await prev
            if len(data) >= 256 * 1024:
                await asyncio.to_thread(_hash_write, data)
            else:
                _hash_write(data)

        async def sink(header: dict, view: memoryview, is_eof: bool) -> None:
            try:
                if len(view):
                    state["total"] += len(view)
                    if max_len is not None and state["total"] > max_len:
                        raise err.CapacityExceeded(
                            f"block {block_id} exceeds its "
                            f"{max_len}B extent")
                    if offload:
                        tail["t"] = asyncio.ensure_future(
                            _chained(tail["t"], bytes(view)))
                    elif inline_io:
                        _hash_write(view)
                    else:
                        state["crc"] = checksum.crc_update(
                            algo, view, state["crc"])
                        await asyncio.to_thread(_file_write, bytes(view))
                if not is_eof:
                    return
                if tail["t"] is not None:
                    await tail["t"]
                if header.get("abort"):
                    # the client superseded this upload attempt (mid-
                    # stream failover replaced the block elsewhere):
                    # discard the temp state now instead of leaking it
                    # until connection teardown. No ack — the client
                    # already stopped listening on this req_id.
                    conn.close_stream(msg.req_id)
                    f.close()
                    self.store.delete(block_id)
                    wspan.set_attr("aborted", True)
                    wspan.finish()
                    return
                conn.close_stream(msg.req_id)
                f.close()
                want = header.get("crc32")
                if want is not None \
                        and header.get("algo", algo) == algo \
                        and want != state["crc"]:
                    raise err.AbnormalData(
                        f"block {block_id} crc mismatch: "
                        f"{state['crc']:#x} != {want:#x}")
                await asyncio.to_thread(
                    self.store.commit, block_id, state["total"],
                    checksum=state["crc"], checksum_algo=algo)
                self.metrics.inc("bytes.written", state["total"])
                wspan.set_attr("bytes", state["total"])
                wspan.finish()
                await conn.send(response_for(msg, header={
                    "block_id": block_id, "len": state["total"],
                    "crc32": state["crc"], "worker_id": self.worker_id},
                    flags=Flags.RESPONSE | Flags.EOF))
            except Exception as e:  # noqa: BLE001 — surface to the client
                if isinstance(e, OSError):
                    # real (or injected) media write failure: feed the
                    # dir health machinery
                    self.store.note_io_error(info.tier)
                wspan.error(e).finish()
                conn.close_stream(msg.req_id)
                try:
                    f.close()
                except Exception:
                    pass
                self.store.delete(block_id)
                from curvine_tpu.rpc.frame import error_for
                await conn.send(error_for(msg, e))

        conn.set_stream_sink(msg.req_id, sink)
        return None                # reply is sent from the sink at EOF

    async def _sc_write_open(self, msg: Message, conn: ServerConn):
        """Short-circuit write grant: a co-located client writes the temp
        block file directly (no socket copy, one hash pass) and commits
        via SC_WRITE_COMMIT. The TPU-host counterpart of the reference's
        short-circuit read (orpc zero-copy parity, write direction)."""
        q = unpack(msg.data) or {}
        if self.draining:
            raise err.WorkerDraining(
                f"worker {self.worker_id} is draining; "
                f"re-place block {q['block_id']}")
        info = self.store.create_temp(
            q["block_id"], StorageType(q.get("storage_type",
                                             int(StorageType.MEM))),
            q.get("len_hint", 0), tenant=_tenant_of(msg))
        if info.is_extent:
            # the sc client opens the path with O_TRUNC — fatal on a
            # shared bdev file; stream over the socket instead
            self.store.delete(q["block_id"])
            raise err.Unsupported("short-circuit write unsupported on "
                                  "bdev tiers")
        return {}, pack({"path": info.path, "worker_id": self.worker_id})

    async def _sc_write_commit(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        info = await asyncio.to_thread(
            self.store.commit, q["block_id"], q["len"],
            checksum=q.get("crc32"), checksum_algo=q.get("algo", "crc32"))
        self.metrics.inc("bytes.written", info.len)
        return {}, pack({"block_id": info.block_id, "len": info.len,
                         "worker_id": self.worker_id})

    async def _sc_write_abort(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        self.store.delete(q["block_id"])
        return {}, pack({})

    async def _read_block(self, msg: Message, conn: ServerConn):
        """Streaming download. Request {block_id, offset, len, chunk_size}.
        Parity: read_handler.rs. Chunks are preadv'd into one reusable
        buffer and sent as views — no per-chunk allocations (first-touch
        page faults dominate large allocs on virtualized hosts). The
        transport is set to drain fully so buffer reuse is safe."""
        import numpy as np
        q = unpack(msg.data) or msg.header
        # read pin: while this stream runs, tier moves of bdev-resident
        # blocks are refused, so the extent can't be freed and reused
        # under us (file-layout moves stay safe via unlink semantics)
        info = self.store.pin_read(q["block_id"])
        try:
            offset = q.get("offset", 0)
            length = q.get("len", -1)
            chunk_size = q.get("chunk_size", self.chunk_size)
            end = info.len if length < 0 else min(info.len, offset + length)
            inline_io = info.tier.storage_type <= StorageType.MEM
            want_crc = bool(q.get("verify", False))
            hook = self.store.fault_hook
            # a bit-flip fault needs the bytes in userspace to mutate —
            # the kernel-sendfile path can't expose them, so fall through
            # to the copying path while such a spec is armed
            force_copy = hook is not None \
                and hook.wants_read_data(info.path)
            if hook is not None:
                hook.check_read(info.path)

            base = info.offset              # bdev extents start mid-file
            engine = info.tier.io_engine
            if engine is not None:
                # direct-IO tier: chunks come off the submission ring
                # O_DIRECT (batched at the engine's queue depth), so a
                # cold SSD/HDD read never evicts MEM-tier/FUSE pages.
                # One reusable buffer; send completes before reuse.
                buf = np.empty(min(chunk_size, max(1, end - offset)),
                               dtype=np.uint8)
                crc = 0
                pos = offset
                while pos < end:
                    if msg.deadline is not None:
                        # the client stopped listening at its budget:
                        # abandon the stream instead of shoveling chunks
                        # into a dead socket buffer
                        msg.deadline.check(f"read block {q['block_id']}")
                    n = min(chunk_size, end - pos)
                    view = memoryview(buf[:n])
                    got = await engine.read_into(info.path, base + pos, view)
                    if got <= 0:
                        break
                    view = view[:got]
                    if force_copy:
                        hook.mutate_read(info.path, view)
                    if want_crc:
                        crc = zlib.crc32(view, crc)
                    pos += got
                    await conn.send(response_for(
                        msg, data=view, flags=Flags.RESPONSE | Flags.CHUNK))
                header = {"len": pos - offset, "direct_io": True}
                header.update(_integrity_header(info))
                if want_crc:
                    header["crc32"] = crc
                await conn.send(response_for(
                    msg, header=header, flags=Flags.RESPONSE | Flags.EOF))
                self.metrics.inc("bytes.read", pos - offset)
                self.metrics.inc("bytes.read.direct", pos - offset)
                return None
            if not want_crc and not force_copy:
                # zero-copy: chunk payloads leave via kernel sendfile, data
                # never enters userspace (TCP checksums the wire; at-rest
                # integrity is the scrubber's job, end-to-end integrity
                # the client's — the commit-time crc rides the EOF frame)
                f = open(info.path, "rb")
                try:
                    pos = offset
                    while pos < end:
                        if msg.deadline is not None:
                            msg.deadline.check(
                                f"read block {q['block_id']}")
                        n = min(chunk_size, end - pos)
                        sent = await conn.send_chunk_from_file(
                            msg.code, msg.req_id, f, base + pos, n)
                        if sent <= 0:
                            break
                        pos += sent
                    header = {"len": pos - offset}
                    header.update(_integrity_header(info))
                    await conn.send(response_for(
                        msg, header=header,
                        flags=Flags.RESPONSE | Flags.EOF))
                    self.metrics.inc("bytes.read", pos - offset)
                finally:
                    f.close()
                return None

            # verified path: preadv into one reusable buffer + streaming
            # crc (sock_sendall completes only once the kernel took the
            # bytes, so reusing the buffer between sends is safe)
            fd = os.open(info.path, os.O_RDONLY)
            buf = np.empty(min(chunk_size, max(1, end - offset)),
                           dtype=np.uint8)
            try:
                crc = 0
                pos = offset
                while pos < end:
                    if msg.deadline is not None:
                        msg.deadline.check(f"read block {q['block_id']}")
                    n = min(chunk_size, end - pos)
                    view = memoryview(buf[:n])
                    if inline_io:
                        got = os.preadv(fd, [view], base + pos)
                    else:
                        got = await asyncio.to_thread(os.preadv, fd, [view],
                                                      base + pos)
                    if got <= 0:
                        break
                    view = view[:got]
                    if force_copy:
                        hook.mutate_read(info.path, view)
                    crc = zlib.crc32(view, crc)
                    pos += got
                    await conn.send(response_for(
                        msg, data=view, flags=Flags.RESPONSE | Flags.CHUNK))
                header = {"crc32": crc, "len": pos - offset}
                header.update(_integrity_header(info))
                await conn.send(response_for(
                    msg, header=header,
                    flags=Flags.RESPONSE | Flags.EOF))
                self.metrics.inc("bytes.read", pos - offset)
            finally:
                os.close(fd)
            return None
        except OSError:
            # media refused the read (real or injected): count it
            # against the dir health and surface the error to the
            # client, which fails over to another replica
            self.store.note_io_error(info.tier)
            raise
        finally:
            self.store.unpin_read(q["block_id"])

    async def _write_blocks_batch(self, msg: Message, conn: ServerConn):
        """Many small blocks in one request — the small-file fast path.
        Parity: worker/handler/batch_write_handler.rs. Body: msgpack
        {"blocks": [{block_id, storage_type, data}]}."""
        q = unpack(msg.data) or {}
        results = []
        for b in q.get("blocks", []):
            data = b["data"]
            info = self.store.create_temp(
                b["block_id"], StorageType(b.get("storage_type",
                                                 int(StorageType.MEM))),
                len(data), tenant=_tenant_of(msg))
            try:
                await asyncio.to_thread(_write_block_bytes, info, data)
                # sender-computed checksum (EC cell placement and other
                # trusted peers): the cell commits first-class verified,
                # so the scrubber covers it like any block
                await asyncio.to_thread(
                    self.store.commit, b["block_id"], len(data),
                    checksum=b.get("crc32"),
                    checksum_algo=b.get("algo", "crc32"))
                results.append({"block_id": b["block_id"], "len": len(data),
                                "worker_id": self.worker_id,
                                "storage_type": int(info.tier.storage_type)})
            except Exception as e:
                if isinstance(e, OSError):
                    self.store.note_io_error(info.tier)
                self.store.delete(b["block_id"])
                raise
        self.metrics.inc("bytes.written",
                         sum(r["len"] for r in results))
        # results ride the DATA frame: consumers (unified batch writer,
        # EC cell placement) parse unpack(rep.data)["results"]
        return {}, pack({"results": results})

    async def _delete_block(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        self.store.delete(q["block_id"])
        if self.hbm is not None:
            self.hbm.drop(q["block_id"])     # no orphaned device copies
        return {}

    async def _get_block_info(self, msg: Message, conn: ServerConn):
        """Metadata + local path (enables client short-circuit reads)."""
        q = unpack(msg.data) or {}
        # lookup + lease recording are one atomic store operation: a
        # free slipping in between would lease an already-freed extent
        info, lease_ms = self.store.grant_sc(q["block_id"])
        rep = {"block_id": info.block_id, "len": info.len,
               "storage_type": int(info.tier.storage_type),
               "path": os.path.abspath(info.path),
               "offset": info.offset}
        if info.tier.io_engine is not None:
            # capability plumb-through: parallel readers size their
            # slice fan-out to the tier's submission depth instead of
            # guessing (client/reader.py read_range)
            rep["direct_io"] = True
            rep["queue_depth"] = (info.tier.io_queue_depth
                                  or info.tier.io_engine.queue_depth)
        if lease_ms:
            # extent grants expire: the client must re-probe before the
            # tier's quarantine can return the freed extent to reuse
            rep["lease_ms"] = lease_ms
        if info.crc32c is not None:
            # commit-time checksum: short-circuit readers verify the
            # mmap/pread bytes against it without a worker round-trip
            rep["crc32"] = info.crc32c
            rep["crc_algo"] = info.crc_algo
        if self._shm_servable(info):
            # capability negotiation: a client that understands the shm
            # plane fetches the sealed memfd over the side channel and
            # serves reads as zero-RPC mmap slices; everyone else just
            # ignores the flags and keeps the fd/socket paths
            rep["shm"] = True
            rep["shm_sock"] = self._shm_channel.path
        elif self._shm_warm_servable(info):
            # warm-cache export: a read-hot below-MEM block is servable
            # over the SAME channel/protocol; shm_warm lets the client
            # account the hit to the warm plane (read.shm_warm_hits)
            rep["shm"] = True
            rep["shm_warm"] = True
            rep["shm_sock"] = self._shm_channel.path
        exports = getattr(self.hbm, "exports", None)
        if exports is not None and self.conf.worker.ici_transfer:
            e = exports.get(q["block_id"])
            if e is not None:
                # peer-addressable HBM advertisement (docs/ici-plane.md):
                # an ICI-capable consumer can fetch the device buffer
                # from this worker's tier instead of reading bytes —
                # device ordinal + mesh coords + buffer shape/dtype
                rep["hbm"] = {"worker_id": self.worker_id,
                              "ici_coords": list(
                                  self.conf.worker.ici_coords or []),
                              **e}
        return rep

    def _shm_servable(self, info) -> bool:
        """MEM-tier file-layout committed blocks only: extents live
        inside a shared backing file (a memfd copy would defeat the
        lease machinery) and disk tiers would double-buffer the page
        cache into anonymous memory for no latency win."""
        return (self.shm is not None and self._shm_channel is not None
                and info.state == BlockState.COMMITTED
                and not getattr(info, "is_extent", False)
                and info.tier.storage_type == StorageType.MEM)

    def _shm_warm_servable(self, info) -> bool:
        """Warm-cache eligibility for the tiers below MEM: committed
        file-layout blocks whose heat (the SC_READ_REPORT rail) crossed
        worker.shm_warm_min_reads and that fit the warm cache. Extents
        stay excluded for the same lease reasons as the MEM gate."""
        warm = self.shm_warm
        return (warm is not None and self._shm_channel is not None
                and info.state == BlockState.COMMITTED
                and not getattr(info, "is_extent", False)
                and int(info.tier.storage_type) > int(StorageType.MEM)
                and info.heat >= self.conf.worker.shm_warm_min_reads
                and info.len <= warm.cap_bytes)

    def _shm_invalidate(self, block_id: int) -> None:
        """BlockStore on_delete/on_move hook (fires under the store
        lock): drop both export flavors; must not re-enter the store."""
        if self.shm is not None:
            self.shm.invalidate(block_id)
        if self.shm_warm is not None:
            self.shm_warm.invalidate(block_id)

    def _shm_grant(self, block_id: int) -> tuple[int, int]:
        """Side-channel policy hook (runs on the channel thread): look
        the block up, gate on tier/layout, export a sealed memfd — from
        the MEM exporter or, for heat-qualified below-MEM blocks, the
        warm cache. LookupError → NOT_FOUND reply → the client falls
        back."""
        try:
            info = self.store.get(block_id, touch=False)
        except err.CurvineError:
            raise LookupError(f"block {block_id}") from None
        if self._shm_servable(info):
            fd, length = self.shm.export(block_id, info.path, info.len)
            self.metrics.inc("shm.grants")
            return fd, length
        if self._shm_warm_servable(info):
            fd, length = self.shm_warm.export(block_id, info.path,
                                              info.len)
            self.metrics.inc("shm.warm_grants")
            return fd, length
        raise LookupError(f"block {block_id} not shm-servable")

    async def _sc_read_report(self, msg: Message, conn: ServerConn):
        """Short-circuit read accounting: clients read through cached fds
        (the store only sees the initial probe), so they periodically
        report per-block read counts — heat/atime then track actual
        traffic and the promotion/HBM-autopin scans target the truly hot
        blocks instead of the most-probed ones."""
        q = unpack(msg.data) or {}
        warm: dict[int, str] = {}
        for bid, reads in (q.get("block_reads") or {}).items():
            bid = int(bid)
            self.store.touch_reads(bid, int(reads))
            # the report is the moment heat crosses the warm threshold:
            # advertise newly warm-servable blocks on the REPLY so the
            # reporting client (which cached its GET_BLOCK_INFO probe
            # from before the block was hot) learns the capability
            # without a re-probe — its next read maps the warm copy
            if self.shm_warm is not None:
                try:
                    info = self.store.get(bid, touch=False)
                except err.CurvineError:
                    continue
                if self._shm_warm_servable(info):
                    warm[bid] = self._shm_channel.path
        return {"shm_warm": warm} if warm else {}

    async def _replicate_block(self, msg: Message, conn: ServerConn):
        """Pull a block replica from a peer worker and report to master.
        Parity: worker/replication/replication_job.rs (pull-based)."""
        q = unpack(msg.data) or {}
        block_id = q["block_id"]
        ok, message = True, ""
        ecq = q.get("ec")
        if ecq is not None:
            # stripe-cell rebuild: there may be NOTHING to copy — decode
            # the cell from k sibling cells instead of pulling a replica
            try:
                if not self.store.contains(block_id):
                    await self._reconstruct_cell(ecq, block_id)
                    await self._leader_call(
                        RpcCode.WORKER_BLOCK_REPORT, pack({
                            "worker_id": self.worker_id,
                            "blocks": {block_id: ecq["cell_size"]},
                            "storage_types": {block_id: int(
                                self.store.get(block_id,
                                               touch=False)
                                .tier.storage_type)},
                            "incremental": True}))
            except Exception as e:  # noqa: BLE001
                ok, message = False, str(e)
                self.store.delete(block_id)
            try:
                await self._leader_call(
                    RpcCode.REPORT_BLOCK_REPLICATION_RESULT,
                    pack({"block_id": block_id,
                          "worker_id": self.worker_id,
                          "success": ok, "message": message}))
            except Exception as e:
                log.warning("reconstruct result report failed: %s", e)
            return {"success": ok, "message": message}
        src = WorkerAddress.from_wire(q["source"])
        via = ""
        try:
            if not self.store.contains(block_id):
                # device path first when the master hinted the source
                # holds the block in HBM (docs/ici-plane.md): zero bytes
                # on the TCP rail when it lands. ANY failure — peer
                # outside the device domain, stale advertisement, device
                # error — falls through to the TCP pull below; the
                # fallback is a counter, never an error.
                ici = q.get("ici")
                if ici is not None and self.conf.worker.ici_transfer:
                    landed = False
                    try:
                        landed = await self._ici_land(
                            block_id, ici, q.get("block_len", 0))
                    except Exception as e:  # noqa: BLE001
                        log.debug("ici pull of block %d failed: %s",
                                  block_id, e)
                        self.store.delete(block_id)   # clear any temp
                    if landed:
                        via = "ici"
                        self.metrics.inc("ici.peer_pulls")
                    else:
                        self.metrics.inc("ici.tcp_fallbacks")
            if not self.store.contains(block_id):
                peer = await self.peer_pool.get(
                    f"{src.ip_addr or src.hostname}:{src.rpc_port}")
                info = self.store.create_temp(block_id,
                                              size_hint=q.get("block_len", 0))
                total = 0
                crc = 0
                crc_algo = checksum.preferred_algo()
                src_crc = None
                src_algo = None
                cap = info.alloc_len if info.is_extent else None
                hook = self.store.fault_hook
                f = await asyncio.to_thread(_open_block_writer, info)
                try:
                    # the master's pull budget rides the submit header:
                    # a dead/wedged source fails this stream inside the
                    # remaining budget instead of the full RPC timeout
                    async for m in peer.call_stream(
                            RpcCode.READ_BLOCK, header={"block_id": block_id},
                            deadline=msg.deadline):
                        if len(m.data):
                            total += len(m.data)
                            if cap is not None and total > cap:
                                # never write past the extent into a
                                # neighboring committed block
                                raise err.CapacityExceeded(
                                    f"replica {block_id} exceeds its "
                                    f"{cap}B extent")
                            crc = checksum.crc_update(crc_algo, m.data, crc)
                            if hook is not None:
                                hook.check_write(info.path)
                            await asyncio.to_thread(f.write, m.data)
                        if m.is_eof:
                            h = m.header or {}
                            src_crc = h.get("block_crc32")
                            src_algo = h.get("block_crc_algo")
                finally:
                    await asyncio.to_thread(f.close)
                if src_crc is not None:
                    got = crc if src_algo == crc_algo else (
                        checksum.crc_update(src_algo,
                                            _read_back(info, total))
                        if checksum.supported(src_algo) else None)
                    if got is not None and got != src_crc:
                        # the SOURCE replica (or the wire) is bad —
                        # healing must never multiply corruption; fail
                        # the job so the master retries another holder
                        raise err.AbnormalData(
                            f"replica pull of {block_id} checksum "
                            f"mismatch (got {got:#010x} want "
                            f"{src_crc:#010x})")
                self.store.commit(block_id, total, checksum=crc,
                                  checksum_algo=crc_algo)
                # tell master about the new replica via commit on next report;
                # also push an immediate incremental report
                await self._leader_call(RpcCode.WORKER_BLOCK_REPORT, pack({
                    "worker_id": self.worker_id,
                    "blocks": {block_id: total},
                    "storage_types": {block_id: int(info.tier.storage_type)},
                    "incremental": True}))
        except Exception as e:  # noqa: BLE001
            ok, message = False, str(e)
            if isinstance(e, OSError) and "info" in locals():
                # local media failure while landing the pull (open or
                # write) — connection errors ride CurvineError types, so
                # an OSError here is this disk's fault, not the source's
                self.store.note_io_error(info.tier)
            self.store.delete(block_id)
        try:
            await self._leader_call(
                RpcCode.REPORT_BLOCK_REPLICATION_RESULT,
                pack({"block_id": block_id, "worker_id": self.worker_id,
                      "success": ok, "message": message, "via": via}))
        except Exception as e:
            log.warning("replication result report failed: %s", e)
        return {"success": ok, "message": message, "via": via}

    async def _ici_land(self, block_id: int, hint: dict,
                        block_len: int) -> bool:
        """Land one replica over the ICI device path: fetch the peer's
        HBM-resident buffer through the in-process device domain
        (tpu/ici_plane.py), then commit it locally with the same crc
        discipline as a TCP pull. Returns False (peer not reachable this
        way, stale advertisement, length mismatch) to request the TCP
        fallback; only genuinely local landing failures raise."""
        import numpy as np
        from curvine_tpu.tpu import ici_plane
        arr = await asyncio.to_thread(
            ici_plane.fetch_device_block,
            int(hint.get("worker_id", -1)), block_id)
        if arr is None:
            return False
        buf = np.asarray(arr).reshape(-1).view(np.uint8)
        if block_len and buf.nbytes != block_len:
            return False        # advertisement outlived the block bytes
        info = self.store.create_temp(block_id, size_hint=buf.nbytes)
        if info.is_extent and buf.nbytes > info.alloc_len:
            self.store.delete(block_id)
            return False
        crc_algo = checksum.preferred_algo()
        crc = checksum.crc_update(crc_algo, buf)
        f = await asyncio.to_thread(_open_block_writer, info)
        try:
            await asyncio.to_thread(f.write, buf)
        finally:
            await asyncio.to_thread(f.close)
        self.store.commit(block_id, buf.nbytes, checksum=crc,
                          checksum_algo=crc_algo)
        await self._leader_call(RpcCode.WORKER_BLOCK_REPORT, pack({
            "worker_id": self.worker_id,
            "blocks": {block_id: buf.nbytes},
            "storage_types": {block_id: int(info.tier.storage_type)},
            "incremental": True}))
        return True

    async def _ici_transfer(self, msg: Message, conn: ServerConn):
        """Coordination RPC (RpcCode.ICI_TRANSFER): pair this worker
        with a named peer to move one block device-to-device. Succeeds
        only over the device path; a miss replies success=False WITHOUT
        raising so the caller keeps its TCP rail as the fallback —
        same contract as the hinted replication pull."""
        q = unpack(msg.data) or {}
        block_id = q["block_id"]
        if self.store.contains(block_id):
            return {"success": True, "via": "local"}
        if not self.conf.worker.ici_transfer:
            return {"success": False, "via": "",
                    "message": "ici transfer disabled"}
        landed = False
        try:
            landed = await self._ici_land(
                block_id, {"worker_id": q.get("source_worker_id", -1)},
                q.get("block_len", 0))
        except Exception as e:  # noqa: BLE001
            log.debug("ici transfer of block %d failed: %s", block_id, e)
            self.store.delete(block_id)
        if landed:
            self.metrics.inc("ici.peer_pulls")
            return {"success": True, "via": "ici"}
        self.metrics.inc("ici.tcp_fallbacks")
        return {"success": False, "via": ""}

    async def _hbm_pin(self, msg: Message, conn: ServerConn):
        """Pin a cached block into the HBM tier-0 (device-resident).
        In-process consumers (sdk/tpu loaders embedded on the TPU VM) then
        fetch it as an on-device array via `hbm.get`."""
        q = unpack(msg.data) or {}
        if self.hbm is None:
            raise err.Unsupported("hbm tier not enabled on this worker")
        block_id = q["block_id"]
        info = self.store.get(block_id)
        import numpy as np
        buf = np.empty(info.len, dtype=np.uint8)
        fd = os.open(info.path, os.O_RDONLY)
        try:
            os.preadv(fd, [memoryview(buf)], info.offset)
        finally:
            os.close(fd)
        multi = hasattr(self.hbm, "tiers")     # MultiHbmTier vs single
        if multi and q.get("replicas", 1) > 1:
            arrs = await asyncio.to_thread(self.hbm.put_replicated,
                                           block_id, buf, q["replicas"])
            arr = arrs[0]
        elif multi:
            arr = await asyncio.to_thread(self.hbm.put, block_id, buf,
                                          q.get("device_id"))
        else:
            arr = await asyncio.to_thread(self.hbm.put, block_id, buf)
        self.metrics.gauge("hbm.used", self.hbm.used)
        return {"block_id": block_id, "len": int(arr.nbytes),
                "holders": self.hbm.holders(block_id) if multi else [0],
                "hbm": self.hbm.stats()}

    async def _hbm_unpin(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        if self.hbm is not None:
            self.hbm.drop(q["block_id"])
            self.metrics.gauge("hbm.used", self.hbm.used)
        return {}

    async def _submit_task(self, msg: Message, conn: ServerConn):
        q = unpack(msg.data) or {}
        task = TaskInfo.from_wire(q["task"])
        if task.kind == "ec_convert":
            asyncio.ensure_future(self._run_ec_convert_task(task))
        else:
            asyncio.ensure_future(self._run_load_task(task))
        return {"accepted": True}

    async def _run_load_task(self, task: TaskInfo) -> None:
        """UFS ↔ cache transfer. Parity: worker/task/load_task_runner.rs
        (load) + the export job flow (cache → UFS)."""
        from curvine_tpu.client import CurvineClient
        async with self._task_sem:
            client = CurvineClient(self.conf)
            try:
                if task.kind == "export":
                    n = await client.export_to_ufs(task.path)
                elif task.kind == "prefetch":
                    n = await client.prefetch(task.path)
                else:
                    n = await client.load_from_ufs(task.path)
                task.state = JobState.COMPLETED
                task.loaded_len = n
            except Exception as e:  # noqa: BLE001
                task.state = JobState.FAILED
                task.message = str(e)
                log.warning("load task %s failed: %s", task.task_id, e)
            finally:
                task.worker_id = self.worker_id
                try:
                    await self._leader_call(RpcCode.REPORT_TASK,
                                            pack({"task": task.to_wire()}))
                except Exception as e:
                    log.warning("task report failed: %s", e)
                await client.close()

    # ---------------- erasure coding ----------------

    async def _pull_verified(self, src: WorkerAddress, block_id: int,
                             deadline=None) -> bytes:
        """Pull one whole block/cell from a peer into memory, verified
        against the commit-time checksum riding the EOF frame. In-memory
        on purpose: every EC caller needs the full bytes for the matrix
        pass anyway, and cells are bounded by block_size/k."""
        peer = await self.peer_pool.get(
            f"{src.ip_addr or src.hostname}:{src.rpc_port}")
        chunks: list[bytes] = []
        src_crc = src_algo = None
        async for m in peer.call_stream(
                RpcCode.READ_BLOCK, header={"block_id": block_id},
                deadline=deadline):
            if len(m.data):
                chunks.append(bytes(m.data))
            if m.is_eof:
                h = m.header or {}
                src_crc = h.get("block_crc32")
                src_algo = h.get("block_crc_algo")
        data = b"".join(chunks)
        if src_crc is not None and checksum.supported(src_algo):
            if checksum.crc_update(src_algo, data) != src_crc:
                raise err.AbnormalData(
                    f"pull of block {block_id} from worker "
                    f"{src.worker_id} failed checksum verify")
        return data

    async def _pull_any(self, sources: list[dict], block_id: int,
                        deadline=None) -> bytes:
        last: Exception | None = None
        for wire in sources:
            try:
                return await self._pull_verified(
                    WorkerAddress.from_wire(wire), block_id,
                    deadline=deadline)
            except Exception as e:  # noqa: BLE001 — try the next holder
                last = e
        raise last or err.BlockNotFound(
            f"no servable source for block {block_id}")

    def _write_local_cell(self, cell_id: int, data: bytes) -> int:
        """Commit one stripe cell into the local store with a fresh
        first-class checksum (cells scrub and verify like any block)."""
        info = self.store.create_temp(cell_id, size_hint=len(data))
        algo = checksum.preferred_algo()
        crc = checksum.crc_update(algo, data)
        try:
            _write_block_bytes(info, data, self.store.fault_hook)
            self.store.commit(cell_id, len(data), checksum=crc,
                              checksum_algo=algo)
        except Exception:
            self.store.delete(cell_id)
            raise
        return int(info.tier.storage_type)

    async def _place_cells(self, placed: dict) -> list[dict]:
        """Land encoded cells on their target workers. Local targets
        commit directly; remote targets ride WRITE_BLOCKS_BATCH (cells
        are small one-shot writes — the streaming protocol buys nothing)
        with the sender-computed checksum so every cell commits
        first-class verified. `placed`: addr_key -> (addr, [(cell_id,
        bytes), ...]). Returns EC_COMMIT_STRIPE cell entries."""
        out = []
        algo = checksum.preferred_algo()
        for addr, cells in placed.values():
            if addr.worker_id == self.worker_id:
                for cid, data in cells:
                    st = await asyncio.to_thread(
                        self._write_local_cell, cid, data)
                    out.append({"block_id": cid,
                                "worker_id": self.worker_id,
                                "storage_type": st})
                continue
            peer = await self.peer_pool.get(
                f"{addr.ip_addr or addr.hostname}:{addr.rpc_port}")
            rep = await peer.call(RpcCode.WRITE_BLOCKS_BATCH, data=pack({
                "blocks": [{"block_id": cid, "data": data,
                            "crc32": checksum.crc_update(algo, data),
                            "algo": algo}
                           for cid, data in cells]}))
            for r in (unpack(rep.data) or {}).get("results", []):
                out.append({"block_id": r["block_id"],
                            "worker_id": r["worker_id"],
                            "storage_type": r.get("storage_type", 1)})
        return out

    async def _convert_one_stripe(self, prof, plan: dict) -> None:
        from curvine_tpu.common import ec as eclib
        block_id = plan["block_id"]
        data = await self._pull_any(plan["sources"], block_id)
        if len(data) != plan["block_len"]:
            raise err.AbnormalData(
                f"block {block_id}: pulled {len(data)}B, "
                f"expected {plan['block_len']}B")
        cells, _ = await asyncio.to_thread(
            eclib.split, data, prof.k, plan["cell_size"])
        parity = await asyncio.to_thread(eclib.encode, prof, cells)
        coded = cells + parity
        placed: dict = {}
        for c in plan["cells"]:
            addr = WorkerAddress.from_wire(c["addr"])
            key = (addr.worker_id, addr.rpc_port)
            placed.setdefault(key, (addr, []))[1].append(
                (c["block_id"], bytes(coded[c["index"]])))
        entries = await self._place_cells(placed)
        # commit the stripe map on the master: this flips reads over to
        # the cells and starts retiring the replicated copies
        await self._leader_call(RpcCode.EC_COMMIT_STRIPE, pack({
            "block_id": block_id, "cells": entries}))

    async def _run_ec_convert_task(self, task: TaskInfo) -> None:
        """Stripe a batch of cold replicated blocks: pull each block
        (verified), RS-encode it into k+m cells, land the cells on their
        planned workers, and EC_COMMIT_STRIPE. One bad block fails the
        task (the job planner re-plans on resubmit) but blocks already
        committed stay converted — the conversion is per-stripe atomic."""
        from curvine_tpu.common import ec as eclib
        async with self._task_sem:
            payload = task.payload or {}
            done = 0
            try:
                prof = eclib.ECProfile.parse(payload.get("profile", ""))
                for plan in payload.get("blocks", []):
                    await self._convert_one_stripe(prof, plan)
                    done += 1
                task.state = JobState.COMPLETED
            except Exception as e:  # noqa: BLE001
                task.state = JobState.FAILED
                task.message = str(e)
                log.warning("ec convert task %s failed after %d stripes: "
                            "%s", task.task_id, done, e)
            task.loaded_len = done
            task.worker_id = self.worker_id
            try:
                await self._leader_call(RpcCode.REPORT_TASK,
                                        pack({"task": task.to_wire()}))
            except Exception as e:
                log.warning("task report failed: %s", e)

    async def _reconstruct_cell(self, ecq: dict, cell_id: int) -> None:
        """Rebuild one lost/rotten stripe cell from any k live sibling
        cells (decode, or re-encode for a parity target) and commit it
        locally under a fresh checksum."""
        from curvine_tpu.common import ec as eclib
        prof = eclib.ECProfile.parse(ecq["profile"])
        cell_size = ecq["cell_size"]
        slots: list[bytes | None] = [None] * (prof.k + prof.m)
        got = 0
        for s in ecq["sources"]:
            if got >= prof.k:
                break
            try:
                b = await self._pull_verified(
                    WorkerAddress.from_wire(s["addr"]), s["block_id"])
            except Exception as e:  # noqa: BLE001 — source died mid-heal
                log.debug("cell source %d unavailable: %s",
                          s["block_id"], e)
                continue
            if len(b) != cell_size:
                continue             # partial/stale copy: never decode it
            slots[s["index"]] = b
            got += 1
        if got < prof.k:
            raise err.BlockNotFound(
                f"cell {cell_id}: only {got}/{prof.k} sibling cells "
                f"readable")
        idx = ecq["cell_index"]
        rebuilt = await asyncio.to_thread(
            eclib.reconstruct, prof, slots, [idx])
        await asyncio.to_thread(self._write_local_cell, cell_id,
                                bytes(rebuilt[idx]))
