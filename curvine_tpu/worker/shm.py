"""Shared-memory block export: sealed memfds + SCM_RIGHTS hand-off.

The worker-side half of the shm short-circuit read plane
(docs/data-plane.md). For MEM-tier file-layout blocks the worker keeps
a bounded cache of sealed memfd copies; a co-located client that saw
the ``shm``/``shm_sock`` capability flags on its GET_BLOCK_INFO probe
connects to the unix side channel, sends the block id, and receives the
fd in SCM_RIGHTS ancillary data — after which every read of the block
is an mmap slice with zero RPCs and zero copies.

Shape: HDFS short-circuit local reads (DfsClientShm / the
DomainSocket fd-passing plane), adapted to sealed memfds so the handed
fd is immutable by construction: F_SEAL_SHRINK|GROW|WRITE mean the
bytes a client mapped can never change under it, and eviction on the
worker merely closes OUR fd — client-held dups keep the pages alive
(the same unlink semantics the fd-based short-circuit path relies on).

asyncio cannot carry SCM_RIGHTS, so the side channel is a small
blocking AF_UNIX listener on a daemon thread; requests are one fixed
8-byte frame and replies one 16-byte frame, so a request is served in
microseconds and a thread per accepted connection stays cheap (clients
connect once per block, not per read)."""

from __future__ import annotations

import array
import logging
import os
import socket
import struct
import tempfile
import threading

log = logging.getLogger(__name__)

# request: little-endian u64 block id.  reply: i8 status + 7 pad bytes
# + u64 block length; status 0 carries the fd in SCM_RIGHTS ancillary.
_REQ = struct.Struct("<Q")
_REP = struct.Struct("<b7xQ")
OK = 0
NOT_FOUND = 1
ERROR = 2

_SENDFILE_CHUNK = 8 * 1024 * 1024


def shm_supported() -> bool:
    """memfd_create + unix-socket fd passing: Linux, py3.8+."""
    return hasattr(os, "memfd_create") and hasattr(socket, "SCM_RIGHTS")


def channel_path(port: int) -> str:
    """Side-channel socket path: short (AF_UNIX caps sun_path at ~108
    bytes, so the worker's data dir — often a deep tmp path in tests —
    is not usable), unique per process+port."""
    return os.path.join(tempfile.gettempdir(),
                        f"cv-shm-{os.getpid()}-{port}.sock")


def _seal(fd: int) -> None:
    import fcntl
    seals = (fcntl.F_SEAL_SHRINK | fcntl.F_SEAL_GROW
             | fcntl.F_SEAL_WRITE | fcntl.F_SEAL_SEAL)
    fcntl.fcntl(fd, fcntl.F_ADD_SEALS, seals)


class ShmExporter:
    """Bounded LRU of sealed-memfd block copies.

    ``export`` returns a worker-owned fd for a committed MEM-tier block:
    a memfd the block file's bytes were sendfile'd into, then sealed.
    Eviction (LRU past ``cap``) and ``invalidate`` (block deleted) close
    the worker's fd only — dups already handed to clients stay valid.
    Thread-safe: called from the side-channel thread and the event
    loop."""

    def __init__(self, cap: int = 128):
        self.cap = max(1, cap)
        self._lock = threading.Lock()
        # block_id -> (memfd, length); dict order is the LRU order
        self._fds: dict[int, tuple[int, int]] = {}
        self.exports = 0        # memfd copies materialized
        self.hits = 0           # grants served from the cache
        self.evictions = 0

    def export(self, block_id: int, path: str, length: int) -> tuple[int, int]:
        """(memfd, length) for the block file at ``path``; cached."""
        with self._lock:
            ent = self._fds.pop(block_id, None)
            if ent is not None:
                self._fds[block_id] = ent       # refresh LRU position
                self.hits += 1
                return ent
        fd = self._copy_to_memfd(block_id, path, length)
        with self._lock:
            ent = self._fds.pop(block_id, None)
            if ent is not None:
                # raced with another grant: keep the first copy
                self._fds[block_id] = ent
                self.hits += 1
                self._close(fd)
                return ent
            while len(self._fds) >= self.cap:
                old_fd, _n = self._fds.pop(next(iter(self._fds)))
                self._close(old_fd)
                self.evictions += 1
            self._fds[block_id] = (fd, length)
            self.exports += 1
            return fd, length

    @staticmethod
    def _copy_to_memfd(block_id: int, path: str, length: int) -> int:
        src = os.open(path, os.O_RDONLY)
        try:
            fd = os.memfd_create(f"cv-blk-{block_id}",
                                 os.MFD_CLOEXEC | os.MFD_ALLOW_SEALING)
            try:
                os.ftruncate(fd, length)
                off = 0
                while off < length:
                    n = os.sendfile(fd, src, off,
                                    min(_SENDFILE_CHUNK, length - off))
                    if n == 0:
                        raise OSError(
                            f"short copy of block {block_id}: "
                            f"{off}/{length}")
                    off += n
                _seal(fd)
            except OSError:
                os.close(fd)
                raise
            return fd
        finally:
            os.close(src)

    @staticmethod
    def _close(fd: int) -> None:
        try:
            os.close(fd)
        except OSError:
            pass

    def invalidate(self, block_id: int) -> None:
        with self._lock:
            ent = self._fds.pop(block_id, None)
        if ent is not None:
            self._close(ent[0])

    def __len__(self) -> int:
        with self._lock:
            return len(self._fds)

    def close(self) -> None:
        with self._lock:
            fds, self._fds = list(self._fds.values()), {}
        for fd, _n in fds:
            self._close(fd)


class WarmShmCache:
    """Byte-bounded warm cache of sealed-memfd copies for blocks BELOW
    the MEM tier (docs/data-plane.md).

    A read-hot SSD/HDD block (heat over ``worker.shm_warm_min_reads``,
    accumulated through the SC_READ_REPORT rail) gets its bytes copied
    once into a sealed memfd; from then on co-located clients serve it
    exactly like a MEM export — zero RPCs, zero syscalls per read. The
    cache is bounded in BYTES (``worker.shm_warm_cap_mb``) because warm
    copies are anonymous memory the MEM tier doesn't account for, and
    eviction runs through the same admission policy family as the MEM
    tier (S3-FIFO by default): a one-touch scan that sneaks a copy in
    leaves through the probationary queue without displacing the warm
    working set. Eviction and invalidation close the WORKER's fd only —
    client-held dups and mappings stay valid (unlink semantics), same
    contract as ShmExporter."""

    def __init__(self, cap_bytes: int, admission: str = "s3fifo",
                 ghost_entries: int = 1024):
        from curvine_tpu.common.cache import make_policy
        self.cap_bytes = max(0, cap_bytes)
        self.policy = make_policy(admission, ghost_entries=ghost_entries)
        self._lock = threading.Lock()
        # block_id -> (memfd, length); insertion order only (the policy
        # owns the eviction order, not this dict)
        self._fds: dict[int, tuple[int, int]] = {}
        self._atime: dict[int, float] = {}
        self.bytes = 0
        self.exports = 0        # warm copies materialized
        self.hits = 0           # grants served from the cache
        self.evictions = 0

    def export(self, block_id: int, path: str, length: int) -> tuple[int, int]:
        """(memfd, length) for the block file at ``path``; copies once,
        then serves from the cache. Raises LookupError for blocks larger
        than the whole cache (never worth evicting everything for)."""
        import time as _time
        with self._lock:
            ent = self._fds.get(block_id)
            if ent is not None:
                self.hits += 1
                self._atime[block_id] = _time.time()
                self.policy.hits += 1
                self.policy.on_access(block_id)
                return ent
        if length > self.cap_bytes:
            raise LookupError(
                f"block {block_id} ({length}B) exceeds warm cache")
        fd = ShmExporter._copy_to_memfd(block_id, path, length)
        with self._lock:
            ent = self._fds.get(block_id)
            if ent is not None:
                # raced with another grant: keep the first copy
                self.hits += 1
                self._close(fd)
                return ent
            self._evict_locked(length)
            self._fds[block_id] = (fd, length)
            self._atime[block_id] = _time.time()
            self.bytes += length
            self.policy.on_admit(block_id, length)
            self.exports += 1
            return fd, length

    def _evict_locked(self, need: int) -> None:
        """Make room for ``need`` bytes, closing victims in policy
        order (S3-FIFO: probationary one-touch copies first)."""
        if self.bytes + need <= self.cap_bytes:
            return
        order = iter(self.policy.victim_order(
            [(k, self._atime.get(k, 0.0)) for k in self._fds]))
        while self.bytes + need > self.cap_bytes and self._fds:
            victim = next(order, None)
            if victim is None or victim not in self._fds:
                if victim is None:          # policy ran dry: FIFO rest
                    victim = next(iter(self._fds))
                else:
                    continue
            fd, n = self._fds.pop(victim)
            self._atime.pop(victim, None)
            self._close(fd)
            self.bytes -= n
            self.policy.on_remove(victim, evicted=True)
            self.evictions += 1

    def invalidate(self, block_id: int) -> None:
        """Block deleted or moved tiers: drop the warm copy (a plain
        removal, not an eviction — no ghost entry, the block is gone)."""
        with self._lock:
            ent = self._fds.pop(block_id, None)
            if ent is not None:
                self._atime.pop(block_id, None)
                self.bytes -= ent[1]
                self.policy.on_remove(block_id, evicted=False)
        if ent is not None:
            self._close(ent[0])

    @staticmethod
    def _close(fd: int) -> None:
        try:
            os.close(fd)
        except OSError:
            pass

    def __contains__(self, block_id: int) -> bool:
        with self._lock:
            return block_id in self._fds

    def __len__(self) -> int:
        with self._lock:
            return len(self._fds)

    def stats(self) -> dict:
        with self._lock:
            out = {"entries": len(self._fds), "bytes": self.bytes,
                   "exports": self.exports, "hits": self.hits,
                   "evictions": self.evictions}
        out.update({f"policy_{k}": v for k, v in self.policy.stats().items()})
        return out

    def close(self) -> None:
        with self._lock:
            fds, self._fds = list(self._fds.values()), {}
            self._atime.clear()
            self.bytes = 0
        for fd, _n in fds:
            self._close(fd)


class ShmChannel:
    """AF_UNIX SCM_RIGHTS side channel serving block fds.

    ``grant(block_id) -> (fd, length)`` is the server's policy hook
    (resolve the block, check the tier, export through the
    ShmExporter); it runs on the channel's threads, so it must only
    touch thread-safe state (BlockStore and ShmExporter both take their
    own locks)."""

    def __init__(self, path: str, grant):
        self.path = path
        self.grant = grant
        self._srv: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(self.path)
            srv.listen(64)
        except OSError:
            srv.close()
            raise
        self._srv = srv
        self._thread = threading.Thread(
            target=self._accept_loop, name="shm-channel", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break                    # listener closed (stop)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        """One client connection: fixed-size request/reply frames until
        EOF (clients typically fetch one fd per connection)."""
        with conn:
            conn.settimeout(5.0)
            while not self._stop.is_set():
                try:
                    req = self._recv_exact(conn, _REQ.size)
                except OSError:
                    return
                if req is None:
                    return               # clean EOF
                (block_id,) = _REQ.unpack(req)
                try:
                    fd, length = self.grant(block_id)
                except LookupError:
                    self._reply(conn, NOT_FOUND, 0, None)
                    continue
                except Exception as e:  # noqa: BLE001 — keep serving
                    log.debug("shm grant for %d failed: %s", block_id, e)
                    self._reply(conn, ERROR, 0, None)
                    continue
                if not self._reply(conn, OK, length, fd):
                    return

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            got = conn.recv(n - len(buf))
            if not got:
                return None if not buf else buf
            buf += got
        return buf

    @staticmethod
    def _reply(conn: socket.socket, status: int, length: int,
               fd: int | None) -> bool:
        anc = []
        if fd is not None:
            anc = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                    array.array("i", [fd]))]
        try:
            conn.sendmsg([_REP.pack(status, length)], anc)
            return True
        except OSError:
            return False

    def stop(self) -> None:
        self._stop.set()
        srv, self._srv = self._srv, None
        if srv is not None:
            # close() alone does NOT wake a thread blocked in accept()
            # on Linux; shutdown() forces accept to return so the join
            # below is immediate instead of eating its timeout
            try:
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                srv.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)


def fetch_block_fd(sock_path: str, block_id: int,
                   timeout: float = 5.0) -> tuple[int, int]:
    """Client half: connect to the worker's side channel, request one
    block, return (fd, length). Blocking — run under asyncio.to_thread.
    Raises LookupError when the worker no longer serves the block and
    OSError on channel trouble (both are clean fallbacks to the socket
    read path)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(sock_path)
        s.sendall(_REQ.pack(block_id))
        data, anc, _flags, _addr = s.recvmsg(
            _REP.size, socket.CMSG_SPACE(array.array("i").itemsize))
        if len(data) < _REP.size:
            raise ConnectionResetError("shm channel closed mid-reply")
        status, length = _REP.unpack(data)
        fds = array.array("i")
        for level, ctype, cdata in anc:
            if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                fds.frombytes(cdata[:len(cdata)
                                    - (len(cdata) % fds.itemsize)])
        if status == NOT_FOUND:
            for fd in fds:
                os.close(fd)
            raise LookupError(f"block {block_id} not shm-served")
        if status != OK or not fds:
            for fd in fds:
                os.close(fd)
            raise OSError(f"shm grant failed (status {status})")
        fd = fds[0]
        for extra in list(fds)[1:]:
            os.close(extra)
        return fd, length
