"""Shared-memory block export: sealed memfds + SCM_RIGHTS hand-off.

The worker-side half of the shm short-circuit read plane
(docs/data-plane.md). For MEM-tier file-layout blocks the worker keeps
a bounded cache of sealed memfd copies; a co-located client that saw
the ``shm``/``shm_sock`` capability flags on its GET_BLOCK_INFO probe
connects to the unix side channel, sends the block id, and receives the
fd in SCM_RIGHTS ancillary data — after which every read of the block
is an mmap slice with zero RPCs and zero copies.

Shape: HDFS short-circuit local reads (DfsClientShm / the
DomainSocket fd-passing plane), adapted to sealed memfds so the handed
fd is immutable by construction: F_SEAL_SHRINK|GROW|WRITE mean the
bytes a client mapped can never change under it, and eviction on the
worker merely closes OUR fd — client-held dups keep the pages alive
(the same unlink semantics the fd-based short-circuit path relies on).

asyncio cannot carry SCM_RIGHTS, so the side channel is a small
blocking AF_UNIX listener on a daemon thread; requests are one fixed
8-byte frame and replies one 16-byte frame, so a request is served in
microseconds and a thread per accepted connection stays cheap (clients
connect once per block, not per read)."""

from __future__ import annotations

import array
import logging
import os
import socket
import struct
import tempfile
import threading

log = logging.getLogger(__name__)

# request: little-endian u64 block id.  reply: i8 status + 7 pad bytes
# + u64 block length; status 0 carries the fd in SCM_RIGHTS ancillary.
_REQ = struct.Struct("<Q")
_REP = struct.Struct("<b7xQ")
OK = 0
NOT_FOUND = 1
ERROR = 2

_SENDFILE_CHUNK = 8 * 1024 * 1024


def shm_supported() -> bool:
    """memfd_create + unix-socket fd passing: Linux, py3.8+."""
    return hasattr(os, "memfd_create") and hasattr(socket, "SCM_RIGHTS")


def channel_path(port: int) -> str:
    """Side-channel socket path: short (AF_UNIX caps sun_path at ~108
    bytes, so the worker's data dir — often a deep tmp path in tests —
    is not usable), unique per process+port."""
    return os.path.join(tempfile.gettempdir(),
                        f"cv-shm-{os.getpid()}-{port}.sock")


def _seal(fd: int) -> None:
    import fcntl
    seals = (fcntl.F_SEAL_SHRINK | fcntl.F_SEAL_GROW
             | fcntl.F_SEAL_WRITE | fcntl.F_SEAL_SEAL)
    fcntl.fcntl(fd, fcntl.F_ADD_SEALS, seals)


class ShmExporter:
    """Bounded LRU of sealed-memfd block copies.

    ``export`` returns a worker-owned fd for a committed MEM-tier block:
    a memfd the block file's bytes were sendfile'd into, then sealed.
    Eviction (LRU past ``cap``) and ``invalidate`` (block deleted) close
    the worker's fd only — dups already handed to clients stay valid.
    Thread-safe: called from the side-channel thread and the event
    loop."""

    def __init__(self, cap: int = 128):
        self.cap = max(1, cap)
        self._lock = threading.Lock()
        # block_id -> (memfd, length); dict order is the LRU order
        self._fds: dict[int, tuple[int, int]] = {}
        self.exports = 0        # memfd copies materialized
        self.hits = 0           # grants served from the cache
        self.evictions = 0

    def export(self, block_id: int, path: str, length: int) -> tuple[int, int]:
        """(memfd, length) for the block file at ``path``; cached."""
        with self._lock:
            ent = self._fds.pop(block_id, None)
            if ent is not None:
                self._fds[block_id] = ent       # refresh LRU position
                self.hits += 1
                return ent
        fd = self._copy_to_memfd(block_id, path, length)
        with self._lock:
            ent = self._fds.pop(block_id, None)
            if ent is not None:
                # raced with another grant: keep the first copy
                self._fds[block_id] = ent
                self.hits += 1
                self._close(fd)
                return ent
            while len(self._fds) >= self.cap:
                old_fd, _n = self._fds.pop(next(iter(self._fds)))
                self._close(old_fd)
                self.evictions += 1
            self._fds[block_id] = (fd, length)
            self.exports += 1
            return fd, length

    @staticmethod
    def _copy_to_memfd(block_id: int, path: str, length: int) -> int:
        src = os.open(path, os.O_RDONLY)
        try:
            fd = os.memfd_create(f"cv-blk-{block_id}",
                                 os.MFD_CLOEXEC | os.MFD_ALLOW_SEALING)
            try:
                os.ftruncate(fd, length)
                off = 0
                while off < length:
                    n = os.sendfile(fd, src, off,
                                    min(_SENDFILE_CHUNK, length - off))
                    if n == 0:
                        raise OSError(
                            f"short copy of block {block_id}: "
                            f"{off}/{length}")
                    off += n
                _seal(fd)
            except OSError:
                os.close(fd)
                raise
            return fd
        finally:
            os.close(src)

    @staticmethod
    def _close(fd: int) -> None:
        try:
            os.close(fd)
        except OSError:
            pass

    def invalidate(self, block_id: int) -> None:
        with self._lock:
            ent = self._fds.pop(block_id, None)
        if ent is not None:
            self._close(ent[0])

    def __len__(self) -> int:
        with self._lock:
            return len(self._fds)

    def close(self) -> None:
        with self._lock:
            fds, self._fds = list(self._fds.values()), {}
        for fd, _n in fds:
            self._close(fd)


class ShmChannel:
    """AF_UNIX SCM_RIGHTS side channel serving block fds.

    ``grant(block_id) -> (fd, length)`` is the server's policy hook
    (resolve the block, check the tier, export through the
    ShmExporter); it runs on the channel's threads, so it must only
    touch thread-safe state (BlockStore and ShmExporter both take their
    own locks)."""

    def __init__(self, path: str, grant):
        self.path = path
        self.grant = grant
        self._srv: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def start(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(self.path)
            srv.listen(64)
        except OSError:
            srv.close()
            raise
        self._srv = srv
        self._thread = threading.Thread(
            target=self._accept_loop, name="shm-channel", daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break                    # listener closed (stop)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        """One client connection: fixed-size request/reply frames until
        EOF (clients typically fetch one fd per connection)."""
        with conn:
            conn.settimeout(5.0)
            while not self._stop.is_set():
                try:
                    req = self._recv_exact(conn, _REQ.size)
                except OSError:
                    return
                if req is None:
                    return               # clean EOF
                (block_id,) = _REQ.unpack(req)
                try:
                    fd, length = self.grant(block_id)
                except LookupError:
                    self._reply(conn, NOT_FOUND, 0, None)
                    continue
                except Exception as e:  # noqa: BLE001 — keep serving
                    log.debug("shm grant for %d failed: %s", block_id, e)
                    self._reply(conn, ERROR, 0, None)
                    continue
                if not self._reply(conn, OK, length, fd):
                    return

    @staticmethod
    def _recv_exact(conn: socket.socket, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            got = conn.recv(n - len(buf))
            if not got:
                return None if not buf else buf
            buf += got
        return buf

    @staticmethod
    def _reply(conn: socket.socket, status: int, length: int,
               fd: int | None) -> bool:
        anc = []
        if fd is not None:
            anc = [(socket.SOL_SOCKET, socket.SCM_RIGHTS,
                    array.array("i", [fd]))]
        try:
            conn.sendmsg([_REP.pack(status, length)], anc)
            return True
        except OSError:
            return False

    def stop(self) -> None:
        self._stop.set()
        srv, self._srv = self._srv, None
        if srv is not None:
            # close() alone does NOT wake a thread blocked in accept()
            # on Linux; shutdown() forces accept to return so the join
            # below is immediate instead of eating its timeout
            try:
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                srv.close()
            except OSError:
                pass
        try:
            os.unlink(self.path)
        except OSError:
            pass
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)


def fetch_block_fd(sock_path: str, block_id: int,
                   timeout: float = 5.0) -> tuple[int, int]:
    """Client half: connect to the worker's side channel, request one
    block, return (fd, length). Blocking — run under asyncio.to_thread.
    Raises LookupError when the worker no longer serves the block and
    OSError on channel trouble (both are clean fallbacks to the socket
    read path)."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(sock_path)
        s.sendall(_REQ.pack(block_id))
        data, anc, _flags, _addr = s.recvmsg(
            _REP.size, socket.CMSG_SPACE(array.array("i").itemsize))
        if len(data) < _REP.size:
            raise ConnectionResetError("shm channel closed mid-reply")
        status, length = _REP.unpack(data)
        fds = array.array("i")
        for level, ctype, cdata in anc:
            if level == socket.SOL_SOCKET and ctype == socket.SCM_RIGHTS:
                fds.frombytes(cdata[:len(cdata)
                                    - (len(cdata) % fds.itemsize)])
        if status == NOT_FOUND:
            for fd in fds:
                os.close(fd)
            raise LookupError(f"block {block_id} not shm-served")
        if status != OK or not fds:
            for fd in fds:
                os.close(fd)
            raise OSError(f"shm grant failed (status {status})")
        fd = fds[0]
        for extra in list(fds)[1:]:
            os.close(extra)
        return fd, length
