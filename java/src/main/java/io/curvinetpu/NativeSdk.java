package io.curvinetpu;

/**
 * JNI binding of the native curvine-tpu client ABI (csrc/sdk.cc,
 * libcurvine_sdk.so). Parity: the reference's Java SDK binds a native
 * client the same way (curvine-libsdk/src/java/java_abi.rs behind
 * io/curvine/CurvineNative.java); here the native client is the C++
 * wire-protocol SDK and this class is its Java face.
 *
 * All handles are opaque native pointers. Methods returning int follow
 * the C ABI convention: 0 success, -1 failure (read lastError()).
 * Thread-safety: a client handle and any streams derived from it must
 * be confined to one thread at a time (the C client is not locked).
 */
final class NativeSdk {

    static {
        System.loadLibrary("curvine_jni"); // libcurvine_jni.so wraps libcurvine_sdk
    }

    private NativeSdk() {}

    // ---- client lifecycle ----
    static native long connect(String host, int port, String user);

    static native void close(long handle);

    static native String lastError();

    static native int lastErrorCode();

    // ---- metadata ----
    static native int mkdir(long handle, String path);

    static native int delete(long handle, String path, boolean recursive);

    static native int rename(long handle, String src, String dst);

    static native int exists(long handle, String path); // 1/0/-1

    static native long len(long handle, String path);   // -1: not found

    static native String list(long handle, String path); // JSON array

    static native String stat(long handle, String path); // JSON object

    // ---- whole-file ----
    static native int put(long handle, String path, byte[] data, long n);

    static native long get(long handle, String path, byte[] buf, long cap);

    // ---- streaming reader ----
    static native long openReader(long handle, String path);

    static native long read(long reader, byte[] buf, int off, int cap);

    static native long seek(long reader, long pos);

    static native long readerLen(long reader);

    static native long readerPos(long reader);

    static native int closeReader(long reader);

    // ---- streaming writer ----
    static native long openWriter(long handle, String path, boolean overwrite);

    static native int write(long writer, byte[] buf, int off, int n);

    static native int flush(long writer);

    static native long writerPos(long writer);

    static native int closeWriter(long writer);
}
