package io.curvinetpu;

import java.io.IOException;
import java.io.OutputStream;

/**
 * OutputStream over a native streaming writer handle (parity:
 * curvine-libsdk/java .../CurvineOutputStream.java over lib_fs_writer).
 * Bytes stream to workers block by block as they are written; close()
 * commits outstanding blocks and completes the file on the master —
 * until then the file is visible but incomplete.
 */
public final class CurvineOutputStream extends OutputStream {

    private long handle;
    private final byte[] one = new byte[1];

    CurvineOutputStream(long handle) {
        this.handle = handle;
    }

    private long h() throws IOException {
        if (handle == 0) {
            throw new IOException("stream closed");
        }
        return handle;
    }

    @Override
    public void write(int b) throws IOException {
        one[0] = (byte) b;
        write(one, 0, 1);
    }

    @Override
    public void write(byte[] b, int off, int len) throws IOException {
        if (off < 0 || len < 0 || off + len > b.length) {
            throw new IndexOutOfBoundsException();
        }
        if (len == 0) {
            return;
        }
        if (NativeSdk.write(h(), b, off, len) != 0) {
            throw CurvineException.fromNative();
        }
    }

    public long getPos() throws IOException {
        return NativeSdk.writerPos(h());
    }

    @Override
    public void flush() throws IOException {
        if (NativeSdk.flush(h()) != 0) {
            throw CurvineException.fromNative();
        }
    }

    @Override
    public void close() throws IOException {
        if (handle != 0) {
            long h = handle;
            handle = 0;
            if (NativeSdk.closeWriter(h) != 0) {
                throw CurvineException.fromNative();
            }
        }
    }
}
