package io.curvinetpu;

import java.util.ArrayList;
import java.util.HashMap;
import java.util.List;
import java.util.Map;

/**
 * File metadata as returned by the master (parity:
 * curvine-libsdk/java .../CurvineFsStat.java). Parsed from the flat
 * JSON objects the native ABI emits (cv_sdk_stat / cv_sdk_list) with a
 * small built-in parser so the SDK has zero third-party dependencies.
 */
public final class CurvineFileStatus {

    public final String name;
    public final long len;
    public final boolean isDir;
    public final long mtime;
    public final long atime;
    public final int mode;
    public final int replicas;
    public final long blockSize;
    public final boolean isComplete;
    public final String owner;
    public final String group;

    CurvineFileStatus(Map<String, Object> m) {
        this.name = str(m, "name");
        this.len = num(m, "len");
        this.isDir = bool(m, "is_dir");
        this.mtime = num(m, "mtime");
        this.atime = num(m, "atime");
        this.mode = (int) num(m, "mode");
        this.replicas = (int) num(m, "replicas");
        this.blockSize = num(m, "block_size");
        this.isComplete = bool(m, "is_complete");
        this.owner = str(m, "owner");
        this.group = str(m, "group");
    }

    private static String str(Map<String, Object> m, String k) {
        Object v = m.get(k);
        return v instanceof String ? (String) v : "";
    }

    private static long num(Map<String, Object> m, String k) {
        Object v = m.get(k);
        return v instanceof Long ? (Long) v : 0L;
    }

    private static boolean bool(Map<String, Object> m, String k) {
        Object v = m.get(k);
        return v instanceof Boolean && (Boolean) v;
    }

    @Override
    public String toString() {
        return String.format("%s%s len=%d owner=%s:%s mode=%o",
                name, isDir ? "/" : "", len, owner, group, mode);
    }

    // ------------------------------------------------------------------
    // Minimal JSON reader for the flat objects/arrays the C ABI produces
    // (string/long/boolean values only; strings use \uXXXX and \" \\
    // escapes — exactly what csrc/sdk.cc json_escape emits).
    // ------------------------------------------------------------------

    static final class Json {
        private final String s;
        private int i;

        Json(String s) {
            this.s = s;
        }

        static Map<String, Object> object(String text) {
            Json j = new Json(text);
            j.ws();
            Map<String, Object> m = j.obj();
            return m;
        }

        static List<Map<String, Object>> array(String text) {
            Json j = new Json(text);
            j.ws();
            j.expect('[');
            List<Map<String, Object>> out = new ArrayList<>();
            j.ws();
            if (j.peek() == ']') {
                j.i++;
                return out;
            }
            while (true) {
                j.ws();
                out.add(j.obj());
                j.ws();
                char c = j.next();
                if (c == ']') {
                    return out;
                }
                if (c != ',') {
                    throw new IllegalArgumentException("bad JSON array");
                }
            }
        }

        private Map<String, Object> obj() {
            expect('{');
            Map<String, Object> m = new HashMap<>();
            ws();
            if (peek() == '}') {
                i++;
                return m;
            }
            while (true) {
                ws();
                String key = string();
                ws();
                expect(':');
                ws();
                m.put(key, value());
                ws();
                char c = next();
                if (c == '}') {
                    return m;
                }
                if (c != ',') {
                    throw new IllegalArgumentException("bad JSON object");
                }
            }
        }

        private Object value() {
            char c = peek();
            if (c == '"') {
                return string();
            }
            if (s.startsWith("true", i)) {
                i += 4;
                return Boolean.TRUE;
            }
            if (s.startsWith("false", i)) {
                i += 5;
                return Boolean.FALSE;
            }
            int start = i;
            while (i < s.length() && (s.charAt(i) == '-' || s.charAt(i) == '+'
                    || Character.isDigit(s.charAt(i)))) {
                i++;
            }
            if (i == start) {
                throw new IllegalArgumentException("bad JSON value at " + i);
            }
            return Long.parseLong(s.substring(start, i));
        }

        private String string() {
            expect('"');
            StringBuilder b = new StringBuilder();
            while (true) {
                char c = next();
                if (c == '"') {
                    return b.toString();
                }
                if (c == '\\') {
                    char e = next();
                    if (e == 'u') {
                        b.append((char) Integer.parseInt(
                                s.substring(i, i + 4), 16));
                        i += 4;
                    } else {
                        b.append(e); // \" and \\ pass through
                    }
                } else {
                    b.append(c);
                }
            }
        }

        private void ws() {
            while (i < s.length() && Character.isWhitespace(s.charAt(i))) {
                i++;
            }
        }

        private char peek() {
            return s.charAt(i);
        }

        private char next() {
            return s.charAt(i++);
        }

        private void expect(char c) {
            if (next() != c) {
                throw new IllegalArgumentException(
                        "expected '" + c + "' at " + (i - 1));
            }
        }
    }
}
