package io.curvinetpu;

import java.io.IOException;
import java.util.ArrayList;
import java.util.List;
import java.util.Map;

/**
 * Java client for a curvine-tpu cluster over the native SDK (parity:
 * curvine-libsdk/java .../CurvineFileSystem.java). One instance wraps
 * one native client handle; use from one thread at a time, or one
 * instance per thread (connections are cheap).
 *
 * <pre>
 * try (CurvineTpuFileSystem fs =
 *         CurvineTpuFileSystem.connect("master-host", 8995, "alice")) {
 *     fs.mkdir("/data");
 *     try (CurvineOutputStream out = fs.create("/data/x.bin", true)) {
 *         out.write(bytes);
 *     }
 *     try (CurvineInputStream in = fs.open("/data/x.bin")) {
 *         in.read(buf);
 *     }
 * }
 * </pre>
 */
public final class CurvineTpuFileSystem implements AutoCloseable {

    private long handle;

    private CurvineTpuFileSystem(long handle) {
        this.handle = handle;
    }

    /** Dial the master. user "" means root (superuser in default conf). */
    public static CurvineTpuFileSystem connect(String host, int port,
            String user) throws IOException {
        long h = NativeSdk.connect(host, port, user == null ? "" : user);
        if (h == 0) {
            throw CurvineException.fromNative();
        }
        return new CurvineTpuFileSystem(h);
    }

    private long h() throws IOException {
        if (handle == 0) {
            throw new IOException("filesystem closed");
        }
        return handle;
    }

    public void mkdir(String path) throws IOException {
        if (NativeSdk.mkdir(h(), path) != 0) {
            throw CurvineException.fromNative();
        }
    }

    public void delete(String path, boolean recursive) throws IOException {
        if (NativeSdk.delete(h(), path, recursive) != 0) {
            throw CurvineException.fromNative();
        }
    }

    public void rename(String src, String dst) throws IOException {
        if (NativeSdk.rename(h(), src, dst) != 0) {
            throw CurvineException.fromNative();
        }
    }

    public boolean exists(String path) throws IOException {
        int rc = NativeSdk.exists(h(), path);
        if (rc < 0) {
            throw CurvineException.fromNative();
        }
        return rc == 1;
    }

    public CurvineFileStatus getFileStatus(String path) throws IOException {
        String json = NativeSdk.stat(h(), path);
        if (json == null) {
            throw CurvineException.fromNative();
        }
        return new CurvineFileStatus(CurvineFileStatus.Json.object(json));
    }

    public List<CurvineFileStatus> listStatus(String path)
            throws IOException {
        String json = NativeSdk.list(h(), path);
        if (json == null) {
            throw CurvineException.fromNative();
        }
        List<CurvineFileStatus> out = new ArrayList<>();
        for (Map<String, Object> m : CurvineFileStatus.Json.array(json)) {
            out.add(new CurvineFileStatus(m));
        }
        return out;
    }

    /** Open a seekable read stream. */
    public CurvineInputStream open(String path) throws IOException {
        long r = NativeSdk.openReader(h(), path);
        if (r == 0) {
            throw CurvineException.fromNative();
        }
        return new CurvineInputStream(r);
    }

    /** Create a file and return its write stream. */
    public CurvineOutputStream create(String path, boolean overwrite)
            throws IOException {
        long w = NativeSdk.openWriter(h(), path, overwrite);
        if (w == 0) {
            throw CurvineException.fromNative();
        }
        return new CurvineOutputStream(w);
    }

    /** Whole-file write (creates with overwrite). */
    public void put(String path, byte[] data) throws IOException {
        if (NativeSdk.put(h(), path, data, data.length) != 0) {
            throw CurvineException.fromNative();
        }
    }

    /** Whole-file read. Files beyond a byte[]'s reach need open(). */
    public byte[] get(String path) throws IOException {
        long n = NativeSdk.len(h(), path);
        if (n < 0) {
            throw CurvineException.fromNative();
        }
        if (n > Integer.MAX_VALUE - 8) {
            throw new IOException("file too large for get(): " + n
                    + " bytes; use open() and stream");
        }
        byte[] buf = new byte[(int) n];
        long got = NativeSdk.get(h(), path, buf, buf.length);
        if (got < 0) {
            throw CurvineException.fromNative();
        }
        if (got != n) {
            byte[] trim = new byte[(int) got];
            System.arraycopy(buf, 0, trim, 0, (int) got);
            return trim;
        }
        return buf;
    }

    @Override
    public void close() {
        if (handle != 0) {
            NativeSdk.close(handle);
            handle = 0;
        }
    }
}
