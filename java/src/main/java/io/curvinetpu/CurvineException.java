package io.curvinetpu;

import java.io.IOException;

/**
 * IOException carrying the wire ErrorCode of a remote failure (0 for
 * local/transport errors). Parity:
 * curvine-libsdk/java .../exception/CurvineException.java.
 */
public class CurvineException extends IOException {

    private final int code;

    public CurvineException(String message, int code) {
        super(message);
        this.code = code;
    }

    /** Wire ErrorCode (curvine_tpu.common.errors.ErrorCode), 0 = local. */
    public int getCode() {
        return code;
    }

    /** Build from the native thread-local last-error state. */
    static CurvineException fromNative() {
        return new CurvineException(NativeSdk.lastError(),
                NativeSdk.lastErrorCode());
    }
}
