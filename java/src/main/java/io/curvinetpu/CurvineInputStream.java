package io.curvinetpu;

import java.io.IOException;
import java.io.InputStream;

/**
 * Seekable InputStream over a native streaming reader handle (parity:
 * curvine-libsdk/java .../CurvineInputStream.java over lib_fs_reader).
 * Block streams are opened lazily and reopened at offset after seek().
 */
public final class CurvineInputStream extends InputStream {

    private long handle;
    private final byte[] one = new byte[1];

    CurvineInputStream(long handle) {
        this.handle = handle;
    }

    private long h() throws IOException {
        if (handle == 0) {
            throw new IOException("stream closed");
        }
        return handle;
    }

    @Override
    public int read() throws IOException {
        int n = read(one, 0, 1);
        return n <= 0 ? -1 : one[0] & 0xFF;
    }

    @Override
    public int read(byte[] b, int off, int len) throws IOException {
        if (off < 0 || len < 0 || off + len > b.length) {
            throw new IndexOutOfBoundsException();
        }
        if (len == 0) {
            return 0;
        }
        long got = NativeSdk.read(h(), b, off, len);
        if (got < 0) {
            throw CurvineException.fromNative();
        }
        return got == 0 ? -1 : (int) got;
    }

    /** Absolute seek; small forward hops reuse the open block stream. */
    public void seek(long pos) throws IOException {
        if (NativeSdk.seek(h(), pos) < 0) {
            throw CurvineException.fromNative();
        }
    }

    public long getPos() throws IOException {
        return NativeSdk.readerPos(h());
    }

    /** Total file length. */
    public long length() throws IOException {
        return NativeSdk.readerLen(h());
    }

    @Override
    public long skip(long n) throws IOException {
        long cur = getPos();
        long to = Math.min(length(), cur + Math.max(0, n));
        seek(to);
        return to - cur;
    }

    @Override
    public int available() throws IOException {
        return (int) Math.min(Integer.MAX_VALUE, length() - getPos());
    }

    @Override
    public void close() {
        if (handle != 0) {
            NativeSdk.closeReader(handle);
            handle = 0;
        }
    }
}
