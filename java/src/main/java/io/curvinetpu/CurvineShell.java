package io.curvinetpu;

import java.io.FileInputStream;
import java.io.FileOutputStream;
import java.io.IOException;
import java.io.InputStream;
import java.io.OutputStream;

/**
 * Minimal command-line shell over the SDK (parity:
 * curvine-libsdk/java .../CurvineShell.java). Doubles as the smoke test
 * a JDK-equipped environment runs against a live cluster:
 *
 * <pre>
 * java -cp curvine-tpu-sdk.jar io.curvinetpu.CurvineShell \
 *     --master host:port ls /          # also: mkdir put get cat rm stat
 * </pre>
 */
public final class CurvineShell {

    private CurvineShell() {}

    public static void main(String[] args) throws IOException {
        String master = "127.0.0.1:8995";
        int i = 0;
        if (args.length >= 2 && args[0].equals("--master")) {
            master = args[1];
            i = 2;
        }
        if (args.length - i < 1) {
            usage();
            return;
        }
        String host = master.substring(0, master.lastIndexOf(':'));
        int port = Integer.parseInt(
                master.substring(master.lastIndexOf(':') + 1));
        String cmd = args[i];
        try (CurvineTpuFileSystem fs =
                CurvineTpuFileSystem.connect(host, port, "")) {
            switch (cmd) {
                case "ls":
                    for (CurvineFileStatus st : fs.listStatus(args[i + 1])) {
                        System.out.println(st);
                    }
                    break;
                case "mkdir":
                    fs.mkdir(args[i + 1]);
                    break;
                case "put": { // put <local> <remote>
                    try (InputStream in = new FileInputStream(args[i + 1]);
                            CurvineOutputStream out =
                                    fs.create(args[i + 2], true)) {
                        copy(in, out);
                    }
                    break;
                }
                case "get": { // get <remote> <local>
                    try (CurvineInputStream in = fs.open(args[i + 1]);
                            OutputStream out =
                                    new FileOutputStream(args[i + 2])) {
                        copy(in, out);
                    }
                    break;
                }
                case "cat": {
                    try (CurvineInputStream in = fs.open(args[i + 1])) {
                        copy(in, System.out);
                    }
                    break;
                }
                case "rm":
                    fs.delete(args[i + 1], true);
                    break;
                case "stat":
                    System.out.println(fs.getFileStatus(args[i + 1]));
                    break;
                default:
                    usage();
            }
        }
    }

    private static void copy(InputStream in, OutputStream out)
            throws IOException {
        byte[] buf = new byte[1 << 20];
        int n;
        while ((n = in.read(buf)) > 0) {
            out.write(buf, 0, n);
        }
        out.flush();
    }

    private static void usage() {
        System.err.println("usage: CurvineShell [--master host:port] "
                + "ls|mkdir|put|get|cat|rm|stat <args>");
    }
}
