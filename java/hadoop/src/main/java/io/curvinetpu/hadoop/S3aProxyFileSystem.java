package io.curvinetpu.hadoop;

import java.io.IOException;
import java.net.URI;

import org.apache.hadoop.conf.Configuration;
import org.apache.hadoop.fs.FSDataInputStream;
import org.apache.hadoop.fs.Path;
import org.apache.hadoop.fs.s3a.S3AFileSystem;

/**
 * Drop-in S3A replacement that routes reads through the curvine-tpu
 * cache when the object is cached, falling back to real S3 otherwise
 * (parity: curvine-libsdk/java .../S3aProxyFileSystem.java — 96 LoC
 * that let existing {@code s3a://} jobs hit the cache with ONE conf
 * change):
 *
 * <pre>
 *   fs.s3a.impl       = io.curvinetpu.hadoop.S3aProxyFileSystem
 *   fs.cv.master.host = master-host
 *   fs.cv.master.port = 8995
 * </pre>
 *
 * Mapping mirrors the in-tree S3 gateway: {@code s3a://bucket/key} ↔
 * namespace path {@code /bucket/key} (override the prefix per bucket
 * with {@code fs.cv.s3a.prefix.<bucket> = /mnt/something}). Writes and
 * everything else stay on the real S3AFileSystem.
 */
public class S3aProxyFileSystem extends S3AFileSystem {

    private CurvineFileSystem cache;

    @Override
    public void initialize(URI name, Configuration conf) throws IOException {
        super.initialize(name, conf);
        if (conf.get("fs.cv.master.host") != null) {
            cache = new CurvineFileSystem();
            cache.initialize(URI.create(
                    "cv://" + conf.get("fs.cv.master.host") + ":"
                    + conf.get("fs.cv.master.port", "8995")), conf);
        }
    }

    /** s3a://bucket/key → cached namespace path, or null if unmapped. */
    Path toCvPath(Path path) {
        URI u = path.toUri();
        String bucket = u.getHost();
        if (bucket == null) {
            return null;
        }
        String prefix = getConf() == null ? null
                : getConf().get("fs.cv.s3a.prefix." + bucket);
        if (prefix == null) {
            prefix = "/" + bucket;
        }
        return new Path(prefix + u.getPath());
    }

    FSDataInputStream openCached(Path path, int bufferSize) {
        if (cache == null) {
            return null;
        }
        try {
            Path cv = toCvPath(path);
            if (cv == null || !cache.exists(cv)) {
                return null;           // not cached → real S3
            }
            return cache.open(cv, bufferSize);
        } catch (IOException e) {
            return null;               // cache trouble must never fail s3a
        }
    }

    @Override
    public FSDataInputStream open(Path path, int bufferSize)
            throws IOException {
        FSDataInputStream cached = openCached(path, bufferSize);
        return cached != null ? cached : super.open(path, bufferSize);
    }

    @Override
    public void close() throws IOException {
        super.close();
        if (cache != null) {
            cache.close();
            cache = null;
        }
    }
}
