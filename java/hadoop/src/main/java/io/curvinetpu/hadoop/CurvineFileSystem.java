package io.curvinetpu.hadoop;

import java.io.IOException;
import java.io.OutputStream;
import java.net.URI;
import java.util.List;

import org.apache.hadoop.conf.Configuration;
import org.apache.hadoop.fs.FSDataInputStream;
import org.apache.hadoop.fs.FSDataOutputStream;
import org.apache.hadoop.fs.FSInputStream;
import org.apache.hadoop.fs.FileStatus;
import org.apache.hadoop.fs.FileSystem;
import org.apache.hadoop.fs.Path;
import org.apache.hadoop.fs.permission.FsPermission;
import org.apache.hadoop.util.Progressable;

import io.curvinetpu.CurvineFileStatus;
import io.curvinetpu.CurvineInputStream;
import io.curvinetpu.CurvineTpuFileSystem;

/**
 * Hadoop-compatible FileSystem over the curvine-tpu native SDK — the
 * ecosystem entry point for Spark/Flink/Hive (parity:
 * curvine-libsdk/java .../CurvineFileSystem.java, which extends
 * org.apache.hadoop.fs.FileSystem for exactly this purpose).
 *
 * <p>Registration (core-site.xml):
 * <pre>
 *   fs.cv.impl = io.curvinetpu.hadoop.CurvineFileSystem
 * </pre>
 * URIs look like {@code cv://master-host:8995/path}; the authority
 * names the master (conf keys {@code fs.cv.master.host/port} override).
 *
 * <p>Compiled against java/hadoop-stubs/ in CI (no Hadoop tree in the
 * image) and against real hadoop-common wherever it exists — the stub
 * signatures mirror Hadoop's public API.
 */
public class CurvineFileSystem extends FileSystem {

    public static final String SCHEME = "cv";

    private URI uri;
    private CurvineTpuFileSystem fs;
    private Path workingDir = new Path("/");

    @Override
    public String getScheme() {
        return SCHEME;
    }

    @Override
    public void initialize(URI name, Configuration conf) throws IOException {
        super.initialize(name, conf);
        String host = conf.get("fs.cv.master.host",
                name.getHost() == null ? "127.0.0.1" : name.getHost());
        int port = conf.getInt("fs.cv.master.port",
                name.getPort() > 0 ? name.getPort() : 8995);
        String user = conf.get("fs.cv.user", "");
        this.uri = URI.create(SCHEME + "://" + host + ":" + port);
        this.fs = CurvineTpuFileSystem.connect(host, port, user);
    }

    @Override
    public URI getUri() {
        return uri;
    }

    @Override
    public void setWorkingDirectory(Path newDir) {
        workingDir = newDir;
    }

    @Override
    public Path getWorkingDirectory() {
        return workingDir;
    }

    /** cv://host:port/a/b (or relative) → namespace path /a/b. */
    String toCvPath(Path path) {
        String p = path.toUri().getPath();
        if (p == null || p.isEmpty()) {
            return "/";
        }
        if (!p.startsWith("/")) {
            String base = workingDir.toUri().getPath();
            p = (base.endsWith("/") ? base : base + "/") + p;
        }
        return p;
    }

    private CurvineTpuFileSystem fs() throws IOException {
        if (fs == null) {
            throw new IOException("filesystem not initialized");
        }
        return fs;
    }

    @Override
    public FSDataInputStream open(Path path, int bufferSize)
            throws IOException {
        CurvineInputStream in = fs().open(toCvPath(path));
        return new FSDataInputStream(new CurvineFsInputStream(in));
    }

    @Override
    public FSDataOutputStream create(Path path, FsPermission permission,
            boolean overwrite, int bufferSize, short replication,
            long blockSize, Progressable progress) throws IOException {
        OutputStream out = fs().create(toCvPath(path), overwrite);
        return new FSDataOutputStream(out, null);
    }

    @Override
    public FSDataOutputStream append(Path path, int bufferSize,
            Progressable progress) throws IOException {
        throw new IOException(
                "append is not supported by the cv Hadoop adapter yet; "
                + "write-once or use the WebHDFS gateway");
    }

    @Override
    public boolean rename(Path src, Path dst) throws IOException {
        try {
            fs().rename(toCvPath(src), toCvPath(dst));
            return true;
        } catch (IOException e) {
            return false;          // Hadoop contract: false, not throw
        }
    }

    @Override
    public boolean delete(Path path, boolean recursive) throws IOException {
        try {
            fs().delete(toCvPath(path), recursive);
            return true;
        } catch (IOException e) {
            return false;
        }
    }

    @Override
    public boolean mkdirs(Path path, FsPermission permission)
            throws IOException {
        fs().mkdir(toCvPath(path));
        return true;
    }

    @Override
    public FileStatus getFileStatus(Path path) throws IOException {
        return toHadoop(fs().getFileStatus(toCvPath(path)), path);
    }

    @Override
    public FileStatus[] listStatus(Path path) throws IOException {
        List<CurvineFileStatus> sts = fs().listStatus(toCvPath(path));
        FileStatus[] out = new FileStatus[sts.size()];
        for (int i = 0; i < sts.size(); i++) {
            CurvineFileStatus st = sts.get(i);
            out[i] = toHadoop(st, new Path(path, st.name));
        }
        return out;
    }

    FileStatus toHadoop(CurvineFileStatus st, Path path) {
        return new FileStatus(st.len, st.isDir, st.replicas, st.blockSize,
                st.mtime, st.atime, new FsPermission((short) st.mode),
                st.owner, st.group, path);
    }

    @Override
    public void close() throws IOException {
        super.close();
        if (fs != null) {
            fs.close();
            fs = null;
        }
    }

    /** Hadoop FSInputStream (seek + positioned read) over the SDK's
     *  seekable stream. */
    static final class CurvineFsInputStream extends FSInputStream {
        private final CurvineInputStream in;

        CurvineFsInputStream(CurvineInputStream in) {
            this.in = in;
        }

        @Override
        public int read() throws IOException {
            return in.read();
        }

        @Override
        public int read(byte[] b, int off, int len) throws IOException {
            return in.read(b, off, len);
        }

        @Override
        public void seek(long pos) throws IOException {
            in.seek(pos);
        }

        @Override
        public long getPos() throws IOException {
            return in.getPos();
        }

        @Override
        public boolean seekToNewSource(long targetPos) throws IOException {
            return false;      // replica choice lives in the native SDK
        }

        @Override
        public void close() throws IOException {
            in.close();
        }
    }
}
