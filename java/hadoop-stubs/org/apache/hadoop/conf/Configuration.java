package org.apache.hadoop.conf;

import java.util.HashMap;
import java.util.Map;

public class Configuration {
    private final Map<String, String> props = new HashMap<>();

    public String get(String name) { return props.get(name); }

    public String get(String name, String defaultValue) {
        return props.getOrDefault(name, defaultValue);
    }

    public int getInt(String name, int defaultValue) {
        String v = props.get(name);
        return v == null ? defaultValue : Integer.parseInt(v);
    }

    public void set(String name, String value) { props.put(name, value); }
}
