package org.apache.hadoop.fs;

import java.io.IOException;
import java.io.InputStream;

public abstract class FSInputStream extends InputStream
        implements Seekable, PositionedReadable {

    @Override
    public int read(long position, byte[] buffer, int offset, int length)
            throws IOException {
        long oldPos = getPos();
        try {
            seek(position);
            return read(buffer, offset, length);
        } finally {
            seek(oldPos);
        }
    }

    @Override
    public void readFully(long position, byte[] buffer, int offset,
            int length) throws IOException {
        int done = 0;
        while (done < length) {
            int n = read(position + done, buffer, offset + done,
                    length - done);
            if (n < 0) {
                throw new IOException("end of stream");
            }
            done += n;
        }
    }

    @Override
    public void readFully(long position, byte[] buffer) throws IOException {
        readFully(position, buffer, 0, buffer.length);
    }
}
