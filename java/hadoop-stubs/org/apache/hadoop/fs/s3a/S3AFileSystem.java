package org.apache.hadoop.fs.s3a;

import java.io.IOException;
import java.net.URI;

import org.apache.hadoop.fs.FSDataInputStream;
import org.apache.hadoop.fs.FSDataOutputStream;
import org.apache.hadoop.fs.FileStatus;
import org.apache.hadoop.fs.FileSystem;
import org.apache.hadoop.fs.Path;
import org.apache.hadoop.fs.permission.FsPermission;
import org.apache.hadoop.util.Progressable;

/** Compile stub of hadoop-aws's S3AFileSystem (public surface only). */
public class S3AFileSystem extends FileSystem {

    @Override
    public String getScheme() { return "s3a"; }

    @Override
    public URI getUri() { return URI.create("s3a:///"); }

    @Override
    public FSDataInputStream open(Path f, int bufferSize)
            throws IOException {
        throw new IOException("stub");
    }

    @Override
    public FSDataOutputStream create(Path f, FsPermission permission,
            boolean overwrite, int bufferSize, short replication,
            long blockSize, Progressable progress) throws IOException {
        throw new IOException("stub");
    }

    @Override
    public FSDataOutputStream append(Path f, int bufferSize,
            Progressable progress) throws IOException {
        throw new IOException("stub");
    }

    @Override
    public boolean rename(Path src, Path dst) throws IOException {
        throw new IOException("stub");
    }

    @Override
    public boolean delete(Path f, boolean recursive) throws IOException {
        throw new IOException("stub");
    }

    @Override
    public FileStatus[] listStatus(Path f) throws IOException {
        throw new IOException("stub");
    }

    @Override
    public void setWorkingDirectory(Path new_dir) {}

    @Override
    public Path getWorkingDirectory() { return new Path("/"); }

    @Override
    public boolean mkdirs(Path f, FsPermission permission)
            throws IOException {
        throw new IOException("stub");
    }

    @Override
    public FileStatus getFileStatus(Path f) throws IOException {
        throw new IOException("stub");
    }
}
