package org.apache.hadoop.fs;

import java.io.DataOutputStream;
import java.io.IOException;
import java.io.OutputStream;

public class FSDataOutputStream extends DataOutputStream {

    public FSDataOutputStream(OutputStream out, Object stats)
            throws IOException {
        super(out);
    }

    public long getPos() { return written; }
}
