package org.apache.hadoop.fs;

import java.io.IOException;
import java.net.URI;

import org.apache.hadoop.conf.Configuration;
import org.apache.hadoop.fs.permission.FsPermission;
import org.apache.hadoop.util.Progressable;

public abstract class FileSystem {
    private Configuration conf;

    public void initialize(URI name, Configuration conf) throws IOException {
        this.conf = conf;
    }

    public Configuration getConf() { return conf; }

    public String getScheme() {
        throw new UnsupportedOperationException("no scheme");
    }

    public abstract URI getUri();

    public abstract FSDataInputStream open(Path f, int bufferSize)
            throws IOException;

    public abstract FSDataOutputStream create(Path f,
            FsPermission permission, boolean overwrite, int bufferSize,
            short replication, long blockSize, Progressable progress)
            throws IOException;

    public abstract FSDataOutputStream append(Path f, int bufferSize,
            Progressable progress) throws IOException;

    public abstract boolean rename(Path src, Path dst) throws IOException;

    public abstract boolean delete(Path f, boolean recursive)
            throws IOException;

    public abstract FileStatus[] listStatus(Path f) throws IOException;

    public abstract void setWorkingDirectory(Path new_dir);

    public abstract Path getWorkingDirectory();

    public abstract boolean mkdirs(Path f, FsPermission permission)
            throws IOException;

    public abstract FileStatus getFileStatus(Path f) throws IOException;

    public boolean exists(Path f) throws IOException {
        try {
            getFileStatus(f);
            return true;
        } catch (IOException e) {
            return false;
        }
    }

    public boolean mkdirs(Path f) throws IOException {
        return mkdirs(f, FsPermission.getDefault());
    }

    public FsStatus getStatus(Path p) throws IOException {
        return new FsStatus(Long.MAX_VALUE, 0, Long.MAX_VALUE);
    }

    public void close() throws IOException {}
}
