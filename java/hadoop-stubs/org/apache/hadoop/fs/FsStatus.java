package org.apache.hadoop.fs;

public class FsStatus {
    private final long capacity;
    private final long used;
    private final long remaining;

    public FsStatus(long capacity, long used, long remaining) {
        this.capacity = capacity;
        this.used = used;
        this.remaining = remaining;
    }

    public long getCapacity() { return capacity; }
    public long getUsed() { return used; }
    public long getRemaining() { return remaining; }
}
