package org.apache.hadoop.fs;

import java.net.URI;

public class Path {
    private final URI uri;

    public Path(String pathString) { this.uri = URI.create(pathString); }

    public Path(URI aUri) { this.uri = aUri; }

    public Path(Path parent, String child) {
        String base = parent.uri.toString();
        this.uri = URI.create(
            base.endsWith("/") ? base + child : base + "/" + child);
    }

    public URI toUri() { return uri; }

    public String getName() {
        String p = uri.getPath();
        int i = p.lastIndexOf('/');
        return i < 0 ? p : p.substring(i + 1);
    }

    @Override
    public String toString() { return uri.toString(); }
}
