package org.apache.hadoop.fs;

import java.io.IOException;

public interface PositionedReadable {
    int read(long position, byte[] buffer, int offset, int length)
            throws IOException;
    void readFully(long position, byte[] buffer, int offset, int length)
            throws IOException;
    void readFully(long position, byte[] buffer) throws IOException;
}
