package org.apache.hadoop.fs;

import java.io.IOException;

public interface Seekable {
    void seek(long pos) throws IOException;
    long getPos() throws IOException;
    boolean seekToNewSource(long targetPos) throws IOException;
}
