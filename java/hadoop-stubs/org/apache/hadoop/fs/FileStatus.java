package org.apache.hadoop.fs;

import org.apache.hadoop.fs.permission.FsPermission;

public class FileStatus {
    private final long length;
    private final boolean isdir;
    private final int replication;
    private final long blocksize;
    private final long mtime;
    private final long atime;
    private final FsPermission permission;
    private final String owner;
    private final String group;
    private final Path path;

    public FileStatus(long length, boolean isdir, int replication,
            long blocksize, long mtime, long atime, FsPermission permission,
            String owner, String group, Path path) {
        this.length = length;
        this.isdir = isdir;
        this.replication = replication;
        this.blocksize = blocksize;
        this.mtime = mtime;
        this.atime = atime;
        this.permission = permission;
        this.owner = owner;
        this.group = group;
        this.path = path;
    }

    public long getLen() { return length; }
    public boolean isDirectory() { return isdir; }
    public boolean isFile() { return !isdir; }
    public int getReplication() { return replication; }
    public long getBlockSize() { return blocksize; }
    public long getModificationTime() { return mtime; }
    public long getAccessTime() { return atime; }
    public FsPermission getPermission() { return permission; }
    public String getOwner() { return owner; }
    public String getGroup() { return group; }
    public Path getPath() { return path; }
}
