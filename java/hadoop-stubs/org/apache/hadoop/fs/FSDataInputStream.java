package org.apache.hadoop.fs;

import java.io.DataInputStream;
import java.io.IOException;
import java.io.InputStream;

public class FSDataInputStream extends DataInputStream
        implements Seekable, PositionedReadable {

    public FSDataInputStream(InputStream in) { super(in); }

    public InputStream getWrappedStream() { return in; }

    @Override
    public void seek(long pos) throws IOException {
        ((Seekable) in).seek(pos);
    }

    @Override
    public long getPos() throws IOException {
        return ((Seekable) in).getPos();
    }

    @Override
    public boolean seekToNewSource(long targetPos) throws IOException {
        return ((Seekable) in).seekToNewSource(targetPos);
    }

    @Override
    public int read(long position, byte[] buffer, int offset, int length)
            throws IOException {
        return ((PositionedReadable) in).read(position, buffer, offset,
                length);
    }

    @Override
    public void readFully(long position, byte[] buffer, int offset,
            int length) throws IOException {
        ((PositionedReadable) in).readFully(position, buffer, offset,
                length);
    }

    @Override
    public void readFully(long position, byte[] buffer) throws IOException {
        ((PositionedReadable) in).readFully(position, buffer);
    }
}
