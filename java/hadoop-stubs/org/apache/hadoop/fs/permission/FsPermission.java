package org.apache.hadoop.fs.permission;

public class FsPermission {
    private final short mode;

    public FsPermission(short mode) { this.mode = mode; }

    public short toShort() { return mode; }

    public static FsPermission getDefault() {
        return new FsPermission((short) 0755);
    }
}
