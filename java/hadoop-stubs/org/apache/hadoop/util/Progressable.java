package org.apache.hadoop.util;

public interface Progressable {
    void progress();
}
