"""SDK (sync API + datasets), fault injection, vector tables.

Mirrors reference: curvine-libsdk/tests/, curvine-fault/tests/,
curvine-lancedb/tests/."""

import asyncio
import threading

import numpy as np
import pytest

import jax

from curvine_tpu.common import errors as cerr
from curvine_tpu.fault import FaultInjector, FaultSpec
from curvine_tpu.rpc import RpcCode
from curvine_tpu.testing import MiniCluster

CPU = jax.devices("cpu")[0]


@pytest.fixture
def cluster_loop():
    loop = asyncio.new_event_loop()
    mc = MiniCluster(workers=1)
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    asyncio.run_coroutine_threadsafe(mc.start(), loop).result(30)
    yield mc
    asyncio.run_coroutine_threadsafe(mc.stop(), loop).result(30)
    loop.call_soon_threadsafe(loop.stop)
    t.join(5)


def test_sdk_filesystem(cluster_loop):
    from curvine_tpu.sdk import CurvineFileSystem
    mc = cluster_loop
    with CurvineFileSystem(master=mc.master.addr) as fs:
        fs.mkdir("/sdk/dir")
        assert fs.exists("/sdk/dir")
        with fs.open("/sdk/f.bin", "wb") as f:
            f.write(b"alpha")
            f.write(b"beta")
        st = fs.get_status("/sdk/f.bin")
        assert st.len == 9
        with fs.open("/sdk/f.bin", "rb") as f:
            assert f.read(5) == b"alpha"
            assert f.read() == b"beta"
            f.seek(0)
            assert f.read() == b"alphabeta"
            assert f.pread(5, 4) == b"beta"
        with fs.open("/sdk/f.bin", "ab") as f:
            f.write(b"!")
        assert fs.read_all("/sdk/f.bin") == b"alphabeta!"
        names = [s.name for s in fs.list_status("/sdk")]
        assert sorted(names) == ["dir", "f.bin"]
        fs.rename("/sdk/f.bin", "/sdk/g.bin")
        fs.delete("/sdk", recursive=True)
        assert not fs.exists("/sdk")
        info = fs.master_info()
        assert len(info.live_workers) == 1


def test_sdk_torch_dataset(cluster_loop):
    from curvine_tpu.sdk import CurvineFileSystem
    from curvine_tpu.sdk.datasets import CurvineIterableDataset, jax_batches
    import torch
    mc = cluster_loop
    with CurvineFileSystem(master=mc.master.addr) as fs:
        fs.mkdir("/ds")
        samples = np.arange(64 * 16, dtype=np.uint8).reshape(64, 16)
        fs.write_all("/ds/shard-0.bin", samples[:32].tobytes())
        fs.write_all("/ds/shard-1.bin", samples[32:].tobytes())

    ds = CurvineIterableDataset(mc.master.addr, "/ds", sample_bytes=16)
    loader = torch.utils.data.DataLoader(ds, batch_size=8, num_workers=0)
    batches = list(loader)
    assert len(batches) == 8
    got = torch.cat(batches).numpy()
    assert np.array_equal(got, samples)

    with CurvineFileSystem(master=mc.master.addr) as fs:
        fs.write_all("/ds2/t.bin",
                     np.arange(1024, dtype=np.int32).tobytes())
        out = list(jax_batches(fs, "/ds2", batch=2, seq_len=64))
        assert all(b.shape == (2, 64) for b in out)
        assert len(out) == 8


async def test_fault_injection_delay_error_drop():
    async with MiniCluster(workers=1) as mc:
        inj = FaultInjector().install(mc.master.rpc)
        c = mc.client()
        # faults are injected into the PYTHON rpc server; stat/exists
        # must not ride the native fast port or the lease cache around
        # the injector here
        c.meta._fast_enabled = False
        c.meta.cache = None
        # error injection on FILE_STATUS
        fid = inj.add(FaultSpec(kind="error", codes=[int(RpcCode.FILE_STATUS)],
                                error_code=int(cerr.ErrorCode.IO)))
        await c.write_all("/ok", b"x")
        with pytest.raises(cerr.CurvineError):
            await c.meta.file_status("/ok")
        inj.remove(fid)
        assert (await c.meta.file_status("/ok")).len == 1

        # delay injection is observable
        import time
        inj.add(FaultSpec(kind="delay", codes=[int(RpcCode.EXISTS)],
                          delay_ms=300))
        t0 = time.perf_counter()
        await c.meta.exists("/ok")
        assert time.perf_counter() - t0 >= 0.28
        inj.clear()

        # drop: client request times out, then retries succeed after clear
        fid = inj.add(FaultSpec(kind="drop", codes=[int(RpcCode.EXISTS)],
                                max_hits=1))
        c.conf.client.rpc_timeout_ms = 500
        c.meta.pool.timeout_ms = 500
        for conns in c.meta.pool._conns.values():
            for conn in conns:
                conn.timeout = 0.5
        assert await c.meta.exists("/ok")   # one drop, retry succeeds
        assert inj.log and inj.log[-1]["kind"] == "drop"


async def test_fault_http_control():
    import aiohttp
    from curvine_tpu.fault.http import FaultControlServer
    async with MiniCluster(workers=1) as mc:
        inj = FaultInjector().install(mc.master.rpc)
        ctl = FaultControlServer(inj)
        await ctl.start()
        try:
            base = f"http://127.0.0.1:{ctl.port}"
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/faults", json={
                        "kind": "delay", "delay_ms": 10}) as r:
                    assert r.status == 201
                    fid = (await r.json())["fault_id"]
                async with s.get(f"{base}/faults") as r:
                    faults = await r.json()
                    assert len(faults) == 1
                async with s.delete(f"{base}/faults/{fid}") as r:
                    assert r.status == 200
                async with s.get(f"{base}/faults") as r:
                    assert await r.json() == []
        finally:
            await ctl.stop()


async def test_vector_table():
    from curvine_tpu.vector import VectorTable
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        dim = 32
        t = await VectorTable.create(c, "/vec/emb", dim,
                                     columns={"doc_id": "i64"})
        rng = np.random.default_rng(0)
        v1 = rng.normal(size=(100, dim)).astype(np.float32)
        v2 = rng.normal(size=(50, dim)).astype(np.float32)
        await t.append(v1, {"doc_id": np.arange(100, dtype=np.int64)})
        await t.append(v2, {"doc_id": np.arange(100, 150, dtype=np.int64)})
        assert await t.count() == 150

        # reopen and knn: query = row 120 exactly → top hit is itself
        t2 = await VectorTable.open(c, "/vec/emb")
        assert t2.row_groups == 2
        ids, scores = await t2.knn(v2[20], k=5, device=CPU)
        assert ids[0, 0] == 120
        assert scores[0, 0] == pytest.approx(1.0, abs=1e-5)

        # l2 metric, batch queries
        ids, _ = await t2.knn(np.stack([v1[3], v2[7]]), k=3, metric="l2",
                              device=CPU)
        assert ids[0, 0] == 3 and ids[1, 0] == 107

        # take() returns the right columns
        vecs, cols = await t2.take(np.array([120, 3]))
        assert cols["doc_id"].tolist() == [120, 3]
        assert np.allclose(vecs[0], v2[20])


async def test_vector_table_delete_update_compact():
    """Lance-model mutations: delete vector (tombstones), update =
    delete+insert, compaction rewrites row groups dropping dead rows.
    Parity: curvine-lancedb table mutation surface."""
    from curvine_tpu.common import errors as err
    from curvine_tpu.vector import VectorTable
    rng = np.random.default_rng(0)
    dim = 32
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        t = await VectorTable.create(c, "/vec/mut", dim,
                                     columns={"label": "i32"})
        v = rng.normal(size=(100, dim)).astype(np.float32)
        labels = np.arange(100, dtype=np.int32)
        await t.append(v[:60], {"label": labels[:60]})
        await t.append(v[60:], {"label": labels[60:]})
        assert await t.count() == 100

        # delete: knn never returns tombstoned rows
        ids, _ = await t.knn(v[42], k=1, device=CPU)
        assert int(ids[0, 0]) == 42
        assert await t.delete([42, 7, 99]) == 3
        assert await t.count() == 97
        ids, _ = await t.knn(v[42], k=3, device=CPU)
        assert 42 not in ids[0]
        with pytest.raises(err.InvalidArgument):
            await t.take([7])

        # update: new version wins the scan
        new_vec = rng.normal(size=(1, dim)).astype(np.float32)
        await t.update([13], new_vec, {"label": np.array([1313],
                                                         dtype=np.int32)})
        ids, _ = await t.knn(new_vec[0], k=1, device=CPU)
        new_id = int(ids[0, 0])
        assert new_id >= 100                      # appended row
        _, cols = await t.take([new_id])
        assert int(cols["label"][0]) == 1313
        assert await t.count() == 97              # -1 old, +1 new

        # compact: dense renumber, deletes gone, groups rewritten
        # streaming (one live group per non-empty source group)
        kept = await t.compact()
        assert kept == 97
        assert t.row_groups == 3 and t.version == 1
        assert await t.count() == 97
        ids, _ = await t.knn(new_vec[0], k=1, device=CPU)
        _, cols = await t.take([int(ids[0, 0])])
        assert int(cols["label"][0]) == 1313
        # persisted: reopen sees the compacted table
        t2 = await VectorTable.open(c, "/vec/mut")
        assert t2.row_groups == 3 and t2.version == 1
        assert await t2.count() == 97
        # no superseded row-group files linger
        sts = await c.meta.list_status("/vec/mut")
        assert sorted(s.name for s in sts if s.name.startswith("rg-")) == \
            ["rg-00000.vec", "rg-00001.vec", "rg-00002.vec"]
