"""Client metadata lease cache consistency (docs/read-plane.md).

Unit coverage of client/meta_cache.py (LRU bounds, negative entries,
lease adoption, epoch flush, subtree invalidation) plus the cross-client
contracts the cache must honor against a live master:

  * read-your-writes — the WRITING client is never stale, immediately;
  * negative-entry vs create — a cached ENOENT must be revoked by the
    master's META_INVALIDATE push when another client creates the path;
  * rename/delete invalidation — cached positives must drop within the
    staleness bound (one push RTT normally, the lease TTL worst-case).

Cross-client assertions POLL with a deadline past the lease TTL: the
push lands asynchronously, so instant visibility is not the contract —
bounded visibility is, and staleness past the bound is a bug."""

import asyncio
import time

from curvine_tpu.client.meta_cache import MISS, MetaCache
from curvine_tpu.testing import MiniCluster

TOKEN = {"ttl_ms": 3_000, "epoch": 17}


async def _until(pred, timeout: float = 4.0) -> bool:
    """Poll an async predicate until true or the staleness bound (lease
    TTL 3s + push slack) passes."""
    deadline = time.monotonic() + timeout
    while not await pred():
        if time.monotonic() >= deadline:
            return False
        await asyncio.sleep(0.02)
    return True


# ---------------------------------------------------------------------------
# unit: cache mechanics


def test_cache_caches_nothing_before_lease():
    """Until the master grants a TTL, every put is a no-op: the client
    must not invent its own staleness bound."""
    cache = MetaCache()
    cache.put("stat", "/a", "st")
    assert cache.get("stat", "/a") is MISS
    cache.note_lease(TOKEN, "/")
    cache.put("stat", "/a", "st")
    assert cache.get("stat", "/a") == "st"


def test_cache_negative_entries_and_counters():
    cache = MetaCache()
    cache.note_lease(TOKEN, "/d")
    cache.put("stat", "/d/missing", None)      # cached ENOENT
    assert cache.get("stat", "/d/missing") is None
    assert cache.get("stat", "/d/other") is MISS
    assert cache.counters["meta_cache.hits"] == 1
    assert cache.counters["meta_cache.misses"] == 1


def test_cache_lru_bound_evicts_oldest():
    cache = MetaCache(entries=2)
    cache.note_lease(TOKEN, "/")
    cache.put("stat", "/a", 1)
    cache.put("stat", "/b", 2)
    cache.put("stat", "/c", 3)
    assert cache.get("stat", "/a") is MISS
    assert cache.get("stat", "/b") == 2
    assert cache.get("stat", "/c") == 3
    assert cache.counters["meta_cache.evictions"] == 1


def test_cache_invalidate_drops_entry_and_parent_listing():
    cache = MetaCache()
    cache.note_lease(TOKEN, "/d")
    cache.put("stat", "/d/f", "st")
    cache.put("list", "/d", ["f"])
    cache.invalidate(["/d/f"])
    assert cache.get("stat", "/d/f") is MISS
    assert cache.get("list", "/d") is MISS      # child changed → listing


def test_cache_invalidate_subtree_sweeps_descendants():
    """Rename/recursive delete push only the TOP path; everything the
    client cached underneath must go with it."""
    cache = MetaCache()
    cache.note_lease(TOKEN, "/d")
    cache.put("stat", "/d/sub/deep", "st")
    cache.put("list", "/d/sub", ["deep"])
    cache.put("stat", "/dx", "kept")            # sibling, no slash match
    cache.invalidate(["/d"], subtree=True)
    assert cache.get("stat", "/d/sub/deep") is MISS
    assert cache.get("list", "/d/sub") is MISS
    assert cache.get("stat", "/dx") == "kept"


def test_cache_epoch_change_flushes_everything():
    """A new lease epoch means the master restarted and its holder table
    is gone: every entry AND every warm directory lease must drop."""
    cache = MetaCache()
    cache.note_lease(TOKEN, "/d")
    cache.put("stat", "/d/f", "st")
    assert cache.lease_ok("/d")
    cache.note_epoch(TOKEN["epoch"] + 1)
    assert cache.get("stat", "/d/f") is MISS
    assert not cache.lease_ok("/d")


# ---------------------------------------------------------------------------
# integration: consistency contracts against a live master


async def test_read_your_writes_is_immediate():
    """The writing client is NEVER stale — write-through invalidation is
    synchronous with the mutation ack, so there is no poll here."""
    async with MiniCluster(workers=0) as mc:
        c = mc.client()
        await c.meta.mkdir("/ryw")
        await c.meta.create_file("/ryw/f")
        assert await c.meta.exists("/ryw/f")
        hits0 = c.meta.cache.counters.get("meta_cache.hits", 0)
        assert await c.meta.exists("/ryw/f")       # served locally
        assert c.meta.cache.counters["meta_cache.hits"] > hits0

        await c.meta.delete("/ryw/f")
        assert not await c.meta.exists("/ryw/f")   # immediately gone
        await c.meta.create_file("/ryw/f")
        assert await c.meta.exists("/ryw/f")       # immediately back

        # a cached PARENT LISTING must reflect a child mutation too
        names = [s.name for s in await c.meta.list_status("/ryw")]
        assert names == ["f"]
        await c.meta.create_file("/ryw/g")
        names = [s.name for s in await c.meta.list_status("/ryw")]
        assert sorted(names) == ["f", "g"]


async def test_negative_entry_revoked_by_remote_create():
    """Client A caches an ENOENT under lease; client B creates the path.
    The master pushes META_INVALIDATE to A (negatives are leased too —
    the grant happens before the handler answers), so A must see the
    file within the staleness bound."""
    async with MiniCluster(workers=0) as mc:
        a, b = mc.client(), mc.client()
        await b.meta.mkdir("/nc")
        # adopt a lease TTL first: ENOENT replies carry no token, so a
        # fresh client can't cache negatives until one positive leased
        # read has told it how long answers may be believed
        assert await a.meta.exists("/nc")
        assert not await a.meta.exists("/nc/f")
        misses0 = a.meta.cache.counters.get("meta_cache.misses", 0)
        assert not await a.meta.exists("/nc/f")    # cached negative
        assert a.meta.cache.counters.get(
            "meta_cache.misses", 0) == misses0

        await b.meta.create_file("/nc/f")
        assert await _until(lambda: a.meta.exists("/nc/f")), \
            "cached negative outlived the staleness bound after create"


async def test_rename_invalidates_both_ends_within_ttl():
    async with MiniCluster(workers=0) as mc:
        a, b = mc.client(), mc.client()
        await b.meta.mkdir("/rn")
        await b.meta.create_file("/rn/src")
        assert await a.meta.exists("/rn/src")      # cached positive
        assert not await a.meta.exists("/rn/dst")  # cached negative

        await b.meta.rename("/rn/src", "/rn/dst")

        async def moved():
            return (not await a.meta.exists("/rn/src")
                    and await a.meta.exists("/rn/dst"))
        assert await _until(moved), \
            "rename: stale entries outlived the staleness bound"


async def test_delete_invalidates_remote_cache_within_ttl():
    async with MiniCluster(workers=0) as mc:
        a, b = mc.client(), mc.client()
        await b.meta.mkdir("/del")
        await b.meta.create_file("/del/f")
        st = await a.meta.file_status("/del/f")
        assert st is not None and st.name == "f"

        await b.meta.delete("/del/f")

        async def gone():
            return not await a.meta.exists("/del/f")
        assert await _until(gone), \
            "delete: stale positive outlived the staleness bound"


async def test_cross_client_listing_tracks_remote_create():
    async with MiniCluster(workers=0) as mc:
        a, b = mc.client(), mc.client()
        await b.meta.mkdir("/ls")
        await b.meta.create_file("/ls/one")
        assert [s.name for s in await a.meta.list_status("/ls")] == ["one"]

        await b.meta.create_file("/ls/two")

        async def sees_two():
            names = [s.name for s in await a.meta.list_status("/ls")]
            return sorted(names) == ["one", "two"]
        assert await _until(sees_two), \
            "cached listing outlived the staleness bound after create"
