"""Parity extras: locks, list_options, assign_worker, metrics report,
small-file batch writes."""

import asyncio
import os

import pytest

from curvine_tpu.common import errors as err
from curvine_tpu.testing import MiniCluster


async def test_locks():
    async with MiniCluster(workers=1) as mc:
        c1 = mc.client()
        c2 = mc.client()
        await c1.write_all("/locked.bin", b"x")
        lock = await c1.meta.set_lock("/locked.bin")
        assert lock["owner"] == c1.meta.client_id
        # second client blocked
        with pytest.raises(err.LeaseConflict):
            await c2.meta.set_lock("/locked.bin")
        # shared locks coexist
        await c1.meta.set_lock("/shared.bin", kind="shared")
        await c2.meta.set_lock("/shared.bin", kind="shared")
        assert len(await c1.meta.get_lock("/shared.bin")) == 2
        # release frees it
        assert await c1.meta.release_lock("/locked.bin")
        got = await c2.meta.set_lock("/locked.bin")
        assert got["owner"] == c2.meta.client_id
        assert len(await c1.meta.list_locks()) == 3
        # ttl expiry
        await c1.meta.set_lock("/ttl.bin", ttl_ms=50)
        await asyncio.sleep(0.1)
        assert await c1.meta.get_lock("/ttl.bin") == []


async def test_list_options():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.meta.mkdir("/lo/sub")
        for i in range(10):
            await c.write_all(f"/lo/f{i:02d}.bin", b"x")
            await c.write_all(f"/lo/g{i:02d}.dat", b"y")
        sts, total = await c.meta.list_options("/lo", pattern="f*.bin")
        assert total == 10 and all(s.name.startswith("f") for s in sts)
        sts, total = await c.meta.list_options("/lo", dirs_only=True)
        assert [s.name for s in sts] == ["sub"]
        sts, total = await c.meta.list_options("/lo", files_only=True,
                                               offset=5, limit=5)
        assert total == 20 and len(sts) == 5


async def test_assign_worker_and_metrics():
    async with MiniCluster(workers=2) as mc:
        c = mc.client()
        w = await c.meta.assign_worker()
        assert w.rpc_port in {wk.rpc.port for wk in mc.workers}
        w2 = await c.meta.assign_worker(exclude=[w.worker_id])
        assert w2.worker_id != w.worker_id
        await c.meta.report_metrics({"reads": 5, "bytes": 1024})
        assert mc.master.metrics.counters["client.reads"] == 5


async def test_write_files_batch():
    async with MiniCluster(workers=2) as mc:
        c = mc.client()
        files = {f"/batch/f{i}.bin": os.urandom(1000 + i) for i in range(20)}
        await c.write_files_batch(files)
        for p, data in files.items():
            st = await c.meta.file_status(p)
            assert st.is_complete and st.len == len(data)
            assert await (await c.open(p)).read_all() == data


async def test_directory_quotas():
    from curvine_tpu.common.types import SetAttrOpts
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.meta.mkdir("/q")
        await c.meta.set_attr("/q", SetAttrOpts(
            add_x_attr={"quota.files": b"3"}))
        for i in range(3):
            await c.write_all(f"/q/f{i}", b"x")
        with pytest.raises(err.QuotaExceeded):
            await c.meta.create_file("/q/f3")
        # deleting frees quota
        await c.meta.delete("/q/f0")
        await c.write_all("/q/f3", b"x")

        # byte quota blocks block allocation (checked at block_size
        # granularity, like the reference)
        MB = 1024 * 1024
        await c.meta.mkdir("/qb")
        await c.meta.set_attr("/qb", SetAttrOpts(
            add_x_attr={"quota.bytes": str(5 * MB).encode()}))
        await c.write_all("/qb/first", b"y" * (4 * MB + 100))
        with pytest.raises(err.QuotaExceeded):
            await c.write_all("/qb/second", b"z" * MB)
        q = mc.master.quota.get_quota("/qb")
        assert q["bytes"] == 5 * MB and q["used_files"] == 2
        assert q["used_bytes"] == 4 * MB + 100


async def test_cache_pressure_eviction():
    import os as _os
    from curvine_tpu.ufs import memory as memufs
    memufs.reset()
    async with MiniCluster(workers=1, tier_capacity=8 * 1024 * 1024) as mc:
        c = mc.client()
        await c.meta.mount("/p", "mem://pb")
        # 6 x 1MB UFS-backed cached files → 75% used
        for i in range(6):
            await c.write_through(f"/p/f{i}.bin", _os.urandom(1024 * 1024))
        # touch the newest ones so f0/f1 are coldest
        for i in range(2, 6):
            await (await c.open(f"/p/f{i}.bin")).read(10)
        await mc.workers[0].heartbeat_once()   # fresh capacity numbers
        qm = mc.master.quota
        qm.high_water, qm.low_water = 0.6, 0.4
        freed = qm.evict_once()
        assert freed >= 2
        # freed files keep metadata and remain readable via UFS
        st = await c.meta.file_status("/p/f0.bin")
        assert st.len == 1024 * 1024
        data = await c.unified_read("/p/f0.bin")
        assert len(data) == 1024 * 1024


async def test_content_summary_rpc():
    """Master-side recursive content summary (one RPC; reference
    aggregates client-side over ListStatus — content_summary.rs)."""
    from curvine_tpu.testing import MiniCluster
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.write_all("/cs/a/f1", b"x" * 100)
        await c.write_all("/cs/a/b/f2", b"y" * 50)
        await c.write_all("/cs/f3", b"z" * 25)
        cs = await c.meta.content_summary("/cs")
        assert cs["length"] == 175
        assert cs["file_count"] == 3
        assert cs["directory_count"] == 3          # /cs, /cs/a, /cs/a/b
        one = await c.meta.content_summary("/cs/f3")
        assert one == {"length": 25, "file_count": 1,
                       "directory_count": 0}
        import pytest as _p
        from curvine_tpu.common import errors as _err
        with _p.raises(_err.FileNotFound):
            await c.meta.content_summary("/nope")


async def test_content_summary_under_mounts_uses_unified_walk():
    """The master refuses to sum subtrees intersecting mounts (totals
    live partly in the UFS); the client aggregates the unified listing
    — uncached UFS objects count."""
    from curvine_tpu.testing import MiniCluster
    from curvine_tpu.ufs import create_ufs
    from curvine_tpu.ufs import memory as memufs
    from curvine_tpu.common import errors as _err
    import pytest as _p
    memufs.reset()
    ufs = create_ufs("mem://cs")
    await ufs.write_all("mem://cs/x/u1.bin", b"u" * 40)
    await ufs.write_all("mem://cs/u2.bin", b"v" * 60)
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.meta.mount("/m", "mem://cs")
        await c.load_from_ufs("/m/u2.bin")      # one cached, one not
        # master-side RPC refuses (typed), client aggregates unified view
        with _p.raises(_err.Unsupported):
            await c.meta.content_summary("/m")
        cs = await c.content_summary("/m")
        assert cs["length"] == 100 and cs["file_count"] == 2
        assert cs["directory_count"] == 2       # /m and /m/x
        # an ancestor of the mount is also refused master-side and
        # aggregated by the client instead
        await c.write_all("/plain.bin", b"p" * 7)
        root = await c.content_summary("/")
        assert root["length"] == 107


async def test_content_summary_acl_denies_unreadable_subdir():
    """HDFS semantics: getContentSummary needs r-x on every subdirectory
    — an unreadable subdir fails the whole call instead of leaking its
    aggregate size."""
    from curvine_tpu.testing import MiniCluster
    from curvine_tpu.common import errors as _err
    from curvine_tpu.common.types import SetAttrOpts
    import pytest as _p
    async with MiniCluster(workers=1) as mc:
        c = mc.client()          # root/superuser
        await c.write_all("/top/open/a.bin", b"a" * 10)
        await c.write_all("/top/secret/b.bin", b"b" * 20)
        await c.meta.set_attr("/top/secret", SetAttrOpts(mode=0o700))
        await c.meta.set_attr("/top", SetAttrOpts(mode=0o755))
        # superuser sees everything
        cs = await c.meta.content_summary("/top")
        assert cs["length"] == 30
        # a plain user is denied on the unreadable subdir
        mc.conf.client.user = "alice"
        mc.conf.client.groups = ["users"]
        c2 = mc.client()
        with _p.raises(_err.PermissionDenied):
            await c2.meta.content_summary("/top")
