"""North-star end-to-end: token shards cached → sharded device feed →
transformer training steps on a DP×TP mesh; worker HBM tier pin path."""

import asyncio

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec as P

from curvine_tpu.testing import MiniCluster

CPUS = jax.devices("cpu")


@pytest.fixture(autouse=True)
def _cpu_default():
    with jax.default_device(CPUS[0]):
        yield


async def test_train_from_cache_e2e():
    from curvine_tpu.tpu.loader import TpuTrainFeed, write_token_shards
    from curvine_tpu.tpu.mesh import make_mesh
    from curvine_tpu.tpu.model import (
        ModelConfig, batch_spec, init_params, make_optimizer,
        make_train_step, shard_params,
    )
    from curvine_tpu.tpu.broadcast import save_checkpoint, load_checkpoint

    mesh = make_mesh(devices=CPUS, axis_names=("data", "model"))
    cfg = ModelConfig(vocab=128, d_model=64, n_heads=4, n_layers=2,
                      d_ff=128, max_seq=64, dtype="float32")

    # lost_timeout high: jit compilation blocks this in-process loop for
    # tens of seconds, which would otherwise trip worker-lost detection
    async with MiniCluster(workers=1, lost_timeout_ms=600_000) as mc:
        c = mc.client()
        # a learnable pattern: repeating token sequence
        tokens = np.tile(np.arange(16, dtype=np.int32), 4096 // 16 * 8)
        await write_token_shards(c, "/train/tok", tokens, shard_tokens=4096)

        params = shard_params(init_params(jax.random.PRNGKey(0), cfg), mesh)
        opt = make_optimizer(1e-2)
        opt_state = opt.init(params)
        step = jax.jit(make_train_step(cfg, opt, mesh))

        losses = []
        for epoch in range(4):
            feed = TpuTrainFeed(c, "/train/tok", batch=8, seq_len=64,
                                mesh=mesh)
            async for batch in feed:
                assert batch.sharding.spec == P("data", None)
                params, opt_state, loss = step(params, opt_state, batch)
                losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])

        # checkpoint the trained params into the cache and read them back
        await save_checkpoint(c, "/ckpt/final", jax.device_get(params))
        restored = await load_checkpoint(c, "/ckpt/final")
        a = jax.tree.leaves(params)[0]
        b = jax.tree.leaves(restored)[0]
        assert np.allclose(np.asarray(a), np.asarray(b))


async def test_worker_hbm_pin():
    from curvine_tpu.rpc import RpcCode
    from curvine_tpu.rpc.frame import pack, unpack
    from curvine_tpu.tpu.hbm import HbmTier

    async with MiniCluster(workers=1) as mc:
        worker = mc.workers[0]
        # enable the HBM tier on the fly (CPU device stands in for HBM)
        worker.hbm = HbmTier(64 * 1024 * 1024, device=CPUS[0])
        c = mc.client()
        data = np.random.default_rng(0).integers(
            0, 255, 1024 * 1024, dtype=np.uint8).tobytes()
        await c.write_all("/hbm/blk.bin", data)
        fb = await c.meta.get_block_locations("/hbm/blk.bin")
        bid = fb.block_locs[0].block.id

        conn = await c.pool.get(worker.addr)
        rep = await conn.call(RpcCode.HBM_PIN, data=pack({"block_id": bid}))
        body = rep.header or unpack(rep.data)
        assert body["len"] == len(data)
        assert body["hbm"]["blocks"] == 1
        # device-resident array matches the cached bytes
        arr = worker.hbm.get(bid)
        assert arr is not None
        assert bytes(np.asarray(arr).tobytes()) == data
        # heartbeat now advertises the HBM tier to the master
        await worker.heartbeat_once()
        info = await c.meta.master_info()
        tiers = {s.storage_type for w in info.live_workers
                 for s in w.storages}
        from curvine_tpu.common.types import StorageType
        assert StorageType.HBM in tiers

        await conn.call(RpcCode.HBM_UNPIN, data=pack({"block_id": bid}))
        assert worker.hbm.get(bid) is None
