"""Integration: mini-cluster end-to-end fs + block paths.

Mirrors reference tests: curvine-tests/tests/cluster_test.rs,
curvine-server/tests/master_fs_test.rs, worker_test.rs."""

import asyncio
import os

import pytest

from curvine_tpu.common import errors as err
from curvine_tpu.common.types import SetAttrOpts
from curvine_tpu.testing import MiniCluster

MB = 1024 * 1024


async def test_fs_crud():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.meta.mkdir("/a/b/c")
        assert await c.meta.exists("/a/b/c")
        st = await c.meta.file_status("/a/b")
        assert st.is_dir
        ls = await c.meta.list_status("/a")
        assert [s.name for s in ls] == ["b"]

        await c.meta.rename("/a/b", "/a/z")
        assert await c.meta.exists("/a/z/c")
        assert not await c.meta.exists("/a/b")

        with pytest.raises(err.DirNotEmpty):
            await c.meta.delete("/a")
        await c.meta.delete("/a", recursive=True)
        assert not await c.meta.exists("/a")

        with pytest.raises(err.FileNotFound):
            await c.meta.file_status("/nope")


async def test_write_read_roundtrip():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        data = os.urandom(3 * MB)
        await c.write_all("/f1", data)
        st = await c.meta.file_status("/f1")
        assert st.len == len(data) and st.is_complete
        r = await c.open("/f1")
        assert await r.read_all() == data
        # ranged read
        assert await r.pread(100, 1000) == data[100:1100]
        # sequential chunked
        got = bytearray()
        async for ch in (await c.open("/f1")).chunks(256 * 1024):
            got += ch
        assert bytes(got) == data


async def test_multi_block_file():
    async with MiniCluster(workers=1, block_size=1 * MB) as mc:
        c = mc.client()
        data = os.urandom(3 * MB + 12345)   # spans 4 blocks
        await c.write_all("/big", data)
        fb = await c.meta.get_block_locations("/big")
        assert len(fb.block_locs) == 4
        assert sum(b.block.len for b in fb.block_locs) == len(data)
        r = await c.open("/big")
        assert await r.read_all() == data
        # read across block boundary
        assert await r.pread(MB - 10, 20) == data[MB - 10:MB + 10]


async def test_append():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.write_all("/ap", b"hello ")
        w = await c.append("/ap")
        await w.write(b"world")
        await w.close()
        assert await (await c.open("/ap")).read_all() == b"hello world"


async def test_overwrite_and_delete_file():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.write_all("/x", b"one")
        with pytest.raises(err.FileAlreadyExists):
            await c.meta.create_file("/x")
        await c.write_all("/x", b"two-longer")
        assert await (await c.open("/x")).read_all() == b"two-longer"
        await c.meta.delete("/x")
        assert not await c.meta.exists("/x")


async def test_set_attr_and_symlink():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.write_all("/s", b"data")
        await c.meta.set_attr("/s", SetAttrOpts(owner="bob", mode=0o600,
                                                add_x_attr={"k": b"v"}))
        st = await c.meta.file_status("/s")
        assert st.owner == "bob" and st.mode == 0o600
        assert st.x_attr == {"k": b"v"}

        await c.meta.symlink("/s", "/lnk")
        st = await c.meta.file_status("/lnk")
        assert st.target == "/s"

        await c.meta.link("/s", "/hard")
        st = await c.meta.file_status("/hard")
        assert st.nlink == 2
        # deleting one name keeps the data reachable via the other
        await c.meta.delete("/s")
        assert await c.meta.exists("/hard")


async def test_master_info_and_capacity():
    async with MiniCluster(workers=2) as mc:
        c = mc.client()
        info = await c.meta.master_info()
        assert len(info.live_workers) == 2
        assert info.capacity > 0
        await c.write_all("/cap", os.urandom(1 * MB))
        info = await c.meta.master_info()
        assert info.block_num >= 1


async def test_replicated_write():
    async with MiniCluster(workers=2) as mc:
        c = mc.client()
        data = os.urandom(1 * MB)
        await c.write_all("/rep", data, replicas=2)
        fb = await c.meta.get_block_locations("/rep")
        assert all(len(b.locs) == 2 for b in fb.block_locs)
        assert await (await c.open("/rep")).read_all() == data


async def test_journal_restart_recovery():
    mc = MiniCluster(workers=1)
    async with mc:
        c = mc.client()
        await c.meta.mkdir("/keep/me")
        data = os.urandom(1 * MB)
        await c.write_all("/keep/f", data)
        await c.close()

        await mc.restart_master()
        await mc.await_workers(1)
        c2 = mc.client()
        assert await c2.meta.exists("/keep/me")
        st = await c2.meta.file_status("/keep/f")
        assert st.len == len(data)
        # block locations come back via worker re-report/heartbeat
        await mc.workers[0].block_report_once()
        r = await c2.open("/keep/f")
        assert await r.read_all() == data


async def test_free_releases_cache():
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        await c.write_all("/fr", os.urandom(1 * MB))
        freed = await c.meta.free("/fr")
        assert freed == 1
        st = await c.meta.file_status("/fr")
        assert st.len == 1 * MB       # metadata kept
        fb = await c.meta.get_block_locations("/fr")
        assert fb.block_locs == []    # cache dropped


async def test_add_block_abandon_no_ghost_blocks():
    """A writer retry abandons its previous failed allocation (HDFS
    abandonBlock): the inode must not accumulate zero-length ghost
    blocks (round-5 review finding)."""
    from curvine_tpu.common.types import CommitBlock
    async with MiniCluster(workers=1) as mc:
        c = mc.client()
        w = await c.create("/gb/f.bin")     # open-for-write lease
        fs = mc.master.fs
        wid = fs.workers.live_workers()[0].address.worker_id

        b1 = fs.add_block("/gb/f.bin").block.id
        # retry path: abandon b1, allocate b2
        b2 = fs.add_block("/gb/f.bin", abandon_block=b1).block.id
        node = fs.tree.resolve("/gb/f.bin")
        assert node.blocks == [b2]
        assert fs.blocks.get(b1) is None        # block map pruned too

        # a COMMITTED (len>0) block is never abandonable
        fs.add_block("/gb/f.bin", commit_blocks=[CommitBlock(
            block_id=b2, block_len=7, worker_ids=[wid])],
            abandon_block=b2)
        node = fs.tree.resolve("/gb/f.bin")
        assert b2 in node.blocks and len(node.blocks) == 2
        await w.abort()
